"""Bench-artifact trend tables and the regression gate.

The repo commits one `BENCH_rNN.json` per recorded bench run, but nothing
ever read the series: a regression only surfaced if someone eyeballed two
JSON blobs. This module loads the full `BENCH_r*.json` history, builds
per-section trend tables (one row per tracked metric, one column per
round), and renders noise-aware verdicts:

  * **headline metrics** (the txn/s figures a release is judged by) FAIL
    the gate when the newest artifact regresses more than the threshold
    (default 10%) against the previous artifact **on the same platform**;
  * every other tracked metric is informational: the table shows the
    trend arrow and percentage, but only headline regressions gate.

Platform awareness is the load-bearing design point: the artifacts record
the device they ran on (`"TPU v5 lite0"`, a CPU backend, ...), and a
device-time figure measured on a TPU is NOT comparable to one measured on
CPU. A platform change between consecutive artifacts therefore resets the
comparison baseline — the verdict is `platform-change`, never
`regressed` — and the gate compares each artifact against the newest
OLDER artifact of the same platform instead. Noise awareness: each metric
carries a noise fraction (host-side wall timings on a shared box swing
tens of percent; device scan timings are tight), and the verdict fires
only beyond max(threshold, noise).

    python -m foundationdb_tpu.tools.bench_history            # tables
    python -m foundationdb_tpu.tools.bench_history --json
    tools/cli.py bench-history                                 # same
    make bench-history

Exit status is non-zero on any gated regression (naming the section and
metric), so `make bench-history` is a CI gate the same way `make lint`
is.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: gate threshold: a headline metric this much worse than the previous
#: same-platform artifact fails the run
DEFAULT_THRESHOLD = 0.10


class Metric:
    """One tracked (section, dotted path) with its comparison policy."""

    __slots__ = ("section", "path", "label", "higher_is_better", "headline",
                 "noise_frac")

    def __init__(self, section: str, path: str, label: str, *,
                 higher_is_better: bool = True, headline: bool = False,
                 noise_frac: float = 0.05):
        self.section = section
        self.path = path
        self.label = label
        self.higher_is_better = higher_is_better
        self.headline = headline
        self.noise_frac = noise_frac

    @property
    def key(self) -> str:
        return f"{self.section}.{self.path}" if self.path else self.section


#: the tracked metrics, grouped by artifact section. Headline = the
#: figures the README leads with; everything else is informational.
METRICS: Tuple[Metric, ...] = (
    Metric("", "value", "resolved txn/s/chip", headline=True),
    Metric("", "device_ms_per_batch", "device ms/batch",
           higher_is_better=False),
    Metric("", "host_pack_ms_per_batch", "host pack ms/batch",
           higher_is_better=False, noise_frac=0.25),
    Metric("", "native_cpu_txns_per_sec", "native C++ txn/s",
           noise_frac=0.25),
    Metric("sharded_tpu_weak_scale", "v5e8_extrapolated_txns_per_sec",
           "extrapolated v5e-8 txn/s", headline=True),
    # the r05-era ESTIMATED collective: recorded only by chip-era
    # artifacts (the section is absent on CPU profiles), so platform
    # awareness pins it to its own era — it never compares against the
    # MEASURED figures below, which carry the platform they ran on
    Metric("sharded_tpu_weak_scale", "collective_est_ms",
           "estimated ICI collective ms (chip era)",
           higher_is_better=False, noise_frac=0.0),
    Metric("sharded_measured", "collective_ms.8",
           "measured psum ms @8 shards", higher_is_better=False,
           noise_frac=0.5),
    Metric("sharded_measured", "scaling.8.txns_per_s",
           "mesh txn/s @8 shards (total-compute on cpu)", noise_frac=0.25),
    Metric("sharded_measured", "scaling.8.exchange_ms",
           "mesh exchange interval ms @8 shards", higher_is_better=False,
           noise_frac=0.5),
    Metric("sharded_measured", "overlap_ab.speedup",
           "mesh overlapped/serialized speedup", noise_frac=0.25),
    Metric("sharded_measured", "overlap_ab.blocking_syncs",
           "mesh ring blocking syncs", higher_is_better=False,
           noise_frac=0.0),
    Metric("sharded_measured", "scaling.8.parity.mismatches",
           "mesh parity mismatches @8 shards", higher_is_better=False,
           noise_frac=0.0),
    Metric("latency_curve", "production_point.txns_per_sec",
           "serial production txn/s"),
    Metric("latency_under_load", "production_point.sustained_txns_per_sec",
           "pipelined sustained txn/s", headline=True),
    Metric("latency_under_load", "production_point.p99_ms",
           "pipelined p99 ms", higher_is_better=False, noise_frac=0.15),
    Metric("bucket_ladder", "steady_state_compiles",
           "steady-state compiles", higher_is_better=False, noise_frac=0.0),
    Metric("history_floor", "points.-1.bsearch_speedup",
           "bsearch speedup @max occupancy"),
    Metric("history_floor", "apply.points.-1.tiered_speedup",
           "tiered apply speedup @max occupancy"),
    Metric("history_floor", "apply.steady_state_compiles.tiered",
           "tiered apply steady-state compiles", higher_is_better=False,
           noise_frac=0.0),
    Metric("loop_floor", "loop_speedup", "loop host-time speedup",
           noise_frac=0.25),
    Metric("loop_floor", "loop_stats.blocking_syncs", "loop blocking syncs",
           higher_is_better=False, noise_frac=0.0),
    Metric("served_under_chaos", "users_served_per_chip.no_nemesis",
           "users served/chip"),
    Metric("served_while_resharding",
           "users_served_per_chip.while_resharding",
           "users served/chip while resharding", noise_frac=0.25),
    Metric("served_while_resharding", "resharding.blackout_ms_max",
           "worst reshard blackout ms", higher_is_better=False,
           noise_frac=0.5),
    Metric("conflict_heat", "overhead.overhead_pct", "heat overhead %",
           higher_is_better=False, noise_frac=0.5),
    Metric("compile_memory", "peak_hbm_bytes", "peak compiled-program HBM",
           higher_is_better=False, noise_frac=0.15),
    Metric("compile_memory", "steady_state_compiles",
           "post-warmup compiles", higher_is_better=False, noise_frac=0.0),
    Metric("recovery", "rewarm.rewarm_speedup",
           "progcache rewarm speedup", noise_frac=0.5),
    Metric("recovery", "rewarm.warm.compiles",
           "progcache-warm rewarm compiles", higher_is_better=False,
           noise_frac=0.0),
    Metric("recovery", "replay.speedup",
           "snapshot vs full-journal replay", noise_frac=0.5),
    Metric("recovery", "crash.blackout_ms",
           "crash recovery blackout ms", higher_is_better=False,
           noise_frac=0.5),
    # scenario atlas (real/scenarios.py, recorded from BENCH_r11): every
    # scenario's SLO verdict is a zero-noise HEADLINE — a 1 -> 0 drop in
    # ANY recipe fails the gate outright, and a scenario that stops
    # being recorded trips the headline went-missing check. The measured
    # abort fractions ride along informationally at chaos-grade noise.
    *(Metric("scenario_atlas", f"scenarios.{name}.slo_pass",
             f"{name} scenario SLO pass", headline=True, noise_frac=0.0)
      for name in ("flash_sale", "payment_ledger", "secondary_index",
                   "task_queue", "timeseries_ingest", "session_cache")),
    # sustained tps is bounded above by the recipe's fixed offered rate
    # (it can't inflate), so a beyond-noise drop is a real serving
    # regression; the abort/throttle fractions are judged by slo_pass
    # instead of raw trend rows — at ~0.005 absolute they are too
    # ratio-noisy for a relative gate
    *(Metric("scenario_atlas", f"scenarios.{name}.sustained_tps",
             f"{name} scenario sustained txn/s", noise_frac=0.5)
      for name in ("flash_sale", "payment_ledger", "session_cache")),
)


def load_parsed(path: Path) -> dict:
    d = json.loads(path.read_text())
    return d.get("parsed", d)


def load_series(root: Path) -> List[Tuple[int, str, dict]]:
    """Every committed BENCH_r*.json, oldest first: (round, name, parsed)."""
    out = []
    for p in root.glob("BENCH_r*.json"):
        m = re.search(r"r(\d+)", p.stem)
        if not m:
            continue
        out.append((int(m.group(1)), p.name, load_parsed(p)))
    out.sort(key=lambda t: t[0])
    return out


def platform_of(parsed: dict) -> str:
    """Comparison class of an artifact: the device family it measured
    on. Timings from different families never compare."""
    dev = str(parsed.get("device", "")).lower()
    if "tpu" in dev:
        return "tpu"
    if "cpu" in dev or "tfrt" in dev:
        return "cpu"
    if "gpu" in dev or "cuda" in dev:
        return "gpu"
    return dev.split(" ")[0] if dev else "unknown"


def extract(parsed: dict, metric: Metric) -> Optional[float]:
    return extract_path(parsed, metric.section, metric.path)


def extract_path(parsed: dict, section: str, path: str) -> Optional[float]:
    """A numeric value by (section, dotted path), or None when absent.
    Path components index dicts by key and lists by int (negative ok)."""
    node: Any = parsed.get(section) if section else parsed
    if node is None:
        return None
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
        if node is None:
            return None
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def pct_change(prev: float, cur: float) -> Optional[float]:
    """Signed fractional change; None on a zero baseline (a percentage
    of zero is meaningless — and an inf here would leak into rendered
    tables and strict-JSON output)."""
    if prev == 0:
        return None
    return (cur - prev) / abs(prev)


def _verdict(metric: Metric, prev: float, cur: float,
             threshold: float) -> Tuple[str, Optional[float]]:
    """(verdict, signed pct change) for a same-platform pair. Verdicts:
    improved | regressed | ok."""
    change = pct_change(prev, cur)
    if change is None:
        # zero baseline: any movement is all signal (the zero-pinned
        # metrics — compile counts, blocking syncs — have 0 noise)
        if cur == prev:
            return "ok", 0.0
        worse = (cur < prev) if metric.higher_is_better else (cur > prev)
        return ("regressed" if worse else "improved"), None
    worse = -change if metric.higher_is_better else change
    tol = max(threshold, metric.noise_frac)
    if worse > tol:
        return "regressed", change
    if -worse > tol:
        return "improved", change
    return "ok", change


def build_trends(series: Sequence[Tuple[int, str, dict]],
                 threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Trend tables + gate verdicts over the artifact series. For each
    metric, the newest artifact recording it is compared against the
    newest OLDER artifact of the same platform recording it."""
    rounds = [{"round": r, "name": name, "platform": platform_of(p)}
              for r, name, p in series]
    metrics_out = []
    failures = []
    for metric in METRICS:
        values = [extract(p, metric) for _, _, p in series]
        recorded = [i for i, v in enumerate(values) if v is not None]
        row: Dict[str, Any] = {
            "section": metric.section or "headline",
            "metric": metric.path,
            "label": metric.label,
            "higher_is_better": metric.higher_is_better,
            "headline": metric.headline,
            "values": values,
        }
        if recorded:
            cur_i = recorded[-1]
            cur_plat = rounds[cur_i]["platform"]
            prev_i = next((i for i in reversed(recorded[:-1])
                           if rounds[i]["platform"] == cur_plat), None)
            prev = values[prev_i] if prev_i is not None else None
            if prev is None:
                # first recording on this platform: a baseline reset
                # (never a regression verdict across device families)
                verdict = ("platform-change" if len(recorded) > 1 else "new")
                change = None
            else:
                verdict, change = _verdict(metric, prev, values[cur_i],
                                           threshold)
            row.update({
                "latest_round": rounds[cur_i]["round"],
                "latest": values[cur_i],
                "baseline_round": (rounds[prev_i]["round"]
                                   if prev_i is not None else None),
                "baseline": prev,
                "platform": cur_plat,
                "verdict": verdict,
                "change_frac": (round(change, 4)
                                if change is not None else None),
            })
            if verdict == "regressed" and metric.headline:
                delta = (f"{abs(change) * 100:.1f}%"
                         if change is not None else "from a zero baseline")
                failures.append(
                    f"{row['section']}.{metric.path or 'value'} "
                    f"({metric.label}) regressed {delta} "
                    f"(r{rounds[prev_i]['round']:02d} {prev:g} -> "
                    f"r{rounds[cur_i]['round']:02d} {values[cur_i]:g}, "
                    f"platform {cur_plat})")
        else:
            row["verdict"] = "never-recorded"
        # a headline metric that the newest artifact STOPPED recording is
        # itself a gate failure: bench.py's sections are exception-guarded
        # (a broken run just omits the section), so without this check a
        # vanished headline figure would re-verdict the old pair and pass
        last = len(series) - 1
        same_plat = [j for j in recorded
                     if rounds[j]["platform"] == rounds[last]["platform"]]
        if metric.headline and values[last] is None and same_plat:
            row["verdict"] = "went-missing"
            failures.append(
                f"{row['section']}.{metric.path or 'value'} "
                f"({metric.label}) went missing: "
                f"r{rounds[last]['round']:02d} "
                f"[{rounds[last]['platform']}] records no value but "
                f"r{rounds[same_plat[-1]]['round']:02d} did")
        metrics_out.append(row)
    return {"rounds": rounds, "metrics": metrics_out,
            "threshold": threshold, "failures": failures,
            "ok": not failures}


def render_tables(trends: dict, out) -> None:
    rounds = trends["rounds"]
    heads = "".join(f"{'r%02d' % r['round']:>14}" for r in rounds)
    print(f"bench history: {len(rounds)} artifacts "
          f"({', '.join(r['name'] + ' [' + r['platform'] + ']' for r in rounds)})",
          file=out)
    print(f"{'metric':<38}{heads}  verdict", file=out)
    cur_section = None
    for row in trends["metrics"]:
        if row["verdict"] == "never-recorded":
            continue
        if row["section"] != cur_section:
            cur_section = row["section"]
            print(f"-- {cur_section}", file=out)
        cells = "".join(
            f"{('%g' % v if v is not None else '·'):>14}"
            for v in row["values"])
        verdict = row["verdict"]
        if row.get("change_frac") is not None:
            verdict += f" ({row['change_frac'] * 100:+.1f}%)"
        flag = " [HEADLINE]" if row["headline"] else ""
        print(f"  {row['label']:<36}{cells}  {verdict}{flag}", file=out)
    if trends["failures"]:
        print("GATE FAILURES:", file=out)
        for f in trends["failures"]:
            print(f"  {f}", file=out)
    else:
        print(f"gate: OK (threshold {trends['threshold'] * 100:.0f}% on "
              "headline metrics, same-platform baselines)", file=out)


def find_repo_root() -> Path:
    p = Path(__file__).resolve()
    for parent in p.parents:
        if (parent / "bench.py").exists() and list(parent.glob("BENCH_r*.json")):
            return parent
    raise SystemExit("repo root with BENCH_r*.json not found")


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", type=Path, default=None,
                    help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    root = args.dir or find_repo_root()
    series = load_series(root)
    if not series:
        print(f"no BENCH_r*.json under {root}", file=out)
        return 2
    trends = build_trends(series, threshold=args.threshold)
    if args.json:
        print(json.dumps(trends), file=out)
    else:
        render_tables(trends, out)
    return 0 if trends["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
