"""`make telemetry-smoke`: CPU-backend observability-path check, seconds
not minutes, so the span/metric/flight-recorder wiring breaks loudly in CI.

Four assertions (docs/observability.md):

  * spans — a mini latency-under-load run with span collection on emits
    commit-path spans whose named phase segments sum to the client-observed
    p50/p99 within 5% (the bench `latency_attribution` acceptance);
  * metrics — a dynamic sim cluster's unified telemetry (resolver
    counters, engine health transitions — core/telemetry.py) drains
    through the MetricLogger into the `\\xff/metrics/` keyspace and reads
    back;
  * flight recorder — the supervised resolver engines accumulated
    dispatch records during the traffic;
  * zero-cost off — with collection disabled, instrumented span sites
    allocate nothing (the allocation counter stays flat) and cost under
    SPAN_OFF_NS_BUDGET per call.

Prints one JSON line; any failed check exits non-zero.
"""
from __future__ import annotations

import json
import sys
import time

#: per-call budget for a DISABLED span() site (generous: the real cost is
#: one attribute check, ~100ns even on a slow CI box)
SPAN_OFF_NS_BUDGET = 5_000
ATTRIBUTION_TOL = 0.05


def check_spans(failures) -> dict:
    from foundationdb_tpu.pipeline.latency_harness import run_latency_under_load

    dev_by_bucket = {64: 0.45, 128: 0.8}
    r = run_latency_under_load(
        depth=2, batch_txns=128, device_ms=dev_by_bucket[128],
        pack_ms_per_txn=0.0006,
        offered_txns_per_sec=0.9 * 128 / (dev_by_bucket[128] / 1e3),
        n_txns=1_500, device_ms_by_bucket=dev_by_bucket,
        collect_spans=True,
    )
    att = r.attribution
    if not att:
        failures.append("no spans attributed under the harness")
        return {}
    for pct in ("p50", "p99"):
        row = att[pct]
        ratio = row.get("sum_over_client")
        if ratio is None or abs(ratio - 1.0) > ATTRIBUTION_TOL:
            failures.append(
                f"{pct} segment sum {row.get('sum_ms')}ms vs client "
                f"{row.get('client_ms')}ms (ratio {ratio})")
        # residual bounds (the non-tautological half: a regressed span site
        # dumps its time into a residual and trips these)
        for residual in ("resolve_overhead", "reply_net"):
            v = row["segments_ms"].get(residual, 0.0)
            if v < -1e-6 or v > 0.15 * row["client_ms"]:
                failures.append(
                    f"{pct} residual {residual}={v}ms out of bounds for "
                    f"client {row['client_ms']}ms")
    for name in ("queue_wait", "host_pack", "device_dispatch", "force",
                 "pipeline_wait"):
        if name not in att["p99"]["segments_ms"]:
            failures.append(f"named segment {name} missing from attribution")
    return {"n_attributed": att["n_attributed"],
            "p50": att["p50"], "p99": att["p99"]}


def check_metrics_and_flight(failures) -> dict:
    from foundationdb_tpu.client.metric_logger import read_metric
    from foundationdb_tpu.core import telemetry
    from foundationdb_tpu.core.trace import g_spans
    from foundationdb_tpu.fault import registered_engines
    from foundationdb_tpu.client.metric_logger import run_metric_logger
    from foundationdb_tpu.server.cluster import (
        DynamicClusterConfig, build_dynamic_cluster)
    from foundationdb_tpu.sim.loop import delay, set_scheduler, spawn
    from foundationdb_tpu.core import buggify

    out = {}
    c = build_dynamic_cluster(seed=71, cfg=DynamicClusterConfig())
    buggify.disable()   # exact drain timing, no injected logger lag
    g_spans.enabled = False
    sim = c.sim
    db = c.new_client()
    hub = telemetry.hub()

    async def scenario():
        spawn(run_metric_logger(db, hub.tdmetrics, "telemetry",
                                interval=1.0, sync=hub.sync),
              name="telemetryLogger")
        for i in range(12):
            async def w(tr, i=i):
                tr.set(b"obs%03d" % i, b"v")
            await db.run(w)
            await delay(0.5)
        await delay(8.0)    # past the resolver stats interval + a drain
        # the resolver's counters fed hub.tdmetrics via its
        # CounterCollection hookup; engine health states were recorded at
        # construction. Pick one persisted series of each kind.
        health_names = [n for n in hub.tdmetrics.metrics
                        if n.startswith("resolver.") and n.endswith(".state")]
        if not health_names:
            return {"error": "no health-state series registered"}
        series = await read_metric(db, "telemetry", health_names[0])
        counter_names = [n for n in hub.tdmetrics.metrics
                         if n.startswith("Resolver.")
                         and n.endswith(".batches_resolved")]
        counter_series = []
        if counter_names:
            counter_series = await read_metric(db, "telemetry",
                                               counter_names[0])
        return {"health_series": series, "health_name": health_names[0],
                "counter_series": counter_series,
                "counter_name": counter_names[0] if counter_names else None}

    try:
        res = sim.run_until(sim.sched.spawn(scenario(), name="s"), until=300.0)
    finally:
        set_scheduler(None)
    if not isinstance(res, dict) or res.get("error"):
        failures.append(f"telemetry scenario failed: {res}")
        return out
    if not res["health_series"]:
        failures.append(
            f"health series {res['health_name']} never drained to "
            "\\xff/metrics/")
    if res["counter_name"] and not res["counter_series"]:
        failures.append(
            f"resolver counter series {res['counter_name']} never drained")
    out["persisted_health_entries"] = len(res["health_series"])
    out["persisted_counter_entries"] = len(res["counter_series"])

    engines = registered_engines()
    recorded = sum(len(e.flight) for e in engines)
    if not engines:
        failures.append("no supervised engines registered in the sim")
    elif recorded == 0:
        failures.append("flight recorder never populated under traffic")
    out["engines"] = len(engines)
    out["flight_records"] = recorded
    return out


def check_disabled_overhead(failures) -> dict:
    from foundationdb_tpu.core.trace import (
        NULL_SPAN, g_spans, span, span_allocations, span_event)

    g_spans.enabled = False
    allocs_before = span_allocations[0]
    spans_before = len(g_spans.spans)
    n = 100_000
    t0 = time.perf_counter()
    for i in range(n):
        span("resolver.device_dispatch", i).finish()
        span_event("resolver.retry", i, 0.0, 1.0)
    per_call_ns = (time.perf_counter() - t0) / (2 * n) * 1e9
    if span("x") is not NULL_SPAN:
        failures.append("disabled span() did not return the shared null span")
    if span_allocations[0] != allocs_before:
        failures.append(
            f"disabled tracing allocated "
            f"{span_allocations[0] - allocs_before} spans")
    if len(g_spans.spans) != spans_before:
        failures.append(
            f"disabled tracing recorded "
            f"{len(g_spans.spans) - spans_before} spans")
    if per_call_ns > SPAN_OFF_NS_BUDGET:
        failures.append(
            f"disabled span call costs {per_call_ns:.0f}ns "
            f"> {SPAN_OFF_NS_BUDGET}ns budget")
    return {"disabled_span_ns_per_call": round(per_call_ns, 1)}


def main() -> int:
    failures: list = []
    spans = check_spans(failures)
    metrics = check_metrics_and_flight(failures)
    overhead = check_disabled_overhead(failures)
    out = {"metric": "telemetry_smoke", "ok": not failures,
           "failures": failures, "spans": spans, "metrics": metrics,
           "overhead": overhead}
    print(json.dumps(out))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
