"""Measured mesh resolution numbers (bench.py's `sharded_measured`).

BENCH_r05's weak-scale projection priced the cross-shard exchange with an
ESTIMATED 0.15 ms ICI collective. This module replaces the estimate with
measurements on a real N-device mesh (8 forced XLA host devices when no
accelerator is attached — genuine XLA devices running genuine collectives,
time-sharing host cores):

  * `collective_ms`: a dedicated AOT-compiled psum-chain program — eight
    dependent [T] i32 psums across the mesh, timed end to end, reported
    per psum. This is the collective-only cost the r05 model wanted, at
    each mesh width.
  * `scaling`: per width N in {1, 2, 4, 8}, the mesh engine run in
    SERIALIZED mode over the identical point-txn stream: txn/s, the
    measured scan interval (dispatch -> scan outputs ready) and the
    measured exchange interval (scan ready -> verdict planes ready, i.e.
    psum + lockstep fixpoint + apply) from the engine's own result-ring
    stamps, plus oracle-parity counts for every batch resolved.
  * `overlap_ab`: the 8-wide A/B — the same pipelined driver (pack batch
    i+1 while batch i's exchange drains, force one batch behind, exactly
    the ResolverPipeline's dispatch discipline) against an overlapped
    engine and a serialized one (`resolver_mesh_overlap=serial`
    semantics). Overlapped must win: the host's pack+decode hides under
    device compute, and blocking_syncs stays 0.

On the CPU host platform one core time-shares all N "devices", so
absolute times are total-compute proxies (the platform field says which
era a number belongs to — bench_history never compares cpu measurements
against chip-era estimates).
"""
import json
import os
import sys
import time
from collections import deque

WIDTHS = (1, 2, 4, 8)
PSUM_CHAIN = 8


def _force_host_devices(n=8):
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def main():
    _force_host_devices(8)   # before jax initializes its backend

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.expanduser("~"), ".cache", "fdb_tpu_jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from foundationdb_tpu.core.keyshard import KeyShardMap
    from foundationdb_tpu.core.types import CommitTransaction, KeyRange
    from foundationdb_tpu.ops.conflict_kernel import KernelConfig
    from foundationdb_tpu.ops.oracle import OracleConflictEngine
    from foundationdb_tpu.parallel.mesh_engine import MeshShardedConflictEngine
    from foundationdb_tpu.parallel.sharding import _shard_map

    T = 512               # txns per batch, identical stream at every width
    POOL = 2048
    N_BATCHES = 4
    REPS = 3
    CFG = KernelConfig(
        key_words=4, capacity=4096,
        max_point_reads=1152, max_point_writes=1152,
        max_reads=8, max_writes=8, max_txns=T,
    )

    rng = np.random.default_rng(11)

    def synth(n_txns):
        txns = []
        for _ in range(n_txns):
            t = CommitTransaction()
            for _ in range(2):
                k = b"%06d" % rng.integers(0, POOL)
                t.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            for _ in range(2):
                k = b"%06d" % rng.integers(0, POOL)
                t.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            txns.append(t)
        return txns

    streams = [synth(T) for _ in range(N_BATCHES)]

    def reset_snapshots():
        for txns in streams:
            for tr in txns:
                tr.read_snapshot = 990

    def make_engine(n, overlap):
        return MeshShardedConflictEngine(
            CFG, KeyShardMap.uniform(n),
            jax.make_mesh((n,), ("shard",), devices=jax.devices()[:n]),
            ladder=(), scan_sizes=(), overlap=overlap)

    def run_pipelined(engine, reps, oracle=None):
        """Pack/dispatch with force one batch behind — the overlap
        window the mesh ring exploits. Returns (txns_per_s, parity)."""
        now = 1000
        mism = checked = 0

        def settle(force, want):
            nonlocal mism, checked
            got = force()
            if want is not None:
                checked += len(got)
                mism += sum(int(g) != int(w) for g, w in zip(got, want))

        # warm: compile + fill the interval tables
        for txns in streams:
            got = engine.resolve(txns, now, max(0, now - 200_000))
            if oracle is not None:
                want = oracle.resolve(txns, now, max(0, now - 200_000))
                checked += len(got)
                mism += sum(int(g) != int(w) for g, w in zip(got, want))
            now += T
        t0 = time.perf_counter()
        total = 0
        pending = deque()
        for _ in range(reps):
            for txns in streams:
                old = max(0, now - 200_000)
                plan = engine.columnar_pack(txns, now, old)
                assert plan is not None, "point stream must pack columnar"
                want = (oracle.resolve(txns, now, old)
                        if oracle is not None else None)
                force = engine.columnar_dispatch(plan)
                while len(pending) > 1:
                    settle(*pending.popleft())
                pending.append((force, want))
                now += T
                total += len(txns)
        while pending:
            settle(*pending.popleft())
        dt = time.perf_counter() - t0
        return total / dt, {"checked": checked, "mismatches": mism}

    def timed_psum_chain(n):
        """The collective-only measurement: PSUM_CHAIN dependent [T] i32
        psums over an n-wide mesh, AOT-compiled, timed per psum."""
        mesh = jax.make_mesh((n,), ("shard",), devices=jax.devices()[:n])
        sh = NamedSharding(mesh, P("shard"))

        def chain(x):
            x = x[0]
            for i in range(PSUM_CHAIN):
                # the +i data dependency keeps XLA from folding the chain
                x = lax.psum(x, "shard") + np.int32(i)
            return x[None]

        mapped = _shard_map(chain, mesh=mesh, in_specs=(P("shard"),),
                            out_specs=P("shard"))
        x = jax.device_put(np.ones((n, T), np.int32), sh)
        prog = jax.jit(mapped).lower(
            jax.ShapeDtypeStruct((n, T), np.int32, sharding=sh)).compile()
        jax.block_until_ready(prog(x))   # warm
        t0 = time.perf_counter()
        reps = 20
        for _ in range(reps):
            jax.block_until_ready(prog(x))
        return (time.perf_counter() - t0) * 1e3 / (reps * PSUM_CHAIN)

    res = {
        "devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
        "batch_txns": T,
        "psum_chain": PSUM_CHAIN,
        "collective_ms": {},
        "scaling": {},
    }

    for n in WIDTHS:
        if n > 1:
            res["collective_ms"][str(n)] = round(timed_psum_chain(n), 4)
        reset_snapshots()
        eng = make_engine(n, overlap=False)   # tight phase stamps
        txns_s, parity = run_pipelined(eng, REPS, oracle=OracleConflictEngine())
        ms = eng.mesh_stats
        timed = max(1, int(ms["timed_exchanges"]))
        res["scaling"][str(n)] = {
            "txns_per_s": round(txns_s, 1),
            "scan_ms": round(ms["scan_ms_total"] / timed, 4),
            "exchange_ms": round(ms["exchange_ms_total"] / timed, 4),
            "timed_batches": timed,
            "blocking_syncs": int(eng.loop_stats["blocking_syncs"]),
            "parity": parity,
        }
        assert parity["mismatches"] == 0, f"parity broke at N={n}: {parity}"

    # the 8-wide A/B: identical pipelined driver, overlap on vs off
    reset_snapshots()
    over = make_engine(8, overlap=True)
    over_txns_s, _ = run_pipelined(over, REPS)
    serial_txns_s = res["scaling"]["8"]["txns_per_s"]
    res["overlap_ab"] = {
        "overlapped_txns_per_s": round(over_txns_s, 1),
        "serialized_txns_per_s": serial_txns_s,
        "speedup": round(over_txns_s / serial_txns_s, 3),
        "blocking_syncs": int(over.loop_stats["blocking_syncs"]),
        "drained_nonblocking": int(over.loop_stats["drained_nonblocking"]),
    }
    print(json.dumps(res))


if __name__ == "__main__":
    sys.exit(main())
