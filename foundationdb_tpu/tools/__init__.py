"""Operator tooling (the fdbcli/fdbbackup analog surface)."""
