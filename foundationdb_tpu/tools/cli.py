"""Interactive CLI over a cluster — the fdbcli analog.

Re-design of fdbcli/fdbcli.actor.cpp round-2 scope: status + the
get/set/clear/getrange transaction commands, driven against a simulated
cluster (each command spawns its coroutine and drives the deterministic
sim until it resolves — the CLI is the only wall-clock actor, exactly like
an operator at a terminal).

Run interactively:  python -m foundationdb_tpu.tools.cli [--seed N]
Scripted:           echo "set k v\nget k\nstatus" | python -m ...
"""
from __future__ import annotations

import json
import shlex
import sys
from typing import List, Optional

from ..core import error
from ..server.cluster import DynamicClusterConfig, build_dynamic_cluster

HELP = """\
commands:
  status [json]        cluster status (summary, or the full document)
  get KEY              read a key
  set KEY VALUE        write a key
  clear KEY            clear a key
  clearrange BEGIN END clear a key range
  getrange BEGIN END [LIMIT]   read a range
  shards               shard map + replica teams (from \\xff/keyServers)
  move BEGIN WORKER [WORKER...]  move the shard at BEGIN to new workers
  exclude ADDR [ADDR...]         drain all shard replicas off workers
  include ADDR [ADDR...]         re-admit excluded workers
  configure [single|double|triple] [proxies=N] [resolvers=N] [logs=N]
                       change the database configuration (applies at the
                       next recovery; replication drives DD team growth)
  telemetry [json]     resolver engine telemetry: health, perf counters,
                       abort-cause split, budget-batcher EWMAs
                       (docs/observability.md)
  telemetry read PROCESS METRIC   read a persisted \\xff/metrics/ series
  perf [json]          performance observatory: compile & memory ledger
                       (warmup/steady compile counts + durations, flops,
                       peak compiled-program HBM), the state-memory
                       gauge, and sampled measured device timing — one
                       memory/compile view per resolver
                       (docs/observability.md "Performance observatory")
  bench-history [ARGS...]  BENCH_r*.json trend tables + the noise-aware
                       regression gate (cluster-less; args pass through,
                       e.g. `bench-history --json`)
  heat [json|FILE.json]  keyspace heat & history occupancy: top hot
                       ranges, occupancy headroom, suggested equal-load
                       shard split points — live from the cluster's
                       status doc, or from a campaign report JSON
                       (real/nemesis.py --json) / bench artifact with a
                       conflict_heat section (docs/observability.md)
  sched [json|FILE.json]  conflict-aware admission (pipeline/scheduler.py):
                       predictor hot ranges, serialization lanes,
                       pre-abort / defer / reorder counters and the
                       probe-measured mispredict fraction — live from
                       the cluster status doc, or from a campaign report
                       JSON / bench artifact with a conflict_scheduling
                       section (docs/scheduling.md)
  alerts [json|FILE.json]  cluster-watchdog alert states (core/watchdog.py):
                       rule catalog, pending/firing/resolved lifecycle and
                       burn-rate values — live from the cluster status doc,
                       or cluster-less from a campaign report JSON
                       (docs/observability.md "Watchdog, burn rates &
                       incidents"; per-alert runbook in docs/operations.md)
  incidents [json|FILE.json]  machine-correlated incident timelines:
                       firing alerts grouped and matched against injected
                       fault windows, resolver health transitions and the
                       trace root cause ("p99 burn firing · overlaps
                       partition window · dominant=server_resolve")
  atlas [FILE.json]    scenario-atlas scorecards (docs/scenarios.md):
                       per-scenario SLO verdicts (p99/abort/throttle/
                       parity/incidents) + heat/abort signatures — from
                       a campaign report JSON with scenario stamps, a
                       bench artifact's scenario_atlas section, or this
                       process's scenario.* gauges
  chaos-status [FILE]  nemesis event counts from this process's telemetry
                       hub, or from a campaign report JSON written by
                       `python -m foundationdb_tpu.real.nemesis --json`
  explain VERSION SRC  commit forensics (docs/observability.md "Black-box
                       journal & forensics"): reconstruct one batch
                       version's full causal arc — admission, routing
                       epoch, queue/dispatch spans, verdicts with the
                       first-witness write and ITS committing batch,
                       failover arcs, overlapping incidents and fault
                       windows — from a black-box journal directory or a
                       campaign report JSON that recorded one
  explain --slo REPORT.json   explain the worst retained-ack SLO breach
                       end-to-end (the report's slo_root_cause version)
  blackbox SRC         black-box journal summary (events by kind, version
                       range, epoch flips) for a journal dir / report
  blackbox replay --window v1..v2 SRC
                       differential replay: re-resolve the persisted
                       window through the clean serial oracle and diff
                       verdicts bit-for-bit (works across epoch flips)
  trace FILE.json      validate + summarize an exported Chrome trace
                       (a campaign's --trace-dir output)
  trace fetch ADDR [ADDR...] [OUT.json]
                       fetch live span rings over RPC (trace.spans token),
                       reconstruct per-commit waterfalls, optionally write
                       Chrome trace JSON (docs/observability.md)
  lint [ARGS...]       run fdbtpu-lint, the static invariant checker:
                       determinism, host-sync discipline, donation safety,
                       recompile hazards, knob/doc drift, span registry,
                       blackbox event registry
                       (docs/static_analysis.md; args pass through, e.g.
                       `lint --json` or `lint --rules knob-drift`)
  help                 this text
  exit                 quit
Keys/values are text; prefix with 0x for hex bytes."""


def _arg_bytes(tok: str) -> bytes:
    if tok.startswith("0x"):
        return bytes.fromhex(tok[2:])
    return tok.encode()


def _fmt(b: Optional[bytes]) -> str:
    if b is None:
        return "<not found>"
    try:
        s = b.decode()
        if s.isascii() and s.isprintable():
            return f"'{s}'"
    except UnicodeDecodeError:
        pass
    return "0x" + b.hex()


class Cli:
    def __init__(self, cluster, out=sys.stdout):
        self.cluster = cluster
        self.sim = cluster.sim
        self.db = cluster.new_client()
        self.out = out

    def _drive(self, coro, timeout: float = 60.0):
        return self.sim.run_until(self.sim.sched.spawn(coro, name="cli"),
                                  until=self.sim.sched.time + timeout)

    def _print(self, s: str) -> None:
        print(s, file=self.out)

    # -- commands -------------------------------------------------------------
    def do_status(self, args: List[str]) -> None:
        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return
        if args and args[0] == "json":
            self._print(json.dumps(doc, indent=2, sort_keys=True))
            return
        c = doc["cluster"]
        self._print(f"  recovery state     - {c['recovery_state']}")
        self._print(f"  generation         - {c['generation']}")
        self._print(f"  cluster controller - {c['controller']}")
        self._print(f"  master             - {c.get('master')}")
        self._print(f"  proxies            - {', '.join(c.get('proxies', [])) or '-'}")
        if "version" in c and c["version"] is not None:
            self._print(f"  version            - {c['version']}")
        if doc.get("qos"):
            self._print(f"  tps limit          - {doc['qos'].get('transactions_per_second_limit')}")
            stale = " (STALE — no storage poll answered)" \
                if doc['qos'].get('storage_lag_stale') else ""
            self._print(f"  worst storage lag  - {doc['qos'].get('worst_storage_lag_versions')} versions{stale}")
            health = doc["qos"].get("resolver_health") or {}
            if doc["qos"].get("resolver_degraded"):
                det = ", ".join(f"{a}: {s}" for a, s in sorted(health.items()))
                self._print(f"  resolver engines   - DEGRADED ({det})")
            elif health:
                self._print(f"  resolver engines   - healthy ({len(health)})")
        for s in doc.get("storage", []):
            state = "unreachable" if s.get("unreachable") else f"v={s.get('durable_version')}"
            self._print(f"  storage tag {s['tag']}      - {s['address']} ({state})")
        for sh in doc.get("data", {}).get("shards", []):
            health = "healthy" if sh.get("healthy") else "DEGRADED"
            self._print(f"  shard [{sh['begin'] or chr(39)*2} ...)     - "
                        f"x{sh['replication']} {health}")
        hist = c.get("recovery_history", [])
        if hist:
            self._print(f"  recoveries         - {len(hist)} "
                        f"(latest generation {hist[-1][0]})")
        self._print(f"  workers            - {len(c.get('workers', {}))}")

    def do_telemetry(self, args: List[str]) -> None:
        """Unified resolver telemetry (docs/observability.md): the status
        document's qos.resolver_telemetry fragment (engine perf counters,
        budget-batcher EWMAs, health), or a persisted metric series."""
        if args and args[0] == "read":
            from ..client.metric_logger import read_metric

            process, metric = args[1], args[2]
            series = self._drive(read_metric(self.db, process, metric))
            for t, v in series:
                self._print(f"  {t:12.3f}  {v}")
            self._print(f"{len(series)} entr{'y' if len(series) == 1 else 'ies'}")
            return
        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return
        qos = doc.get("qos") or {}
        tel = qos.get("resolver_telemetry") or {}
        if args and args[0] == "json":
            self._print(json.dumps(
                {"resolver_health": qos.get("resolver_health", {}),
                 "resolver_telemetry": tel},
                indent=2, sort_keys=True))
            return
        health = qos.get("resolver_health") or {}
        if not health and not tel:
            self._print("no resolver telemetry yet (cluster still seeding?)")
            return
        for addr in sorted(set(health) | set(tel)):
            self._print(f"  resolver {addr}: {health.get(addr, '?')}")
            frag = tel.get(addr) or {}
            perf = frag.get("engine_perf")
            if perf:
                hits = ", ".join(f"{k}:{v}" for k, v in
                                 sorted(perf.get("bucket_hits", {}).items()))
                scans = ", ".join(f"{k}:{v}" for k, v in
                                  sorted(perf.get("scan_dispatches",
                                                  {}).items()))
                # warmup_ms + the compile/scan counters (collected since
                # PR 3) on the same line as the bucket histogram, so one
                # glance says what was compiled, when, and what it served
                self._print(f"    engine   - compiles {perf.get('compiles')} "
                            f"(warmup {perf.get('warmup_ms', 0):.0f}ms, "
                            f"warmed {perf.get('warmed')}), "
                            f"bucket hits {{{hits}}}, scans {{{scans}}}")
                dtm = perf.get("device_time_ms") or {}
                if dtm:
                    # bucket keys are stringified ints: sort numerically
                    # or 128 renders before 64
                    sampled = ", ".join(
                        f"{k}:{v}ms" for k, v in
                        sorted(dtm.items(), key=lambda kv: int(kv[0])))
                    ns = sum((perf.get("device_time_samples") or {}).values())
                    self._print(f"    devtime  - sampled {{{sampled}}} "
                                f"({ns} samples)")
                modes = perf.get("search_mode_hits") or {}
                if modes:
                    picks = ", ".join(f"{k}:{v}" for k, v in
                                      sorted(modes.items()))
                    self._print(f"    search   - mode hits {{{picks}}}")
                dmodes = perf.get("dispatch_mode_hits") or {}
                if dmodes:
                    picks = ", ".join(f"{k}:{v}" for k, v in
                                      sorted(dmodes.items()))
                    self._print(f"    dispatch - mode hits {{{picks}}}")
                verdicts = perf.get("verdicts") or {}
                if verdicts:
                    # abort-cause split (docs/observability.md "Keyspace
                    # heat & occupancy"): aggregated, not per batch
                    split = ", ".join(f"{k}:{v}" for k, v in
                                      sorted(verdicts.items()))
                    self._print(f"    verdicts - {{{split}}}")
            b = frag.get("batcher")
            if b:
                ewma = ", ".join(f"{k}:{v}ms" for k, v in
                                 sorted(b.get("ewma_ms", {}).items()))
                disp = b.get("dispatch_mode")
                self._print(f"    batcher  - budget {b.get('budget_ms')}ms"
                            + (f", dispatch {disp}" if disp else "")
                            + f", ewma {{{ewma}}}")
            if "flight_recorder_entries" in frag:
                self._print(f"    flightrec- {frag['flight_recorder_entries']} "
                            "recent dispatch records")

    @staticmethod
    def _mib(n) -> str:
        return f"{n / (1 << 20):.1f} MiB"

    def do_perf(self, args: List[str]) -> None:
        """Performance observatory (docs/observability.md "Performance
        observatory"): the compile & memory ledger, the PR 11
        state-memory gauge and the sampled measured device timing,
        joined into one per-resolver view off the status document."""
        from ..core.knobs import SERVER_KNOBS

        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return
        tel = (doc.get("qos") or {}).get("resolver_telemetry") or {}
        if args and args[0] == "json":
            self._print(json.dumps(
                {addr: {"perf_ledger": frag.get("perf_ledger"),
                        "state_bytes": frag.get("state_bytes"),
                        "state_memory_pressure":
                            frag.get("state_memory_pressure"),
                        "device_time_ms": (frag.get("engine_perf") or {})
                            .get("device_time_ms")}
                 for addr, frag in tel.items()},
                indent=2, sort_keys=True))
            return
        rendered = 0
        limit = int(SERVER_KNOBS.resolver_state_memory_limit)
        for addr in sorted(tel):
            frag = tel.get(addr) or {}
            ledger = frag.get("perf_ledger")
            sb = frag.get("state_bytes")
            if ledger is None and sb is None:
                continue
            rendered += 1
            self._print(f"  resolver {addr}:")
            if sb is not None:
                pressure = ("PRESSURE"
                            if frag.get("state_memory_pressure") else "ok")
                line = (f"    memory   - state {self._mib(sb)} / "
                        f"limit {self._mib(limit)} ({pressure})")
                if ledger and ledger.get("peak_bytes"):
                    line += (f", peak compiled-program HBM "
                             f"{self._mib(ledger['peak_bytes'])}")
                self._print(line)
            if ledger:
                comp = ledger.get("compiles") or {}
                ms = ledger.get("compile_ms") or {}
                self._print(
                    f"    compiles - warmup {comp.get('warmup', 0)} "
                    f"({ms.get('warmup', 0):.0f}ms), "
                    f"steady {comp.get('steady', 0)} "
                    f"({ms.get('steady', 0):.0f}ms), "
                    f"flops {ledger.get('flops_total', 0):.3g}, "
                    f"bytes {ledger.get('bytes_accessed_total', 0):.3g}")
                for r in (ledger.get("rows") or [])[-8:]:
                    peak = (self._mib(r["peak_bytes"])
                            if r.get("peak_bytes") else "n/a")
                    self._print(
                        f"      [{r.get('kind'):>6}] T={r.get('bucket')} "
                        f"x{r.get('n_chunks')} {r.get('search_mode')}/"
                        f"{r.get('dispatch_mode')} "
                        f"{r.get('duration_ms', 0):.0f}ms "
                        f"peak {peak}")
            dtm = (frag.get("engine_perf") or {}).get("device_time_ms") or {}
            if dtm:
                sampled = ", ".join(
                    f"{k}:{v}ms" for k, v in
                    sorted(dtm.items(), key=lambda kv: int(kv[0])))
                self._print(f"    devtime  - sampled per-bucket {{{sampled}}}")
        if not rendered:
            self._print("no perf-observatory telemetry yet (oracle engines, "
                        "or the cluster is still seeding)")

    def do_bench_history(self, args: List[str]) -> int:
        """BENCH_r*.json trend tables + regression gate (cluster-less;
        docs/observability.md "Performance observatory"). Args pass
        through to tools/bench_history.py, and the gate's exit status is
        returned so one-shot `cli bench-history` fails CI exactly like
        `make bench-history`."""
        from . import bench_history

        rc = bench_history.main(argv=list(args), out=self.out)
        if rc:
            self._print("bench-history: GATE FAILURES (see above)")
        return rc

    # -- cluster-less report loading (one path for every subcommand that
    # renders a campaign report JSON: heat, alerts, incidents, shards,
    # chaos-status, explain, blackbox) --------------------------------------
    def _report_campaigns(self, path: str):
        """(doc, [(label, campaign dict)]) for a report file; a missing
        or corrupt file prints ONE uniform error and returns (None, []).
        Campaign labels follow the `seed N [mode]` convention
        everywhere, and a field a given report never recorded (an old
        report read by a newer CLI — e.g. `blackbox`) renders as the
        caller's uniform "no X records" line, never a KeyError."""
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            self._print(f"cannot read {path}: {e}")
            return None, []
        rows = [(f"seed {rep.get('cfg_seed')} [{rep.get('engine_mode')}]",
                 rep)
                for rep in doc.get("campaigns", [])]
        return doc, rows

    def _render_campaign_field(self, path: str, fld: str, render,
                               missing_hint: str):
        """Render `render(label, value)` for every campaign carrying
        `fld`; one uniform message when none do. Returns the loaded doc
        (None on a load error)."""
        doc, rows = self._report_campaigns(path)
        if doc is None:
            return None
        rendered = 0
        for label, rep in rows:
            value = rep.get(fld)
            if value:
                render(label, value)
                rendered += 1
        if not rendered:
            self._print(f"no {fld} records in {path} ({missing_hint})")
        return doc

    def _render_heat(self, label: str, heat: dict) -> None:
        """One engine's keyspace-heat snapshot (core/heatmap.py layout)."""
        self._print(f"  {label}:")
        occ = heat.get("occupancy", 0)
        cap = heat.get("capacity", 0) or 1
        frac = heat.get("occupancy_frac", occ / cap)
        verd = heat.get("verdicts") or {}
        self._print(f"    occupancy    - {occ}/{cap} rows "
                    f"({frac * 100:.1f}%, headroom {(1 - frac) * 100:.1f}%), "
                    f"gc reclaimed {heat.get('gc_reclaimed', 0)}")
        self._print(f"    verdicts     - committed {verd.get('committed', 0)}, "
                    f"conflicts {verd.get('conflicts', 0)}, "
                    f"too_old {verd.get('too_old', 0)} "
                    f"over {heat.get('batches', 0)} batches")
        self._print(f"    concentration- {heat.get('concentration', 0):.3f} "
                    "(0 = even load, 1 = one hot range)")
        hot = heat.get("hot_ranges") or []
        if hot:
            self._print("    hot ranges   - (share of write+conflict load)")
            for r in hot:
                end = r.get("end")
                self._print(
                    f"      [{r['begin']!r:<24} .. "
                    f"{(end if end is not None else '+inf')!r:<24}) "
                    f"{r['share'] * 100:5.1f}%  w={r['writes']:.0f} "
                    f"c={r['conflicts']:.0f} r={r['reads']:.0f}")
        splits = heat.get("split_points") or []
        if splits:
            bal = heat.get("split_balance") or []
            shards = heat.get("split_shards", len(splits) + 1)
            self._print(f"    split points - {shards} equal-load shards "
                        "(ROADMAP item 1 input):")
            for s in splits:
                self._print(f"      {s!r}")
            if bal:
                self._print("    shard load   - "
                            + ", ".join(f"{f * 100:.1f}%" for f in bal))
        for a in (heat.get("recent_attribution") or [])[-4:]:
            self._print(
                f"    abort@v{a.get('version')} <- write v"
                f"{a.get('witness_version')} in [{a.get('range_begin')!r} ..)")

    def do_heat(self, args: List[str]) -> None:
        """Keyspace heat & history occupancy (docs/observability.md
        "Keyspace heat & occupancy"): hot key ranges, interval-table
        headroom and suggested equal-load shard split points — live from
        the cluster status doc's qos.resolver_telemetry fragment, or from
        a campaign report / bench JSON artifact."""
        if args and args[0].endswith(".json"):
            doc, rows = self._report_campaigns(args[0])
            if doc is None:
                return
            rendered = 0
            for label, rep in rows:
                heat = rep.get("heat")
                if heat:
                    self._render_heat(label, heat)
                    rendered += 1
            ch = (doc.get("parsed", doc)).get("conflict_heat")
            if ch:
                for row in ch.get("sweep", []):
                    if row.get("heat"):
                        self._render_heat(f"zipf s={row.get('s')}",
                                          row["heat"])
                        rendered += 1
            if not rendered:
                self._print(f"no heat snapshots in {args[0]} (campaign "
                            "engines without the layer, or an old report)")
            return
        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return
        tel = (doc.get("qos") or {}).get("resolver_telemetry") or {}
        if args and args[0] == "json":
            self._print(json.dumps(
                {addr: frag.get("heat") for addr, frag in tel.items()},
                indent=2, sort_keys=True))
            return
        rendered = 0
        for addr in sorted(tel):
            heat = (tel.get(addr) or {}).get("heat")
            if heat:
                self._render_heat(f"resolver {addr}", heat)
                rendered += 1
        if not rendered:
            self._print("no keyspace heat yet (oracle engines, "
                        "resolver_heat_buckets=0, or no traffic)")

    # -- scenario atlas (docs/scenarios.md) ---------------------------------
    def _render_atlas_campaigns(self, path: str, rows) -> int:
        """Cross-campaign scorecard table from report campaigns. Every
        campaign gets a row; fields a pre-atlas report never recorded
        (`scenario`, `signature`) render as `—`, never a KeyError."""
        self._print(f"{len(rows)} campaign(s) in {path}")
        self._print(f"  {'scenario':<18} {'seed':>5} {'mode':<11} "
                    f"{'p99ms':>8} {'abort':>6} {'thrtl':>6} {'conc':>6} "
                    f"{'parity':>9}  top range")
        stamped = 0
        for _label, rep in rows:
            name = rep.get("scenario") or "—"
            sig = rep.get("signature") or {}
            if rep.get("scenario"):
                stamped += 1
            p99 = rep.get("p99_outside_ms")
            def frac(k):
                return f"{sig[k]:.3f}" if k in sig else "—"
            top = (f"{sig['top_range']!r} ({sig.get('top_share', 0) * 100:.0f}%)"
                   if sig.get("top_range") else "—")
            self._print(
                f"  {name:<18} {rep.get('cfg_seed', 0):>5} "
                f"{str(rep.get('engine_mode')):<11} "
                f"{(f'{p99:.2f}' if isinstance(p99, (int, float)) else '—'):>8} "
                f"{frac('abort_frac'):>6} {frac('throttle_frac'):>6} "
                f"{frac('concentration'):>6} "
                f"{rep.get('parity_checked', 0):>5}/{rep.get('parity_mismatches', 0)}mm"
                f"  {top}")
        if not stamped:
            self._print("  (no scenario stamps — pre-atlas report; run "
                        "real/scenarios.py recipes to record signatures)")
        return len(rows)

    def _render_atlas_section(self, sa: dict) -> None:
        """Bench-artifact scenario_atlas section: the full scorecard."""
        self._print(f"scenario atlas — seed {sa.get('seed')} "
                    f"[{sa.get('engine_mode')}], "
                    f"{sa.get('seconds')}s per scenario, "
                    f"{'ALL GREEN' if sa.get('all_green') else 'RED'}")
        self._print(f"  {'scenario':<18} {'slo':<4} {'p99ms':>8} "
                    f"{'budget':>7} {'abort':>12} {'throttle':>12} "
                    f"{'tps':>6} {'commits':>7} {'resh':>4}")
        for row in sa.get("scorecard", []):
            p99 = row.get("p99_ms")
            self._print(
                f"  {row.get('scenario', '—'):<18} "
                f"{'ok' if row.get('slo_pass') else 'RED':<4} "
                f"{(f'{p99:.2f}' if isinstance(p99, (int, float)) else '—'):>8} "
                f"{row.get('budget_ms', 0):>7.0f} "
                f"{row.get('abort_frac', 0):>5.3f}<={row.get('max_abort_frac', 0):<5.2f} "
                f"{row.get('throttle_frac', 0):>5.3f}<={row.get('max_throttle_frac', 0):<5.2f} "
                f"{row.get('sustained_tps', 0):>6.1f} "
                f"{row.get('committed', 0):>7} "
                f"{row.get('reshards_executed', 0):>4}")

    def do_atlas(self, args: List[str]) -> None:
        """Scenario-atlas scorecards (docs/scenarios.md): per-scenario
        SLO verdicts and heat/abort signatures — cluster-less from a
        campaign report JSON (real/nemesis.py --json with scenario
        stamps) or a bench artifact with a scenario_atlas section, or
        live from this process's scenario.* telemetry gauges after an
        in-process run_scenario."""
        if args and args[0].endswith(".json"):
            doc, rows = self._report_campaigns(args[0])
            if doc is None:
                return
            rendered = 0
            if rows:
                rendered += self._render_atlas_campaigns(args[0], rows)
            sa = (doc.get("parsed", doc)).get("scenario_atlas")
            if sa and not sa.get("error"):
                self._render_atlas_section(sa)
                rendered += 1
            if not rendered:
                self._print(f"no scenario records in {args[0]} (neither "
                            "campaign reports nor a scenario_atlas "
                            "bench section)")
            return
        from ..core import telemetry

        metrics = telemetry.hub().tdmetrics.metrics
        by_scenario: dict = {}
        for name, m in metrics.items():
            if name.startswith("scenario."):
                _, scen, metric = name.split(".", 2)
                by_scenario.setdefault(scen, {})[metric] = int(
                    getattr(m, "value", 0))
        if not by_scenario:
            self._print("no scenario gauges in this process "
                        "(run real/scenarios.py run_scenario first, or "
                        "point at a report: atlas REPORT.json)")
            return
        for scen in sorted(by_scenario):
            g = by_scenario[scen]
            verdict = g.get("slo_pass")
            self._print(
                f"  {scen:<18} "
                f"{'ok' if verdict else ('RED' if verdict == 0 else '—'):<4}"
                f" p99={g.get('p99_us', -1) / 1000:.2f}ms"
                f" abort={g.get('abort_frac_x1000', 0) / 1000:.3f}"
                f" throttle={g.get('throttle_frac_x1000', 0) / 1000:.3f}"
                f" conc={g.get('concentration_x1000', 0) / 1000:.3f}"
                f" commits={g.get('committed', 0)}")

    # -- conflict-aware admission (docs/scheduling.md) ----------------------
    def _render_sched(self, label: str, snap: dict) -> None:
        """One scheduler snapshot (pipeline/scheduler.py layout)."""
        c = snap.get("counters") or {}
        self._print(f"  {label}: epoch {snap.get('epoch', -1)}, "
                    f"{c.get('ticks', 0)} ticks, "
                    f"{c.get('examined', 0)} examined")
        self._print(f"    dispatched   - {c.get('dispatched', 0)} "
                    f"(reordered {c.get('reordered', 0)}, "
                    f"forced {c.get('forced', 0)})")
        self._print(f"    deferred     - {c.get('deferred', 0)}  "
                    f"laned {c.get('laned', 0)}  "
                    f"lane_drained {c.get('lane_drained', 0)}")
        self._print(f"    pre-aborts   - {c.get('preaborts', 0)}  "
                    f"probes {c.get('probes', 0)} "
                    f"(ok {c.get('probe_ok', 0)}, "
                    f"mispredict {c.get('mispredicts', 0)}) -> "
                    f"mispredict_frac {snap.get('mispredict_frac', 0.0)}")
        lanes = snap.get("lanes") or []
        if lanes or snap.get("pending_laned"):
            self._print(f"    lanes        - {len(lanes)} open "
                        f"({c.get('lanes_opened', 0)} opened, "
                        f"{c.get('lanes_retired', 0)} retired, "
                        f"{snap.get('pending_laned', 0)} queued, "
                        f"{c.get('epoch_flips', 0)} epoch flips)")
            for lane in lanes[:6]:
                self._print(
                    f"      [{lane.get('range_begin')} ..) "
                    f"{lane.get('state'):<8} depth {lane.get('depth')} "
                    f"captured {lane.get('captured')} "
                    f"drained {lane.get('drained')} "
                    f"epoch {lane.get('epoch')}")
        pred = snap.get("predictor") or {}
        hot = pred.get("hot_ranges") or []
        if hot:
            self._print(f"    predictor    - {pred.get('tracked_ranges', 0)}"
                        " tracked, "
                        f"{pred.get('witnesses_consumed', 0)} witnesses; "
                        "hottest:")
            for r in hot:
                self._print(f"      [{r.get('range_begin')} ..) "
                            f"score {r.get('score')}")

    def _render_sched_ab(self, ab: dict) -> None:
        """A/B section a bench artifact records (conflict_scheduling)."""
        self._print("  A/B (same seed, scheduler off vs on):")
        for arm in ("off", "on"):
            row = ab.get(arm) or {}
            self._print(
                f"    {arm:<3} abort_frac {row.get('abort_frac')}  "
                f"served_tps {row.get('served_tps')}  "
                f"p99 {row.get('p99_ms')} ms  parity_mismatches "
                f"{row.get('parity_mismatches')}")
        self._print(
            f"    abort_frac_reduction "
            f"{ab.get('abort_frac_reduction')}  served_tps_ratio "
            f"{ab.get('served_tps_ratio')}  goal_met {ab.get('goal_met')}")

    def do_sched(self, args: List[str]) -> None:
        """Conflict-aware admission (docs/scheduling.md): predictor hot
        ranges, serialization lanes, pre-abort and mispredict-probe
        counters — live from the cluster status doc's
        qos.resolver_telemetry fragment, or from a campaign report /
        bench JSON artifact."""
        if args and args[0].endswith(".json"):
            doc, rows = self._report_campaigns(args[0])
            if doc is None:
                return
            rendered = 0
            for label, rep in rows:
                snap = rep.get("sched")
                if snap:
                    self._render_sched(label, snap)
                    rendered += 1
            ab = (doc.get("parsed", doc)).get("conflict_scheduling")
            if ab and isinstance(ab, dict) and "on" in ab:
                self._render_sched_ab(ab)
                rendered += 1
            if not rendered:
                self._print(f"no scheduler snapshots in {args[0]} "
                            "(resolver_sched off, or an old report)")
            return
        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return
        tel = (doc.get("qos") or {}).get("resolver_telemetry") or {}
        if args and args[0] == "json":
            self._print(json.dumps(
                {addr: frag.get("sched") for addr, frag in tel.items()},
                indent=2, sort_keys=True))
            return
        rendered = 0
        for addr in sorted(tel):
            snap = (tel.get(addr) or {}).get("sched")
            if snap:
                self._render_sched(f"resolver {addr}", snap)
                rendered += 1
        if not rendered:
            self._print("no conflict-scheduler telemetry yet "
                        "(resolver_sched knob off, or no traffic)")

    # -- cluster watchdog (docs/observability.md "Watchdog, burn rates &
    # incidents"; per-alert runbook table in docs/operations.md) ------------
    def _render_alerts(self, label: str, snap: dict) -> None:
        """One watchdog snapshot's alert table (core/watchdog.py)."""
        evals = snap.get("evaluations")
        self._print(f"  {label}: "
                    + (f"{evals} evaluations, " if evals is not None else "")
                    + f"{len(snap.get('firing') or [])} firing"
                    + (" [BURN ALERT — ratekeeper clamping]"
                       if snap.get("burn_firing") else ""))
        alerts = snap.get("alerts") or []
        if not alerts:
            self._print("    no alert states tracked yet (no matching "
                        "series under the rules)")
        for a in sorted(alerts, key=lambda a: (a["state"] == "ok",
                                               a["name"], a["series"])):
            mark = {"firing": "!!", "pending": " ~"}.get(a["state"], "  ")
            self._print(f"    {mark} {a['name']:<24} {a['state']:<8} "
                        f"{a['series']:<36} v={a['value']} "
                        f"fired x{a.get('fired_count', 0)}  {a['detail']}")

    def _render_incidents(self, label: str, incidents: list,
                          alerts_hint: Optional[list] = None) -> None:
        """One campaign's / watchdog's incident timeline."""
        if not incidents:
            self._print(f"  {label}: no incidents"
                        + ("" if alerts_hint is None else
                           f" ({len(alerts_hint)} alert states all quiet)"))
            return
        self._print(f"  {label}: {len(incidents)} incident(s)")
        for inc in incidents:
            t1 = inc.get("t1")
            span = (f"{inc.get('t0', 0):.2f}s .. "
                    + (f"{t1:.2f}s" if t1 is not None else "OPEN"))
            verdict = ("EXPLAINED" if inc.get("explained")
                       else "UNEXPLAINED")
            self._print(f"    #{inc.get('id')} [{span}] {verdict}"
                        + (f" — {inc.get('explanation')}"
                           if inc.get("explanation") else ""))
            self._print(f"       {inc.get('summary')}")
            for a in inc.get("alerts") or []:
                self._print(f"       alert {a.get('name')} "
                            f"({a.get('kind')}) {a.get('series')} "
                            f"v={a.get('value')}  {a.get('detail')}")
            for h in inc.get("health") or []:
                self._print(f"       health t={h.get('t'):.2f}s "
                            f"{h.get('label')} -> {h.get('state')}")
            rc = inc.get("root_cause")
            if rc:
                self._print(f"       root cause: dominant="
                            f"{rc.get('dominant_segment')} "
                            f"({rc.get('dominant_ms')} ms of "
                            f"{rc.get('client_ms')} ms, trace "
                            f"{rc.get('rid')})")

    def _watchdog_fragments(self, args: List[str]):
        """(label, watchdog snapshot-or-campaign dict) rows from a report
        file (cluster-less) or the live cluster status document."""
        if args and args[0].endswith(".json"):
            _doc, rows = self._report_campaigns(args[0])
            return (rows if _doc is not None else None), True
        doc = self._drive(self.db.get_status())
        if doc is None:
            self._print("status unavailable (no cluster controller reachable)")
            return None, False
        tel = (doc.get("qos") or {}).get("resolver_telemetry") or {}
        return [(f"resolver {addr}", frag.get("watchdog"))
                for addr, frag in sorted(tel.items())
                if frag.get("watchdog") is not None], False

    def do_alerts(self, args: List[str]) -> None:
        """Watchdog alert states, live (status doc watchdog fragment) or
        cluster-less over a campaign report JSON (real/nemesis.py --json
        --watchdog)."""
        rows, from_file = self._watchdog_fragments(args)
        if rows is None:
            return
        if args and args[0] == "json":
            self._print(json.dumps(
                {label: {"alerts": (frag or {}).get("alerts"),
                         "firing": (frag or {}).get("firing")}
                 for label, frag in rows},
                indent=2, sort_keys=True, default=str))
            return
        rendered = 0
        for label, frag in rows:
            if from_file:
                alerts = frag.get("alerts")
                if alerts is None:
                    continue
                snap = {"alerts": alerts,
                        "firing": [a for a in alerts
                                   if a.get("state") == "firing"]}
                self._render_alerts(label, snap)
            else:
                self._render_alerts(label, frag)
            rendered += 1
        if not rendered:
            self._print("no watchdog telemetry (watchdog_enabled off, or "
                        "campaigns run without --watchdog)")

    def do_incidents(self, args: List[str]) -> None:
        """Machine-correlated incident timelines, live or cluster-less
        over a campaign report JSON — what `make chaos-real` renders
        after its campaigns."""
        rows, from_file = self._watchdog_fragments(args)
        if rows is None:
            return
        if args and args[0] == "json":
            self._print(json.dumps(
                {label: (frag.get("incidents") if frag else None)
                 for label, frag in rows},
                indent=2, sort_keys=True, default=str))
            return
        rendered = 0
        for label, frag in rows:
            incidents = (frag or {}).get("incidents")
            if incidents is None:
                continue
            self._render_incidents(label, incidents,
                                   alerts_hint=(frag or {}).get("alerts"))
            rendered += 1
        if not rendered:
            self._print("no incident telemetry (watchdog_enabled off, or "
                        "campaigns run without --watchdog)")

    def do_chaos_status(self, args: List[str]) -> None:
        """Nemesis activity (docs/real_cluster.md): chaos.* counters + the
        recent event ring from the telemetry hub — the live view after an
        in-process campaign — or the aggregated counts of a campaign
        report file (real/nemesis.py --json)."""
        if args:
            doc, rows = self._report_campaigns(args[0])
            if doc is None:
                return
            totals: dict = {}
            campaigns = [rep for _label, rep in rows]
            for rep in campaigns:
                for kind, n in (rep.get("chaos_counts") or {}).items():
                    totals[kind] = totals.get(kind, 0) + n
            self._print(f"{len(campaigns)} campaign(s) in {args[0]}")
            if not totals:
                self._print("no nemesis events recorded")
                return
            self._print("nemesis event counts (all campaigns):")
            for kind in sorted(totals):
                self._print(f"  {kind:<18} {totals[kind]}")
            for rep in campaigns:
                eng = rep.get("engine_stats") or {}
                self._print(
                    f"  seed {rep.get('cfg_seed')} [{rep.get('engine_mode')}]"
                    f" p99_outside={rep.get('p99_outside_ms'):.3f}ms"
                    f" failovers={eng.get('failovers', 0)}"
                    f" swap_backs={eng.get('swap_backs', 0)}"
                    f" parity={rep.get('parity_checked')}"
                    f"/{rep.get('parity_mismatches')}mm")
            return
        from ..real.chaos import chaos_status_lines

        for line in chaos_status_lines():
            self._print(line)

    # -- commit forensics (docs/observability.md "Black-box journal &
    # forensics": core/blackbox.py + tools/forensics.py) --------------------
    def _forensics_rows(self, src: str):
        """[(label, events)] for a journal dir / report path, with the
        uniform operator-speakable error on anything unresolvable."""
        from . import forensics

        try:
            return forensics.load_source(src)
        except forensics.ForensicsError as e:
            self._print(str(e))
            return None

    def _explain_rows(self, rows, version: int) -> None:
        from . import forensics

        last_err = "no journal rows"
        for label, events in rows:
            try:
                info = forensics.explain(events, version)
            except forensics.ForensicsError as e:
                last_err = str(e)
                continue
            if len(rows) > 1:
                self._print(f"[{label}]")
            for line in forensics.render_explain(info):
                self._print(line)
            return
        self._print(last_err)

    def do_explain(self, args: List[str]) -> None:
        """Causal explain of one resolved batch version — admission,
        routing epoch, span segments, verdict + first witness, failover
        arc, incident/fault overlap — from a black-box journal dir or a
        campaign report JSON (`explain --slo REPORT.json` explains the
        worst retained-ack breach end to end)."""
        if not args:
            self._print("usage: explain VERSION DIR_OR_REPORT.json | "
                        "explain --slo REPORT.json")
            return
        if args[0] == "--slo":
            if len(args) < 2:
                self._print("usage: explain --slo REPORT.json")
                return
            doc, rows = self._report_campaigns(args[1])
            if doc is None:
                return
            best = None
            for label, rep in rows:
                rc = rep.get("slo_root_cause") or {}
                bb = rep.get("blackbox") or {}
                if rc.get("version") is None or not bb.get("dir"):
                    continue
                if best is None or (rc.get("client_ms") or 0) > best[2]:
                    best = (label, rep, rc.get("client_ms") or 0)
            if best is None:
                self._print(
                    f"no explainable SLO root cause in {args[1]} "
                    "(campaigns without a blackbox journal, or no "
                    "retained traces)")
                return
            label, rep, _ms = best
            rc = rep["slo_root_cause"]
            self._print(
                f"worst retained ack: {label} trace {rc.get('rid')} "
                f"v{rc.get('version')} {rc.get('client_ms')} ms "
                f"dominant={rc.get('dominant_segment')}")
            frows = self._forensics_rows(rep["blackbox"]["dir"])
            if frows is not None:
                self._explain_rows(frows, int(rc["version"]))
            return
        if len(args) < 2:
            self._print("usage: explain VERSION DIR_OR_REPORT.json")
            return
        try:
            version = int(str(args[0]).lstrip("v"))
        except ValueError:
            self._print("usage: explain VERSION DIR_OR_REPORT.json "
                        "(VERSION is a commit version, e.g. v8600)")
            return
        rows = self._forensics_rows(args[1])
        if rows is not None:
            self._explain_rows(rows, version)

    def do_blackbox(self, args: List[str]) -> None:
        """Black-box journal workflows: `blackbox SRC` summarizes what a
        journal holds; `blackbox replay --window v1..v2 SRC` slices the
        journal, re-resolves the window through the clean serial oracle
        and diffs verdicts bit-for-bit (differential replay — works on
        any persisted window, including across a reshard epoch flip)."""
        from . import forensics

        if not args:
            self._print("usage: blackbox SRC | "
                        "blackbox replay --window v1..v2 SRC")
            return
        if args[0] == "replay":
            rest = list(args[1:])
            spec = None
            if "--window" in rest:
                i = rest.index("--window")
                if i + 1 < len(rest):
                    spec = rest[i + 1]
                del rest[i:i + 2]
            if spec is None or not rest:
                self._print("usage: blackbox replay --window v1..v2 SRC")
                return
            try:
                v1, v2 = forensics.parse_window(spec)
            except (forensics.ForensicsError, ValueError) as e:
                self._print(str(e))
                return
            rows = self._forensics_rows(rest[0])
            if rows is None:
                return
            for label, events in rows:
                try:
                    r = forensics.diff_replay(events, v1, v2)
                except forensics.ForensicsError as e:
                    self._print(f"  {label}: {e}")
                    continue
                verdict = ("VERDICT-IDENTICAL" if r["mismatches"] == 0
                           else f"{r['mismatches']} MISMATCHED BATCHES")
                self._print(
                    f"  {label}: replayed {r['window_batches']} batch(es)"
                    f" in v{v1}..v{v2} (+{r['prefix_batches']} prefix) "
                    f"through the clean serial oracle — {verdict}; "
                    f"epochs {r['epochs']}, coverage "
                    f"{'ok' if r['coverage_ok'] else 'PARTIAL (rotated)'}")
                if r.get("duplicate_versions"):
                    self._print(
                        f"    WARNING: versions {r['duplicate_versions']} "
                        "recorded more than once in one stream (appended "
                        "runs in one directory?) — duplicates skipped, "
                        "not double-applied")
                for mm in r["mismatch_detail"]:
                    self._print(f"    v{mm.get('version')}: got "
                                f"{mm.get('got')} want {mm.get('want')}")
            return
        rows = self._forensics_rows(args[0])
        if rows is None:
            return
        for label, events in rows:
            for line in forensics.summarize(label, events):
                self._print(line)

    def do_recovery(self, args: List[str]) -> None:
        """Crash-stop recovery workflows (docs/fault_tolerance.md
        "Crash-stop recovery"): render the durable recovery arc — the
        snapshot cadence, the last restart's mode/coverage/replay, its
        blackout against the resolver_recovery_budget_ms knob and the
        progcache rewarm — from a black-box journal directory or a
        crash-campaign report JSON (the journaled `snapshot` /
        `recovery` events ARE the source; nothing is recomputed)."""
        from ..core.knobs import SERVER_KNOBS

        if not args:
            self._print("usage: recovery DIR_OR_REPORT.json")
            return
        rows = self._forensics_rows(args[0])
        if rows is None:
            return
        budget = float(SERVER_KNOBS.resolver_recovery_budget_ms)
        for label, events in rows:
            snaps = [e for e in events if e.kind == "snapshot"]
            recs = [e for e in events if e.kind == "recovery"]
            self._print(f"  {label}: {len(snaps)} snapshot(s), "
                        f"{len(recs)} recovery arc(s)")
            if snaps:
                s = snaps[-1].payload
                ent = "entry" if s.entries == 1 else "entries"
                self._print(
                    f"    last snapshot v{s.version} (oldest {s.oldest}, "
                    f"{s.entries} coalesced {ent}, {s.bytes} B, "
                    f"{s.ms}ms)")
            if not recs:
                self._print("    no recovery recorded (the node never "
                            "restarted into this journal)")
                continue
            r = recs[-1].payload
            cov = ("ok" if r.coverage_ok
                   else "DEGRADED (rotation ate the horizon)")
            self._print(
                f"    last recovery: mode={r.mode} coverage={cov} "
                f"snapshot v{r.snapshot_version} + {r.replayed_batches} "
                f"replayed batch(es) -> v{r.recovered_version}")
            over = "" if r.blackout_ms <= budget else "  ** OVER BUDGET **"
            self._print(
                f"    blackout {r.blackout_ms}ms (budget {budget}ms"
                f"{over}), warm {r.warm_ms}ms, progcache "
                f"{r.progcache_hits} hit(s) / {r.progcache_misses} "
                f"miss(es)")
            if r.verdict_mismatches:
                self._print(f"    ** {r.verdict_mismatches} VERDICT "
                            "MISMATCH(ES) during replay **")
            if r.error:
                self._print(f"    ** recovery error: {r.error} **")

    def do_lint(self, args: List[str]) -> int:
        """Static invariant check (docs/static_analysis.md): run the
        fdbtpu-lint checkers over the repo — cluster-less, pure AST (never
        imports jax), args pass straight through to the lint CLI.  Returns
        the lint exit status so one-shot `cli lint` fails CI exactly like
        `python -m foundationdb_tpu.tools.lint` does."""
        from .lint import CHECKERS
        from .lint.core import main as lint_main

        rc = lint_main(CHECKERS, argv=list(args), out=self.out)
        if rc:
            self._print("lint: FINDINGS (see above)")
        return rc

    def do_trace(self, args: List[str]) -> None:
        """Distributed-trace workflows (docs/observability.md "Distributed
        tracing"): validate+summarize an exported Chrome trace JSON, or
        fetch live span rings over the `trace.spans` RPC token and
        reconstruct cross-process per-commit waterfalls."""
        import asyncio

        from . import trace_export as tx

        if not args:
            self._print("usage: trace FILE.json | "
                        "trace fetch ADDR [ADDR...] [OUT.json]")
            return
        if args[0] == "fetch":
            addrs = [a for a in args[1:] if ":" in a]
            out = next((a for a in args[1:] if a.endswith(".json")), None)
            if not addrs:
                self._print("trace fetch: need at least one HOST:PORT")
                return
            spans = asyncio.run(tx.fetch_spans(addrs))
            waterfalls = tx.build_waterfalls(spans)
            retained = tx.tail_sample(waterfalls)
            self._print(f"{len(spans)} spans from {len(addrs)} process(es); "
                        f"{len(waterfalls)} waterfalls, "
                        f"{len(retained)} retained by tail sampling")
            for w in retained[:20]:
                path = (f"{w.get('proc_client') or '?'} -> "
                        f"{w.get('proc_server') or 'UNREACHED'}")
                err = f" err={w['err']}" if w["err"] else ""
                self._print(
                    f"  {str(w['rid']):<16} v={w['version']} "
                    f"{w['client_ms']:>9.3f}ms "
                    f"dominant={w['dominant_segment']}{err}  [{path}]")
            if out is not None:
                doc = tx.chrome_trace(tx.spans_for_traces(spans, retained))
                with open(out, "w") as f:
                    json.dump(doc, f, default=str)
                self._print(f"chrome trace -> {out}")
            return
        with open(args[0]) as f:
            doc = json.load(f)
        n = tx.validate_chrome_trace(doc)
        events = doc.get("traceEvents", [])
        # args is optional per the trace-event format: a metadata event
        # without it is valid, it just leaves the pid unnamed
        procs = {ev["pid"]: ev.get("args", {}).get("name", str(ev["pid"]))
                 for ev in events if ev.get("ph") == "M"
                 and ev.get("name") == "process_name"}
        per_proc: dict = {}
        for ev in events:
            if ev.get("ph") == "X":
                name = procs.get(ev["pid"], str(ev["pid"]))
                per_proc[name] = per_proc.get(name, 0) + 1
        self._print(f"{args[0]}: valid Chrome trace, {n} duration events "
                    f"across {len(procs)} process(es)")
        for name in sorted(per_proc):
            self._print(f"  {name:<24} {per_proc[name]} events")
        slowest = sorted((ev for ev in events if ev.get("ph") == "X"
                          and ev.get("cat") == "span"),
                         key=lambda e: -e.get("dur", 0))[:5]
        if slowest:
            self._print("slowest spans:")
            for ev in slowest:
                ev_args = ev.get("args") or {}
                self._print(f"  {ev['name']:<24} {ev['dur'] / 1e3:>9.3f}ms "
                            f"trace={ev_args.get('Trace')}")

    def do_get(self, args: List[str]) -> None:
        (key,) = args

        async def go(tr):
            return await tr.get(_arg_bytes(key))

        self._print(f"`{key}' is {_fmt(self._drive(self.db.run(go)))}")

    def do_set(self, args: List[str]) -> None:
        key, value = args

        async def go(tr):
            tr.set(_arg_bytes(key), _arg_bytes(value))

        self._drive(self.db.run(go))
        self._print("committed")

    def do_clear(self, args: List[str]) -> None:
        (key,) = args

        async def go(tr):
            tr.clear(_arg_bytes(key))

        self._drive(self.db.run(go))
        self._print("committed")

    def do_clearrange(self, args: List[str]) -> None:
        begin, end = args

        async def go(tr):
            tr.clear_range(_arg_bytes(begin), _arg_bytes(end))

        self._drive(self.db.run(go))
        self._print("committed")

    def do_getrange(self, args: List[str]) -> None:
        begin, end = args[0], args[1]
        limit = int(args[2]) if len(args) > 2 else 25

        async def go(tr):
            return await tr.get_range(_arg_bytes(begin), _arg_bytes(end), limit=limit)

        rows = self._drive(self.db.run(go))
        for k, v in rows:
            self._print(f"  {_fmt(k)} -> {_fmt(v)}")
        self._print(f"{len(rows)} row(s)")

    def _render_reshard(self, label: str, rs: dict) -> None:
        """One campaign's online-resharding record (server/reshard.py
        ReshardController.snapshot layout)."""
        sm = rs.get("shard_map") or {}
        self._print(f"  {label}: epoch {sm.get('epoch', rs.get('epoch'))}, "
                    f"{sm.get('n_shards', '?')} shard(s), "
                    f"{rs.get('executed', 0)} reshard(s) executed, "
                    f"{rs.get('stalled', 0)} stalled")
        splits = sm.get("splits") or []
        begins = ["''"] + [repr(s) for s in splits]
        for i, b in enumerate(begins):
            e = begins[i + 1] if i + 1 < len(begins) else "+inf"
            self._print(f"    shard {i}: [{b} .. {e})")
        # mesh-backed slots: device placement per shard (absent in
        # pre-mesh reports and single-chip engine modes — render nothing)
        dview = rs.get("device_view") or []
        if dview:
            self._print("    device placement:")
            for row in dview:
                ms = row.get("last_collective_ms")
                self._print(
                    f"      slot {row.get('sid')} shard {row.get('shard')}"
                    f" -> {row.get('platform', '?')}:{row.get('device')}"
                    f"  [{row.get('span_begin', '')!r} ...)"
                    f"  table {row.get('table_bytes', 0)} B"
                    + (f"  exchange {ms:.3f} ms" if ms else ""))
        hist = sm.get("history") or []
        if len(hist) > 1:
            self._print("    epoch history:")
            for h in hist:
                self._print(f"      epoch {h.get('epoch')} @ v"
                            f"{h.get('flip_version')}: "
                            f"{len(h.get('splits') or []) + 1} shard(s)")
        ops = rs.get("ops") or []
        if ops:
            self._print(f"    blackout budget {rs.get('blackout_budget_ms')}"
                        f" ms, worst {rs.get('blackout_ms_max')} ms, "
                        f"{rs.get('blackout_over_budget', 0)} over")
            for op in ops:
                end = op.get("end") if op.get("end") is not None else "+inf"
                self._print(
                    f"    #{op.get('id')} {op.get('kind'):<5} "
                    f"[{op.get('begin')!r} .. {end!r}) {op.get('state'):<8}"
                    f" blackout={op.get('blackout_ms', 0):.2f}ms"
                    f" precopy={op.get('precopied')} delta={op.get('delta')}"
                    + (" (prewarmed)" if op.get("prewarmed") else "")
                    + (f" ERR {op.get('error')}" if op.get("error") else ""))
        inflight = rs.get("in_flight")
        if inflight:
            self._print(f"    IN FLIGHT: #{inflight.get('id')} "
                        f"{inflight.get('kind')} state="
                        f"{inflight.get('state')}")

    def do_shards(self, args: List[str]) -> None:
        """Resolver epoch/shard map + executed reshards from a campaign
        report JSON (cluster-less, like `heat`), or the storage shard map
        of the live simulated cluster."""
        if args and args[0].endswith(".json"):
            self._render_campaign_field(
                args[0], "reshard", self._render_reshard,
                "campaigns run without --drift / reshard=True?")
            return
        from ..server import system_keys

        async def go(tr):
            return await tr.get_range(system_keys.KEY_SERVERS_PREFIX,
                                      system_keys.KEY_SERVERS_PREFIX + b"\xff")

        rows = self._drive(self.db.run(go))
        if not rows:
            self._print("no shard metadata (cluster still seeding?)")
            return
        for k, v in rows:
            begin = system_keys.shard_begin_of(k)
            team, extra = system_keys.decode_key_servers(v)
            label = _fmt(begin) if begin else "''"
            dests = ", ".join(f"tag {t} @ {a}" for t, a in team)
            moving = f"  (moving: +tags {list(extra)})" if extra else ""
            self._print(f"  [{label} ...) -> {dests}{moving}")

    def _find_master_ep(self, token_prefix: str):
        from ..sim.network import Endpoint

        for p in self.cluster.worker_procs:
            for tok in p.handlers:
                if tok.startswith(token_prefix):
                    return Endpoint(p.address, tok)
        return None

    def do_move(self, args: List[str]) -> None:
        from ..server.masterserver import MOVE_SHARD_TOKEN, MoveShardRequest
        from ..sim.loop import TaskPriority

        begin, dests = _arg_bytes(args[0]) if args[0] != "''" else b"", args[1:]
        ep = self._find_master_ep(MOVE_SHARD_TOKEN)
        if ep is None:
            self._print("no master reachable")
            return

        async def go():
            return await self.sim.net.request(
                self.db.client_addr, ep,
                MoveShardRequest(begin=begin, dest_workers=list(dests)),
                TaskPriority.MOVE_KEYS, timeout=120.0,
            )

        reply = self._drive(go(), timeout=240.0)
        self._print(f"moved shard at {_fmt(begin) if begin else chr(39)*2}: "
                    f"new team {reply['team']}")

    def _exclude_cmd(self, addrs: List[str], exclude: bool) -> None:
        from ..server.masterserver import EXCLUDE_TOKEN, ExcludeServersRequest
        from ..sim.loop import TaskPriority

        if not addrs:
            raise ValueError("need at least one address")
        ep = self._find_master_ep(EXCLUDE_TOKEN)
        if ep is None:
            self._print("no master reachable")
            return

        async def go():
            return await self.sim.net.request(
                self.db.client_addr, ep,
                ExcludeServersRequest(addresses=list(addrs), exclude=exclude),
                TaskPriority.MOVE_KEYS, timeout=240.0,
            )

        reply = self._drive(go(), timeout=480.0)
        verb = "excluded" if exclude else "included"
        self._print(f"{verb}: now excluding {reply['excluded'] or 'nothing'}"
                    + (f"; moved shards {reply['moved']}" if reply.get("moved") else ""))

    def do_configure(self, args: List[str]) -> None:
        from ..server.management import REDUNDANCY_MODES, change_configuration

        if not args:
            raise ValueError("configure what?")
        mode = None
        counts = {}
        for tok in args:
            if tok in REDUNDANCY_MODES:
                mode = tok
            elif "=" in tok:
                k, v = tok.split("=", 1)
                counts[k] = int(v)
            else:
                raise ValueError(f"bad configure token {tok!r}")
        self._drive(change_configuration(self.db, mode=mode, **counts),
                    timeout=120.0)
        self._print("configuration committed (applies at the next recovery)")

    def do_exclude(self, args: List[str]) -> None:
        self._exclude_cmd(args, exclude=True)

    def do_include(self, args: List[str]) -> None:
        self._exclude_cmd(args, exclude=False)

    # -- loop -----------------------------------------------------------------
    def run_command(self, line: str) -> bool:
        """Returns False on exit. Errors print, never crash the shell."""
        try:
            parts = shlex.split(line)
        except ValueError as e:
            self._print(f"parse error: {e}")
            return True
        if not parts:
            return True
        cmd, args = parts[0].lower().replace("-", "_"), parts[1:]
        if cmd in ("exit", "quit"):
            return False
        if cmd == "help":
            self._print(HELP)
            return True
        fn = getattr(self, f"do_{cmd}", None)
        if fn is None:
            self._print(f"unknown command `{cmd}' (try help)")
            return True
        try:
            fn(args)
        except (ValueError, TypeError, IndexError):
            self._print(f"usage error (try help)")
        except error.FDBError as e:
            self._print(f"error: {e}")
        return True

    def repl(self, stream=sys.stdin) -> None:
        interactive = stream.isatty()
        while True:
            if interactive:
                print("fdb> ", end="", flush=True)
            line = stream.readline()
            if not line:
                break
            if not self.run_command(line.strip()):
                break


def main(argv=None) -> int:
    import argparse

    raw = list(sys.argv[1:] if argv is None else argv)
    if raw and raw[0].replace("-", "_") == "lint":
        # before argparse: lint owns its own flags (--json, --rules, ...)
        # and never needs a cluster
        cli = Cli.__new__(Cli)
        cli.out = sys.stdout
        return cli.do_lint(raw[1:])
    if raw and raw[0].replace("-", "_") == "bench_history":
        # same pre-argparse pass-through: the trend gate owns its flags
        # (--json, --threshold, --dir) and reads artifacts, not a cluster
        cli = Cli.__new__(Cli)
        cli.out = sys.stdout
        return cli.do_bench_history(raw[1:])
    if raw and raw[0].replace("-", "_") in ("explain", "blackbox",
                                            "recovery"):
        # pre-argparse pass-through: forensics owns its own flags
        # (--slo, --window) and reads journals/reports, never a cluster
        cli = Cli.__new__(Cli)
        cli.out = sys.stdout
        if raw[0].replace("-", "_") == "explain":
            cli.do_explain(raw[1:])
        elif raw[0].replace("-", "_") == "recovery":
            cli.do_recovery(raw[1:])
        else:
            cli.do_blackbox(raw[1:])
        return 0

    ap = argparse.ArgumentParser(description="cli over a simulated cluster")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("command", nargs="*", default=[],
                    help="run one command and exit (e.g. "
                         "`chaos-status reports.json`, `status`)")
    args = ap.parse_args(argv)
    cmd0 = args.command[0].replace("-", "_") if args.command else ""
    if cmd0 in ("chaos_status", "trace", "atlas") or (
            cmd0 in ("heat", "sched", "alerts", "incidents", "shards")
            and len(args.command) > 1
            and args.command[1].endswith(".json")):
        # no cluster needed: renders the hub / a report, trace or heat
        # artifact file / a live span-ring fetch over RPC / campaign
        # alert+incident timelines
        cli = Cli.__new__(Cli)
        cli.out = sys.stdout
        if cmd0 == "chaos_status":
            cli.do_chaos_status(args.command[1:])
        elif cmd0 == "atlas":
            cli.do_atlas(args.command[1:])
        elif cmd0 == "heat":
            cli.do_heat(args.command[1:])
        elif cmd0 == "sched":
            cli.do_sched(args.command[1:])
        elif cmd0 == "alerts":
            cli.do_alerts(args.command[1:])
        elif cmd0 == "incidents":
            cli.do_incidents(args.command[1:])
        elif cmd0 == "shards":
            cli.do_shards(args.command[1:])
        else:
            cli.do_trace(args.command[1:])
        return 0
    cluster = build_dynamic_cluster(seed=args.seed, cfg=DynamicClusterConfig())
    if args.command:
        # one-shot mode: boot, run the single command, exit
        cli = Cli(cluster)
        cli.sim.run(until=3.0)
        cli.run_command(shlex.join(args.command))
        return 0
    cli = Cli(cluster)
    cli.sim.run(until=3.0)   # let the cluster bootstrap
    print("connected to simulated cluster (seed %d); `help' for commands" % args.seed)
    cli.repl()
    return 0


if __name__ == "__main__":
    sys.exit(main())
