"""History-search floor micro-driver (docs/perf.md "History search modes").

Sweeps device ms/batch vs boundary-table occupancy `n` at a FIXED batch
shape, for both history-query strategies of `ops/conflict_kernel.py`:
`fused_sort` re-sorts the capacity-H table together with the batch every
step, so its per-batch device time carries a floor set by H regardless of
batch size; `bsearch` sorts only the batch rows and binary-searches the
already-sorted table, so its time tracks the batch. This sweep makes that
floor visible and drift-checkable: bench.py's `history_floor` section runs
it at the production capacity on the real chip, and `make bench-smoke`
(tools/bench_smoke.py) runs the same code at toy sizes on the CPU backend
with a zero-recompile assertion (real jax monitoring counters) for both
modes after warmup.

Methodology: the boundary table is synthesized directly at each target
occupancy (sorted distinct packed keys at version 0) and the driven
batches carry valid point READS only — the kernel's shapes are fixed, so
row validity does not change device cost, and a write-free gc=0 batch
leaves the table untouched: every timed step runs at exactly the target
`n`. Timing is the scan methodology of bench.py (one compiled lax.scan of
resolve_steps, device-resident operands, warm run first).

    JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.floor_bench
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import conflict_kernel as ck
from ..ops import keypack

#: CPU-sized default sweep shape: batch far under capacity so both the
#: auto rule and the floor gap are visible in seconds, not minutes
SMOKE_CFG = ck.KernelConfig(key_words=4, capacity=4096, max_txns=128,
                            max_point_reads=256, max_point_writes=256,
                            max_reads=32, max_writes=32)


def _table_state(cfg: ck.KernelConfig, n: int) -> Dict[str, jnp.ndarray]:
    """A boundary table holding exactly n sorted, distinct keys (version
    0) — zero-padded decimal keys are byte-ordered, and keypack preserves
    byte order, so the packed rows are already table-sorted."""
    hkeys = np.zeros((cfg.capacity, cfg.lanes), np.uint32)
    if n:
        hkeys[:n] = keypack.pack_keys(
            [b"fl/%08d" % i for i in range(n)], cfg.key_words)
    hvers = np.full((cfg.capacity,), int(ck.NEG_VERSION), np.int32)
    hvers[:n] = 0
    return {"hkeys": jnp.asarray(hkeys), "hvers": jnp.asarray(hvers),
            "n": jnp.asarray(n, jnp.int32)}


def _read_batch(cfg: ck.KernelConfig, rng: np.random.Generator,
                n: int) -> Dict[str, np.ndarray]:
    """One full batch of valid point reads over the table's own keys
    (snapshots above every stored version: nothing aborts, nothing is
    written, the table stays at occupancy n across every scanned step)."""
    K = cfg.lanes
    Rp, Wp, T = cfg.rp, cfg.wp, cfg.max_txns
    Rr, Wr = cfg.max_reads, cfg.max_writes
    rpb = np.zeros((Rp, K), np.uint32)
    rpb[:] = keypack.pack_keys(
        [b"fl/%08d" % i for i in rng.integers(0, max(1, n), size=Rp)],
        cfg.key_words)
    return {
        "rpb": rpb,
        "rp_snap": np.full((Rp,), 100, np.int32),
        "rp_txn": np.sort(rng.integers(0, T, size=Rp)).astype(np.int32),
        "rp_valid": np.ones((Rp,), bool),
        "rb": np.zeros((Rr, K), np.uint32),
        "re": np.zeros((Rr, K), np.uint32),
        "r_snap": np.zeros((Rr,), np.int32),
        "r_txn": np.zeros((Rr,), np.int32),
        "r_valid": np.zeros((Rr,), bool),
        "wpb": np.zeros((Wp, K), np.uint32),
        "wp_txn": np.zeros((Wp,), np.int32),
        "wp_valid": np.zeros((Wp,), bool),
        "wb": np.zeros((Wr, K), np.uint32),
        "we": np.zeros((Wr, K), np.uint32),
        "w_txn": np.zeros((Wr,), np.int32),
        "w_valid": np.zeros((Wr,), bool),
        "t_ok": np.ones((T,), bool),
        "t_too_old": np.zeros((T,), bool),
        "now": np.asarray(200, np.int32),
        "gc": np.asarray(0, np.int32),
    }


def _write_batch(cfg: ck.KernelConfig, rng: np.random.Generator,
                 n: int) -> Dict[str, np.ndarray]:
    """One full batch of valid point WRITES over the table's own keys —
    the apply-cost probe (docs/perf.md "Incremental history
    maintenance"). Re-writing existing keys keeps the boundary set
    stationary after the first apply folds in the point-write end rows,
    so a warm scan reaches steady occupancy and the timed scan measures
    maintenance cost at a fixed table size."""
    b = _read_batch(cfg, rng, n)
    Rp, Wp, T = cfg.rp, cfg.wp, cfg.max_txns
    b["rp_valid"] = np.zeros((Rp,), bool)
    b["wpb"] = keypack.pack_keys(
        [b"fl/%08d" % i for i in rng.integers(0, max(1, n), size=Wp)],
        cfg.key_words).astype(np.uint32)
    b["wp_txn"] = np.sort(rng.integers(0, T, size=Wp)).astype(np.int32)
    b["wp_valid"] = np.ones((Wp,), bool)
    return b


def run_apply_sweep(
    cfg: Optional[ck.KernelConfig] = None,
    *,
    occupancy_fracs: Sequence[float] = (0.25, 0.5, 0.75),
    scan_steps: int = 48,
    history_runs: int = 8,
    seed: int = 2028,
) -> Dict:
    """The `history_floor.apply` section (docs/perf.md "Incremental
    history maintenance"): device ms per WRITE batch vs table occupancy,
    monolithic vs tiered. The monolithic `apply_writes_and_gc` re-merges
    the capacity-H table with every batch, so its apply time carries the
    same H-shaped floor the fused query sort did; the tiered structure
    appends the batch as one sorted run and compacts every
    `history_runs` batches, so its amortized cost tracks the batch.
    Methodology: the MAINTENANCE phase (`apply_writes_and_gc`) is timed
    in isolation — the query phases cost the same under either
    structure (cross-structure parity is their contract), so timing the
    full step would bury the apply difference under the shared search
    machinery. The table is first brought to its steady boundary set
    (one fold admits the point-write end rows), the write positions are
    recomputed against that steady table, and the timed scan then
    replays the identical apply at stationary occupancy — asserted by
    comparing warm-end and timed-end row counts."""
    cfg = cfg or SMOKE_CFG
    rng = np.random.default_rng(seed)
    runs = []
    for structure in ("monolithic", "tiered"):
        scfg = dataclasses.replace(cfg, history_structure=structure,
                                   history_runs=history_runs)
        for frac in occupancy_fracs:
            n = max(1, int(frac * cfg.capacity))
            batch = jax.device_put(_write_batch(cfg, rng, n))
            committed = jnp.ones((cfg.max_txns,), bool)
            state = dict(ck.initial_state(scfg))
            state.update(_table_state(cfg, n))
            state = jax.device_put(state)
            # steady boundary set: fold the batch once, then recompute
            # the write positions against the folded table so the scan
            # replays a position-correct apply at fixed occupancy
            phases = jax.jit(
                lambda st, b, _cfg=scfg: ck.local_phases(_cfg, st, b)[2])
            one = jax.jit(
                lambda st, b, c, w, _cfg=scfg:
                ck.apply_writes_and_gc(_cfg, st, b, c, w)[0])
            state = one(state, batch, committed, phases(state, batch))
            wpos = phases(state, batch)

            def step(st, _, _cfg=scfg, _b=batch, _c=committed, _w=wpos):
                st2, _overflow, _reclaimed = ck.apply_writes_and_gc(
                    _cfg, st, _b, _c, _w)
                return st2, st2["n"]

            run = jax.jit(
                lambda st, _step=step: lax.scan(_step, st, jnp.arange(scan_steps)))
            runs.append((structure, frac, n, run, state))

    states, steady_n = {}, {}
    for structure, frac, n, run, state in runs:
        st, ns = run(state)
        steady_n[(structure, frac)] = int(np.asarray(ns)[-1])
        states[(structure, frac)] = st

    compiles = {"monolithic": 0, "tiered": 0}
    ms: Dict[tuple, float] = {}
    monitored = True
    for structure, frac, n, run, _state in runs:
        counter = _CompileCounter()
        t0 = time.perf_counter()
        st, ns = run(states[(structure, frac)])
        final_n = int(np.asarray(ns)[-1])
        ms[(structure, frac)] = (time.perf_counter() - t0) / scan_steps * 1e3
        assert final_n == steady_n[(structure, frac)], (
            f"{structure} occupancy not stationary: "
            f"{final_n} != {steady_n[(structure, frac)]}")
        seen = counter.close()
        if seen is None:
            monitored = False
        else:
            compiles[structure] += seen

    points = []
    for frac in occupancy_fracs:
        mono = ms[("monolithic", frac)]
        tier = ms[("tiered", frac)]
        points.append({
            "occupancy_frac": frac,
            "n": max(1, int(frac * cfg.capacity)),
            "monolithic_ms": round(mono, 4),
            "tiered_ms": round(tier, 4),
            "tiered_speedup": round(mono / tier, 3) if tier > 0 else None,
        })
    return {
        "batch_txns": cfg.max_txns,
        "capacity": cfg.capacity,
        "write_rows": cfg.wp,
        "history_runs": history_runs,
        "scan_steps": scan_steps,
        "points": points,
        "steady_state_compiles": compiles if monitored else None,
    }


class _CompileCounter:
    """Counts real backend compiles via jax monitoring events (the same
    counter tests/test_bucket_ladder.py pins tier-1 on); degrades to
    None when the private monitoring module moves."""

    def __init__(self) -> None:
        self.events = 0
        self._mon = None
        try:
            from jax._src import monitoring

            self._mon = monitoring
        except Exception:
            return
        self._cb = self._on_event
        self._mon.register_event_listener(self._cb)

    def _on_event(self, name, **kw):
        if "compil" in name:
            self.events += 1

    def close(self) -> Optional[int]:
        if self._mon is None:
            return None
        self._mon._unregister_event_listener_by_callback(self._cb)
        return self.events


def run_floor_sweep(
    cfg: Optional[ck.KernelConfig] = None,
    *,
    occupancy_fracs: Sequence[float] = (0.25, 0.5, 0.75),
    scan_steps: int = 128,
    seed: int = 2026,
) -> Dict:
    """The `history_floor` section: device ms/batch at each occupancy for
    both modes, plus the post-warmup steady-state compile count per mode
    (must be 0 — a timed run that still compiles is measuring the
    compiler)."""
    cfg = cfg or SMOKE_CFG
    rng = np.random.default_rng(seed)
    runs = []   # (mode, frac, n, jitted_run, device_state)
    for mode in ("fused_sort", "bsearch"):
        mcfg = dataclasses.replace(cfg, history_search=mode)
        for frac in occupancy_fracs:
            n = max(1, int(frac * cfg.capacity))
            batch = jax.device_put(_read_batch(cfg, rng, n))

            def step(st, _, _cfg=mcfg, _batch=batch):
                st, out = ck.resolve_step(_cfg, st, _batch)
                return st, out["n"]

            run = jax.jit(
                lambda st, _step=step: lax.scan(_step, st, jnp.arange(scan_steps)))
            runs.append((mode, frac, n, run, jax.device_put(_table_state(cfg, n))))

    # warm every program first (compile + first execution), THEN time under
    # the compile listener: any event in the timed phase is a retrace the
    # warmup was supposed to make impossible
    states = {}
    for mode, frac, n, run, state in runs:
        st, ns = run(state)
        np.asarray(ns)
        states[(mode, frac)] = st

    compiles = {"fused_sort": 0, "bsearch": 0}
    ms: Dict[tuple, float] = {}
    monitored = True
    for mode, frac, n, run, _state in runs:
        counter = _CompileCounter()
        t0 = time.perf_counter()
        st, ns = run(states[(mode, frac)])
        final_n = int(np.asarray(ns)[-1])
        ms[(mode, frac)] = (time.perf_counter() - t0) / scan_steps * 1e3
        assert final_n == n, f"occupancy drifted: {final_n} != {n}"
        seen = counter.close()
        if seen is None:
            monitored = False
        else:
            compiles[mode] += seen

    points = []
    for frac in occupancy_fracs:
        fused = ms[("fused_sort", frac)]
        bs = ms[("bsearch", frac)]
        points.append({
            "occupancy_frac": frac,
            "n": max(1, int(frac * cfg.capacity)),
            "fused_sort_ms": round(fused, 4),
            "bsearch_ms": round(bs, 4),
            "bsearch_speedup": round(fused / bs, 3) if bs > 0 else None,
        })
    return {
        "batch_txns": cfg.max_txns,
        "capacity": cfg.capacity,
        "auto_pick": ck.pick_history_search(cfg),
        "scan_steps": scan_steps,
        "points": points,
        #: post-warmup compiles per mode; None when the jax monitoring
        #: hook is unavailable (bench-smoke then fails its assertion
        #: loudly rather than passing vacuously)
        "steady_state_compiles": compiles if monitored else None,
    }


def run_loop_floor(
    cfg: Optional[ck.KernelConfig] = None,
    *,
    n_batches: int = 24,
    warm_batches: int = 4,
    depth: int = 2,
    pool: int = 512,
    seed: int = 2027,
) -> Dict:
    """The `loop_floor` section (docs/perf.md "Device-resident loop"):
    per-batch HOST wall time of the step-dispatch engine vs the
    device-resident loop engine at a FIXED batch shape, both driven
    through the wall-clock ResolverPipeline at `depth` over the IDENTICAL
    transaction stream. Step dispatch pays a per-batch launch + blocking
    force; the loop enqueues onto its device queue and drains abort
    bitmaps non-blockingly — the difference is the dispatch floor the
    tentpole removes. Verdict parity across the two engines is asserted
    into the result (the bench canary), alongside the loop's sync
    accounting (blocking_syncs MUST be 0)."""
    from ..ops.device_loop import DeviceLoopEngine
    from ..ops.host_engine import JaxConflictEngine
    from ..pipeline.resolver_pipeline import ResolverPipeline
    from .ladder_bench import make_point_txns

    cfg = cfg or SMOKE_CFG
    rng = np.random.default_rng(seed)
    stream = []
    version = 1_000
    for _ in range(warm_batches + n_batches):
        txns = make_point_txns(cfg.max_txns, pool, rng, version)
        version += max(64, cfg.max_txns)
        stream.append((txns, version, max(0, version - 100_000)))

    def drive(engine):
        engine.warmup()
        pipe = ResolverPipeline(engine, depth=depth)
        verdicts = []
        for s in stream[:warm_batches]:
            verdicts.append([int(x) for x in pipe.submit(*s).result()])
        t0 = time.perf_counter()
        handles = [pipe.submit(*s) for s in stream[warm_batches:]]
        verdicts.extend([int(x) for x in h.result()] for h in handles)
        wall_ms = (time.perf_counter() - t0) * 1e3
        return wall_ms / n_batches, verdicts

    step_ms, step_verdicts = drive(JaxConflictEngine(cfg))
    loop = DeviceLoopEngine(cfg)
    loop_ms, loop_verdicts = drive(loop)
    return {
        "batch_txns": cfg.max_txns,
        "depth": depth,
        "n_batches": n_batches,
        "step_host_ms_per_batch": round(step_ms, 4),
        "loop_host_ms_per_batch": round(loop_ms, 4),
        "loop_speedup": round(step_ms / loop_ms, 3) if loop_ms > 0 else None,
        #: measured host shares of one loop batch — bench.py injects these
        #: as the sim service's queue_enqueue_ms / result_drain_ms so the
        #: loop-mode latency attribution carries real figures
        "loop_enqueue_ms_per_batch": round(
            loop.loop_stats["enqueue_ms"] / max(1, loop.loop_stats["units"]), 4),
        "loop_decode_ms_per_batch": round(
            loop.loop_stats["decode_ms"] / max(1, loop.loop_stats["units"]), 4),
        #: the bench canary: loop and step verdict streams bit-identical
        "parity_ok": step_verdicts == loop_verdicts,
        "loop_stats": dict(loop.loop_stats),
    }


def main() -> int:
    out = run_floor_sweep(scan_steps=48)
    print(json.dumps({"metric": "history_floor", **out}))
    comp = out["steady_state_compiles"]
    if comp and any(comp.values()):
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
