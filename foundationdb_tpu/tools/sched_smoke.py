"""Conflict-scheduler CI smoke (`make sched-smoke`, CPU backend, seconds).

Four checks, each loud on failure (docs/scheduling.md):

  1. ABORT FRACTION DROPS ON A PLANTED HOT-KEY WORKLOAD — the same
     contended stream (small hot pool, stale snapshots, pre-aborts
     retried at a refreshed snapshot like the client contract) must
     serve a materially lower abort fraction with the scheduler ON than
     with it off, at an equal-or-better commit count.
  2. PARITY CANARY — the scheduled arm's dispatched-batch journal
     replays bit-for-bit through a CLEAN serial oracle: scheduling
     changes admission order, never resolution.
  3. PROMETHEUS EXPOSITION PARSES — the hub text now carries `sched.*`
     series; the `fdbtpu_sched` family must be present and the whole
     exposition must pass the strict PR 8 line parser (heat_smoke's).
  4. DISABLED PATH IS INERT — `enabled=False` selects FIFO slices,
     touches no predictor state, registers no telemetry series.

    JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.sched_smoke
"""
from __future__ import annotations

import sys
import time

from ..core import telemetry
from ..core.rng import DeterministicRandom
from ..core.types import CommitTransaction, KeyRange, TransactionCommitResult
from ..ops.oracle import OracleConflictEngine
from ..pipeline.scheduler import ConflictScheduler, SchedConfig
from .heat_smoke import strict_parse_prometheus

#: the planted contention pool: sized so hot-writer arrivals (~half the
#: stream) stay within one lane head per key per tick — contention the
#: scheduler can actually schedule around, not structural oversubscription
HOT_KEYS = 8
COLD_KEYS = 512
BATCHES = 120
CAP = 16
COMMITTED = int(TransactionCommitResult.COMMITTED)


def _txn(snap: int, key: bytes, write: bool) -> CommitTransaction:
    t = CommitTransaction(read_snapshot=int(snap))
    t.read_conflict_ranges.append(KeyRange(key, key + b"\x00"))
    if write:
        t.write_conflict_ranges.append(KeyRange(key, key + b"\x00"))
    return t


def _arrivals(rng, version: int, n: int = 12):
    """Hot read-modify-writes (70%) + cold traffic, snapshots up to 30
    versions stale — the doom rule's fuel."""
    out = []
    for _ in range(n):
        snap = version - rng.random_int(0, 30)
        if rng.random01() < 0.5:
            out.append(_txn(snap, b"hot/%02d" % rng.random_int(0, HOT_KEYS),
                            write=True))
        else:
            out.append(_txn(snap,
                            b"cold/%04d" % rng.random_int(0, COLD_KEYS),
                            write=rng.random01() < 0.5))
    return out


def _run_arm(sched_on: bool, seed: int = 17):
    """One arm of the A/B: the contended stream through scheduler +
    serial oracle, pre-aborts retried at a refreshed snapshot. Returns
    (committed, conflicted, preaborts, journal, scheduler)."""
    rng = DeterministicRandom(seed)
    cfg = SchedConfig.from_knobs()
    cfg.enabled = sched_on
    cfg.probe_interval = 8
    s = ConflictScheduler(cfg, name="smoke_on" if sched_on else "smoke")
    engine = OracleConflictEngine()
    committed = conflicted = preaborts = 0
    journal, pending, version = [], [], 1000
    for _b in range(BATCHES):
        version += 8
        pending.extend(_arrivals(rng, version))
        plan = s.select(pending, CAP)
        pending = plan.remaining
        preaborts += len(plan.preaborts)
        for txn, _rng in plan.preaborts:
            # the client contract: refresh the read version and retry
            retry = CommitTransaction(read_snapshot=version)
            retry.read_conflict_ranges = list(txn.read_conflict_ranges)
            retry.write_conflict_ranges = list(txn.write_conflict_ranges)
            pending.append(retry)
        batch = plan.dispatch
        if not batch:
            continue
        verdicts = [int(v) for v in engine.resolve(batch, version, 0)]
        journal.append((version, tuple(batch), 0, tuple(verdicts)))
        s.observe_batch(batch, verdicts, version)
        committed += sum(1 for v in verdicts if v == COMMITTED)
        conflicted += sum(1 for v in verdicts if v != COMMITTED)
    pending.extend(s.flush())
    if pending:
        version += 8
        batch = pending[:CAP]
        verdicts = [int(v) for v in engine.resolve(batch, version, 0)]
        journal.append((version, tuple(batch), 0, tuple(verdicts)))
        committed += sum(1 for v in verdicts if v == COMMITTED)
        conflicted += sum(1 for v in verdicts if v != COMMITTED)
    return committed, conflicted, preaborts, journal, s


def check_abort_reduction():
    c_off, x_off, _p, _j, _s = _run_arm(False)
    c_on, x_on, preaborts, journal, sched = _run_arm(True)
    frac_off = x_off / max(c_off + x_off, 1)
    frac_on = x_on / max(c_on + x_on, 1)
    assert preaborts > 0, "scheduler ON never pre-aborted on a hot stream"
    assert sched.counters["laned"] > 0, "no hot writer was ever laned"
    assert frac_on < frac_off * 0.7, (
        f"abort_frac did not drop: off={frac_off:.4f} on={frac_on:.4f}")
    assert c_on >= c_off, (
        f"scheduler ON served fewer commits: {c_on} < {c_off}")
    print(f"  abort reduction: off {frac_off:.4f} -> on {frac_on:.4f} "
          f"({preaborts} pre-aborts, commits {c_off} -> {c_on})")
    return journal, sched


def check_parity(journal) -> None:
    clean = OracleConflictEngine()
    for version, txns, oldest, verdicts in journal:
        want = [int(v) for v in clean.resolve(list(txns), version, oldest)]
        assert want == list(verdicts), (
            f"scheduled-order replay diverged at v{version}")
    print(f"  parity: {len(journal)} scheduled batches replay "
          "bit-for-bit through a clean oracle")


def check_prometheus(sched) -> None:
    hub = telemetry.hub()
    hub.sync()
    text = hub.prometheus_text()
    n = strict_parse_prometheus(text)
    assert "# TYPE fdbtpu_sched gauge" in text, "no sched family exposed"
    # the family prefix is the metric name; the series label carries the
    # scheduler label + counter (e.g. series="smoke_on.preaborts")
    assert f'series="{sched.label}.preaborts"' in text, (
        "\n".join(ln for ln in text.splitlines() if "sched" in ln)[:400])
    print(f"  prometheus: {n} samples parse strictly, sched family present")


def check_disabled_path() -> None:
    telemetry.reset()
    s = ConflictScheduler(SchedConfig(enabled=False))
    assert s.label is None, "disabled scheduler registered telemetry"
    pending = [_txn(100, b"k%d" % i, write=True) for i in range(6)]
    plan = s.select(pending, 4)
    assert plan.dispatch == pending[:4] and plan.remaining == pending[4:]
    assert not plan.preaborts
    assert all(v == 0 for v in s.counters.values())
    assert s.predictor.scores == {} and not s.lanes
    telemetry.hub().sync()
    assert not any(name.startswith("sched.")
                   for name in telemetry.hub().tdmetrics.metrics), \
        "sched series synced with the scheduler disabled"
    print("  disabled path: FIFO passthrough, no state, no hub series")


def main() -> int:
    t0 = time.perf_counter()
    telemetry.reset()
    print("sched-smoke (docs/scheduling.md):")
    journal, sched = check_abort_reduction()
    check_parity(journal)
    check_prometheus(sched)
    check_disabled_path()
    print(f"sched-smoke OK in {time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
