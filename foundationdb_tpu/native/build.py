"""Build + load the native components (cc -O2 -shared, cached).

pybind11 is not available in this environment, so the binding is plain
ctypes over a C ABI — the same pattern works for any future native piece
(DiskQueue frame scanning, wire codecs). The build is lazy, cached next to
the source, and every failure path returns None so callers fall back to
their Python implementations.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fastpack.c")
_SO = os.path.join(_DIR, "_fastpack.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-fPIC", "-shared", "-o", _SO, _SRC],
                capture_output=True, timeout=60,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load_fastpack() -> Optional[ctypes.CDLL]:
    """The fastpack library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        lib = ctypes.CDLL(_SO)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.pack_keys.restype = ctypes.c_int
        lib.pack_keys.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64, u32p,
        ]
        lib.conflict_counts.restype = ctypes.c_int
        lib.conflict_counts.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64, i32p, i32p,
        ]
        lib.build_point_rows.restype = None
        lib.build_point_rows.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            u32p, i32p, u32p, i32p, i64p,
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib
