"""Build + load the native components (cc -O2 -shared, cached).

pybind11 is not available in this environment, so the binding is plain
ctypes over a C ABI — the same pattern works for any future native piece
(DiskQueue frame scanning, wire codecs). The build is lazy, cached next to
the source, and every failure path returns None so callers fall back to
their Python implementations.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fastpack.c")
_SO = os.path.join(_DIR, "_fastpack.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-fPIC", "-shared", "-o", _SO, _SRC],
                capture_output=True, timeout=60,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


_CPP_SRC = os.path.join(_DIR, "conflict_engine.cpp")
_CPP_SO = os.path.join(_DIR, "_conflict_engine.so")
_cpp_lib: Optional[ctypes.CDLL] = None
_cpp_tried = False


def _build_cpp() -> bool:
    for cxx in ("g++", "c++", "clang++"):
        try:
            r = subprocess.run(
                [cxx, "-O2", "-std=c++17", "-fPIC", "-shared",
                 "-o", _CPP_SO, _CPP_SRC],
                capture_output=True, timeout=120,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load_conflict_engine() -> Optional[ctypes.CDLL]:
    """The native C++ ConflictSet engine; None if no C++ toolchain."""
    global _cpp_lib, _cpp_tried
    if _cpp_lib is not None or _cpp_tried:
        return _cpp_lib
    _cpp_tried = True
    try:
        if (not os.path.exists(_CPP_SO)
                or os.path.getmtime(_CPP_SO) < os.path.getmtime(_CPP_SRC)):
            if not _build_cpp():
                return None
        lib = ctypes.CDLL(_CPP_SO)
        i64 = ctypes.c_int64
        i64p = ctypes.POINTER(i64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.cse_new.restype = ctypes.c_void_p
        lib.cse_new.argtypes = [i64]
        lib.cse_free.restype = None
        lib.cse_free.argtypes = [ctypes.c_void_p]
        lib.cse_clear.restype = None
        lib.cse_clear.argtypes = [ctypes.c_void_p, i64]
        lib.cse_boundary_count.restype = i64
        lib.cse_boundary_count.argtypes = [ctypes.c_void_p]
        lib.cse_resolve.restype = ctypes.c_int
        lib.cse_resolve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, i64p, ctypes.c_int,
            i64p, i64, i64, u8p,
        ]
        _cpp_lib = lib
    except OSError:
        _cpp_lib = None
    return _cpp_lib


def load_fastpack() -> Optional[ctypes.CDLL]:
    """The fastpack library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        lib = ctypes.CDLL(_SO)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.pack_keys.restype = ctypes.c_int
        lib.pack_keys.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64, u32p,
        ]
        lib.conflict_counts.restype = ctypes.c_int
        lib.conflict_counts.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64, i32p, i32p,
        ]
        lib.build_point_rows.restype = None
        lib.build_point_rows.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            u32p, i32p, u32p, i32p, i64p,
        ]
        lib.conflict_counts_sharded.restype = ctypes.c_int
        lib.conflict_counts_sharded.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, i64p, ctypes.c_int64, i32p, i32p,
        ]
        lib.build_point_rows_sharded.restype = None
        lib.build_point_rows_sharded.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            u32p, i32p, u32p, i32p, i64p,
        ]
        _lib = lib
    except (OSError, AttributeError):
        _lib = None
    return _lib
