"""Native (C) components, loaded via ctypes with Python fallbacks.

The reference's host data plane is C++; this package holds the analogous
native pieces. Everything here is OPTIONAL at runtime: importers fall back
to the numpy implementations when the shared object is missing or the
toolchain is absent, so no environment ever fails to run.
"""
from .build import load_fastpack

__all__ = ["load_fastpack"]
