/* Native key packer: the resolver's host hot path.
 *
 * The analog of the reference's C++ host data plane (its resolver packs
 * and sorts keys in native code; SkipList.cpp operates on raw bytes). One
 * call packs N variable-length keys into fixed-width big-endian uint32
 * words + a length lane, the exact layout ops/keypack.py produces. The
 * Python caller concatenates the key bytes and passes offsets, so the
 * native side is a single tight loop with no allocator traffic.
 *
 * Built by foundationdb_tpu/native/build.py with the toolchain cc; loaded
 * through ctypes. keypack falls back to the vectorized numpy path when the
 * shared object is unavailable, so the framework runs everywhere and runs
 * FASTER where a compiler exists.
 */
#include <stdint.h>
#include <string.h>

/* keys: concatenated key bytes; offs[i]..offs[i+1]: key i's byte range.
 * out: n rows of (key_words + 1) uint32: big-endian words, then length.
 * Returns 0, or 1 if any key exceeds 4*key_words bytes (caller raises). */
int pack_keys(const uint8_t *keys, const int64_t *offs, int64_t n,
              int64_t key_words, uint32_t *out) {
    const int64_t kb = 4 * key_words;
    const int64_t stride = key_words + 1;
    for (int64_t i = 0; i < n; i++) {
        const int64_t len = offs[i + 1] - offs[i];
        if (len > kb) {
            return 1;
        }
        const uint8_t *k = keys + offs[i];
        uint32_t *row = out + i * stride;
        int64_t full = len / 4;
        for (int64_t w = 0; w < full; w++) {
            row[w] = ((uint32_t)k[4 * w] << 24) | ((uint32_t)k[4 * w + 1] << 16)
                   | ((uint32_t)k[4 * w + 2] << 8) | (uint32_t)k[4 * w + 3];
        }
        for (int64_t w = full; w < key_words; w++) {
            uint32_t v = 0;
            for (int64_t b = 0; b < 4; b++) {
                int64_t idx = 4 * w + b;
                v = (v << 8) | (idx < len ? k[idx] : 0);
            }
            row[w] = v;
        }
        row[key_words] = (uint32_t)len;
    }
    return 0;
}
