/* Native key packer: the resolver's host hot path.
 *
 * The analog of the reference's C++ host data plane (its resolver packs
 * and sorts keys in native code; SkipList.cpp operates on raw bytes). One
 * call packs N variable-length keys into fixed-width big-endian uint32
 * words + a length lane, the exact layout ops/keypack.py produces. The
 * Python caller concatenates the key bytes and passes offsets, so the
 * native side is a single tight loop with no allocator traffic.
 *
 * Built by foundationdb_tpu/native/build.py with the toolchain cc; loaded
 * through ctypes. keypack falls back to the vectorized numpy path when the
 * shared object is unavailable, so the framework runs everywhere and runs
 * FASTER where a compiler exists.
 */
#include <stdint.h>
#include <string.h>

/* Pack one key (len <= 4*key_words) into big-endian uint32 words + length. */
static inline void pack_one(const uint8_t *k, int64_t len, int64_t key_words,
                            uint32_t *row) {
    int64_t full = len / 4;
    for (int64_t w = 0; w < full; w++) {
        row[w] = ((uint32_t)k[4 * w] << 24) | ((uint32_t)k[4 * w + 1] << 16)
               | ((uint32_t)k[4 * w + 2] << 8) | (uint32_t)k[4 * w + 3];
    }
    for (int64_t w = full; w < key_words; w++) {
        uint32_t v = 0;
        for (int64_t b = 0; b < 4; b++) {
            int64_t idx = 4 * w + b;
            v = (v << 8) | (idx < len ? k[idx] : 0);
        }
        row[w] = v;
    }
    row[key_words] = (uint32_t)len;
}

/* keys: concatenated key bytes; offs[i]..offs[i+1]: key i's byte range.
 * out: n rows of (key_words + 1) uint32: big-endian words, then length.
 * Returns 0, or 1 if any key exceeds 4*key_words bytes (caller raises). */
int pack_keys(const uint8_t *keys, const int64_t *offs, int64_t n,
              int64_t key_words, uint32_t *out) {
    const int64_t kb = 4 * key_words;
    const int64_t stride = key_words + 1;
    for (int64_t i = 0; i < n; i++) {
        const int64_t len = offs[i + 1] - offs[i];
        if (len > kb) {
            return 1;
        }
        pack_one(keys + offs[i], len, key_words, out + i * stride);
    }
    return 0;
}

/* ---- Columnar conflict-wire parsing (core/wire.py conflict_wire) ----
 *
 * The resolver's host hot path: transactions arrive as concatenated
 * little-endian wire blocks (blob + per-txn offsets) and become the
 * kernel's fixed-shape row arrays in one native pass, the analog of the
 * reference resolver's C++ walk over its serialized batch request
 * (fdbserver/Resolver.actor.cpp).
 */

/* Pass 1: per-txn POINT read/write counts. Returns 0 if every range in
 * every txn is a short-key POINT row (the fast-path precondition), else 1
 * (caller falls back to the general Python router, which handles ranges,
 * empties and the long-key tier). */
int conflict_counts(const uint8_t *blob, const int64_t *offs, int64_t ntxn,
                    int64_t max_key_bytes,
                    int32_t *rp_cnt, int32_t *wp_cnt) {
    for (int64_t t = 0; t < ntxn; t++) {
        const uint8_t *p = blob + offs[t];
        const uint8_t *end = blob + offs[t + 1];
        if (end - p < 8) return 1;
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        for (uint32_t i = 0; i < nr + nw; i++) {
            if (end - p < 4) return 1;
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            uint32_t kind = hdr >> 30;
            int64_t blen = hdr & 0x3fffffff;
            if (kind != 0 || blen > max_key_bytes) return 1;
            p += blen;
            if (p > end) return 1;
        }
        rp_cnt[t] = (int32_t)nr;
        wp_cnt[t] = (int32_t)nw;
    }
    return 0;
}

/* Pass 2: pack POINT rows of txns [t0, t1) into preallocated padded row
 * arrays (rpb/wpb: rows of key_words+1 uint32; rp_txn/wp_txn: owning txn
 * index relative to t0). skip[t] != 0 (too-old txns) contributes no rows.
 * Caller guarantees capacity (chunking) and pointness (pass 1).
 * out_n[0]/out_n[1] receive the row counts. */
void build_point_rows(const uint8_t *blob, const int64_t *offs,
                      int64_t t0, int64_t t1, const uint8_t *skip,
                      int64_t key_words,
                      uint32_t *rpb, int32_t *rp_txn,
                      uint32_t *wpb, int32_t *wp_txn,
                      int64_t *out_n) {
    const int64_t stride = key_words + 1;
    int64_t nr_out = 0, nw_out = 0;
    for (int64_t t = t0; t < t1; t++) {
        if (skip[t]) continue;
        const uint8_t *p = blob + offs[t];
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        const int32_t ti = (int32_t)(t - t0);
        for (uint32_t i = 0; i < nr + nw; i++) {
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            int64_t blen = hdr & 0x3fffffff;
            if (i < nr) {
                pack_one(p, blen, key_words, rpb + nr_out * stride);
                rp_txn[nr_out++] = ti;
            } else {
                pack_one(p, blen, key_words, wpb + nw_out * stride);
                wp_txn[nw_out++] = ti;
            }
            p += blen;
        }
    }
    out_n[0] = nr_out;
    out_n[1] = nw_out;
}
