/* Native key packer: the resolver's host hot path.
 *
 * The analog of the reference's C++ host data plane (its resolver packs
 * and sorts keys in native code; SkipList.cpp operates on raw bytes). One
 * call packs N variable-length keys into fixed-width big-endian uint32
 * words + a length lane, the exact layout ops/keypack.py produces. The
 * Python caller concatenates the key bytes and passes offsets, so the
 * native side is a single tight loop with no allocator traffic.
 *
 * Built by foundationdb_tpu/native/build.py with the toolchain cc; loaded
 * through ctypes. keypack falls back to the vectorized numpy path when the
 * shared object is unavailable, so the framework runs everywhere and runs
 * FASTER where a compiler exists.
 */
#include <stdint.h>
#include <string.h>

/* Pack one key (len <= 4*key_words) into big-endian uint32 words + length. */
static inline void pack_one(const uint8_t *k, int64_t len, int64_t key_words,
                            uint32_t *row) {
    int64_t full = len / 4;
    for (int64_t w = 0; w < full; w++) {
        row[w] = ((uint32_t)k[4 * w] << 24) | ((uint32_t)k[4 * w + 1] << 16)
               | ((uint32_t)k[4 * w + 2] << 8) | (uint32_t)k[4 * w + 3];
    }
    for (int64_t w = full; w < key_words; w++) {
        uint32_t v = 0;
        for (int64_t b = 0; b < 4; b++) {
            int64_t idx = 4 * w + b;
            v = (v << 8) | (idx < len ? k[idx] : 0);
        }
        row[w] = v;
    }
    row[key_words] = (uint32_t)len;
}

/* keys: concatenated key bytes; offs[i]..offs[i+1]: key i's byte range.
 * out: n rows of (key_words + 1) uint32: big-endian words, then length.
 * Returns 0, or 1 if any key exceeds 4*key_words bytes (caller raises). */
int pack_keys(const uint8_t *keys, const int64_t *offs, int64_t n,
              int64_t key_words, uint32_t *out) {
    const int64_t kb = 4 * key_words;
    const int64_t stride = key_words + 1;
    for (int64_t i = 0; i < n; i++) {
        const int64_t len = offs[i + 1] - offs[i];
        if (len > kb) {
            return 1;
        }
        pack_one(keys + offs[i], len, key_words, out + i * stride);
    }
    return 0;
}

/* ---- Columnar conflict-wire parsing (core/wire.py conflict_wire) ----
 *
 * The resolver's host hot path: transactions arrive as concatenated
 * little-endian wire blocks (blob + per-txn offsets) and become the
 * kernel's fixed-shape row arrays in one native pass, the analog of the
 * reference resolver's C++ walk over its serialized batch request
 * (fdbserver/Resolver.actor.cpp).
 */

/* Pass 1: per-txn POINT read/write counts. Returns 0 if every range in
 * every txn is a short-key POINT row (the fast-path precondition), else 1
 * (caller falls back to the general Python router, which handles ranges,
 * empties and the long-key tier). */
int conflict_counts(const uint8_t *blob, const int64_t *offs, int64_t ntxn,
                    int64_t max_key_bytes,
                    int32_t *rp_cnt, int32_t *wp_cnt) {
    for (int64_t t = 0; t < ntxn; t++) {
        const uint8_t *p = blob + offs[t];
        const uint8_t *end = blob + offs[t + 1];
        if (end - p < 8) return 1;
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        for (uint32_t i = 0; i < nr + nw; i++) {
            if (end - p < 4) return 1;
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            uint32_t kind = hdr >> 30;
            int64_t blen = hdr & 0x3fffffff;
            if (kind != 0 || blen > max_key_bytes) return 1;
            p += blen;
            if (p > end) return 1;
        }
        rp_cnt[t] = (int32_t)nr;
        wp_cnt[t] = (int32_t)nw;
    }
    return 0;
}

/* ---- Sharded columnar parsing (S > 1 resolvers) ----
 *
 * Shard split keys arrive as concatenated bytes + offsets (sorted). A point
 * key's shard is the number of split keys <= it (bisect_right over
 * [b""] ++ splits, minus one) — a point range never straddles a split, so
 * each point row lands on exactly one shard (host_engine.KeyShardMap).
 */

static inline int key_cmp(const uint8_t *a, int64_t alen,
                          const uint8_t *b, int64_t blen) {
    int64_t m = alen < blen ? alen : blen;
    int c = memcmp(a, b, (size_t)m);
    if (c) return c;
    return (alen > blen) - (alen < blen);
}

static inline int64_t shard_of(const uint8_t *k, int64_t klen,
                               const uint8_t *splits, const int64_t *soffs,
                               int64_t n_splits) {
    /* count of splits <= k, by binary search for upper bound */
    int64_t lo = 0, hi = n_splits;
    while (lo < hi) {
        int64_t mid = (lo + hi) / 2;
        if (key_cmp(splits + soffs[mid], soffs[mid + 1] - soffs[mid], k, klen) <= 0)
            lo = mid + 1;
        else
            hi = mid;
    }
    return lo;
}

/* Pass 1, sharded: per-(txn, shard) POINT row counts
 * (rp_cnt/wp_cnt: ntxn * S, row-major by txn). Same validity contract as
 * conflict_counts. S = n_splits + 1. */
int conflict_counts_sharded(const uint8_t *blob, const int64_t *offs,
                            int64_t ntxn, int64_t max_key_bytes,
                            const uint8_t *splits, const int64_t *soffs,
                            int64_t n_splits,
                            int32_t *rp_cnt, int32_t *wp_cnt) {
    const int64_t S = n_splits + 1;
    for (int64_t t = 0; t < ntxn; t++) {
        const uint8_t *p = blob + offs[t];
        const uint8_t *end = blob + offs[t + 1];
        if (end - p < 8) return 1;
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        for (uint32_t i = 0; i < nr + nw; i++) {
            if (end - p < 4) return 1;
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            uint32_t kind = hdr >> 30;
            int64_t blen = hdr & 0x3fffffff;
            if (kind != 0 || blen > max_key_bytes) return 1;
            if (p + blen > end) return 1;
            int64_t s = shard_of(p, blen, splits, soffs, n_splits);
            if (i < nr) rp_cnt[t * S + s]++;
            else        wp_cnt[t * S + s]++;
            p += blen;
        }
    }
    return 0;
}

/* Pass 2, sharded: emit POINT rows of txns [t0, t1) into per-shard padded
 * regions. rpb has S regions of rp_cap rows (stride key_words+1 uint32)
 * starting at rpb + s*rp_cap*stride; likewise wpb/wp_cap. rp_txn/wp_txn
 * regions hold txn indices relative to t0. skip[t] != 0 contributes no
 * rows. out_n[2*s] / out_n[2*s+1] receive shard s's read/write row counts.
 * Rows stay txn-ascending inside each shard region (the kernel's segment
 * reduce relies on it). */
void build_point_rows_sharded(const uint8_t *blob, const int64_t *offs,
                              int64_t t0, int64_t t1, const uint8_t *skip,
                              int64_t key_words,
                              const uint8_t *splits, const int64_t *soffs,
                              int64_t n_splits,
                              int64_t rp_cap, int64_t wp_cap,
                              uint32_t *rpb, int32_t *rp_txn,
                              uint32_t *wpb, int32_t *wp_txn,
                              int64_t *out_n) {
    const int64_t S = n_splits + 1;
    const int64_t stride = key_words + 1;
    for (int64_t s = 0; s < 2 * S; s++) out_n[s] = 0;
    for (int64_t t = t0; t < t1; t++) {
        if (skip[t]) continue;
        const uint8_t *p = blob + offs[t];
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        const int32_t ti = (int32_t)(t - t0);
        for (uint32_t i = 0; i < nr + nw; i++) {
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            int64_t blen = hdr & 0x3fffffff;
            int64_t s = shard_of(p, blen, splits, soffs, n_splits);
            if (i < nr) {
                int64_t r = out_n[2 * s]++;
                pack_one(p, blen, key_words, rpb + (s * rp_cap + r) * stride);
                rp_txn[s * rp_cap + r] = ti;
            } else {
                int64_t w = out_n[2 * s + 1]++;
                pack_one(p, blen, key_words, wpb + (s * wp_cap + w) * stride);
                wp_txn[s * wp_cap + w] = ti;
            }
            p += blen;
        }
    }
}

/* Pass 2: pack POINT rows of txns [t0, t1) into preallocated padded row
 * arrays (rpb/wpb: rows of key_words+1 uint32; rp_txn/wp_txn: owning txn
 * index relative to t0). skip[t] != 0 (too-old txns) contributes no rows.
 * Caller guarantees capacity (chunking) and pointness (pass 1).
 * out_n[0]/out_n[1] receive the row counts. */
void build_point_rows(const uint8_t *blob, const int64_t *offs,
                      int64_t t0, int64_t t1, const uint8_t *skip,
                      int64_t key_words,
                      uint32_t *rpb, int32_t *rp_txn,
                      uint32_t *wpb, int32_t *wp_txn,
                      int64_t *out_n) {
    const int64_t stride = key_words + 1;
    int64_t nr_out = 0, nw_out = 0;
    for (int64_t t = t0; t < t1; t++) {
        if (skip[t]) continue;
        const uint8_t *p = blob + offs[t];
        uint32_t nr, nw;
        memcpy(&nr, p, 4);
        memcpy(&nw, p + 4, 4);
        p += 8;
        const int32_t ti = (int32_t)(t - t0);
        for (uint32_t i = 0; i < nr + nw; i++) {
            uint32_t hdr;
            memcpy(&hdr, p, 4);
            p += 4;
            int64_t blen = hdr & 0x3fffffff;
            if (i < nr) {
                pack_one(p, blen, key_words, rpb + nr_out * stride);
                rp_txn[nr_out++] = ti;
            } else {
                pack_one(p, blen, key_words, wpb + nw_out * stride);
                wp_txn[nw_out++] = ti;
            }
            p += blen;
        }
    }
    out_n[0] = nr_out;
    out_n[1] = nw_out;
}
