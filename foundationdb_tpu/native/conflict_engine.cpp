// Native C++ ConflictSet engine — the CPU-resolver half of the framework's
// runtime (the role fdbserver/SkipList.cpp plays in the reference; here an
// ordered boundary map, which is the skip list's observable state, see
// ops/oracle.py for the shared logical model this must match bit-for-bit).
//
// Exposed through a plain C ABI and loaded via ctypes (native/build.py) —
// pybind11 is not in this environment. Batches arrive in the columnar
// conflict-wire format (core/wire.py conflict_wire): the same bytes the
// client serialized, parsed once here with zero Python-object overhead.
//
//   block  := [u32 n_read][u32 n_write] range*
//   range  := [u32 hdr = len | kind<<30][len bytes]            kind 0: point
//           | [u32 hdr][len bytes][u32 elen][elen bytes]       kind 1: range
//           | [u32 hdr][len bytes]                             kind 2: empty
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

using Key = std::string;

constexpr uint32_t kLenMask = (1u << 30) - 1;
constexpr int64_t kNegInf = INT64_MIN / 2;

enum Status : uint8_t { kConflict = 0, kTooOld = 1, kCommitted = 2 };

// Piecewise-constant map key -> version; first boundary is always "".
// (VersionIntervalMap in ops/oracle.py; the reference skip list's
// observable state, SkipList.cpp:350-:665.)
struct IntervalMap {
  std::map<Key, int64_t> m;

  explicit IntervalMap(int64_t v) { m.emplace(Key(), v); }

  int64_t version_at(const Key& k) const {
    auto it = m.upper_bound(k);
    --it;
    return it->second;
  }

  int64_t version_strictly_below(const Key& k) const {
    auto it = m.lower_bound(k);          // first >= k
    if (it != m.begin()) --it;           // last < k (or the "" boundary)
    return it->second;
  }

  int64_t range_max(const Key& b, const Key& e) const {
    auto lo = m.upper_bound(b);
    --lo;                                // interval containing b
    auto hi = m.lower_bound(e);          // first boundary >= e
    int64_t mx = kNegInf;
    for (auto it = lo; it != hi; ++it)
      if (it->second > mx) mx = it->second;
    return mx;
  }

  void write(const Key& b, const Key& e, int64_t v) {
    if (b >= e) return;
    int64_t v_end = version_at(e);
    auto lo = m.lower_bound(b);
    auto hi = m.lower_bound(e);
    m.erase(lo, hi);
    m[b] = v;
    if (m.find(e) == m.end()) m.emplace(e, v_end);
  }

  // Keep rule from removeBefore (SkipList.cpp:686-698): a boundary
  // survives iff its version or its ORIGINAL predecessor's is >= oldest.
  void gc(int64_t oldest) {
    auto it = m.begin();
    int64_t prev = it->second;
    ++it;
    while (it != m.end()) {
      int64_t cur = it->second;
      if (cur >= oldest || prev >= oldest) {
        ++it;
      } else {
        it = m.erase(it);
      }
      prev = cur;
    }
  }
};

struct Engine {
  IntervalMap map;
  int64_t oldest_version = 0;

  explicit Engine(int64_t v) : map(v) {}
};

struct Range {
  const uint8_t* b;
  uint32_t blen;
  const uint8_t* e;  // nullptr for point (end = begin + '\0') / empty kinds
  uint32_t elen;
  uint8_t kind;      // 0 point, 1 range, 2 empty
};

inline uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

bool parse_block(const uint8_t* p, const uint8_t* end, std::vector<Range>* reads,
                 std::vector<Range>* writes) {
  if (end - p < 8) return false;
  uint32_t nr = rd32(p), nw = rd32(p + 4);
  p += 8;
  for (uint32_t i = 0; i < nr + nw; ++i) {
    if (end - p < 4) return false;
    uint32_t hdr = rd32(p);
    p += 4;
    Range r;
    r.kind = hdr >> 30;
    r.blen = hdr & kLenMask;
    if ((uint32_t)(end - p) < r.blen) return false;
    r.b = p;
    p += r.blen;
    r.e = nullptr;
    r.elen = 0;
    if (r.kind == 1) {
      if (end - p < 4) return false;
      r.elen = rd32(p);
      p += 4;
      if ((uint32_t)(end - p) < r.elen) return false;
      r.e = p;
      p += r.elen;
    }
    (i < nr ? reads : writes)->push_back(r);
  }
  return true;
}

inline Key key_of(const uint8_t* p, uint32_t n) { return Key((const char*)p, n); }

inline Key end_key(const Range& r) {
  if (r.kind == 1) return key_of(r.e, r.elen);
  Key k = key_of(r.b, r.blen);
  if (r.kind == 0) k.push_back('\0');  // point: [k, k+'\0')
  return k;                            // empty: [k, k)
}

}  // namespace

extern "C" {

void* cse_new(int64_t initial_version) { return new Engine(initial_version); }

void cse_free(void* h) { delete static_cast<Engine*>(h); }

void cse_clear(void* h, int64_t version) {
  auto* e = static_cast<Engine*>(h);
  e->map = IntervalMap(version);
}

int64_t cse_boundary_count(void* h) {
  return (int64_t)static_cast<Engine*>(h)->map.m.size();
}

// Resolve one ordered batch. blob holds n concatenated conflict-wire
// blocks; offs[n+1] delimits them; snaps[n] are read snapshots. Writes one
// status byte per transaction. Returns 0 on success, -1 on a malformed
// block (no state changed in that case).
int cse_resolve(void* h, const uint8_t* blob, const int64_t* offs, int n,
                const int64_t* snaps, int64_t now, int64_t new_oldest,
                uint8_t* out) {
  auto* eng = static_cast<Engine*>(h);

  std::vector<std::vector<Range>> reads(n), writes(n);
  for (int t = 0; t < n; ++t) {
    if (!parse_block(blob + offs[t], blob + offs[t + 1], &reads[t], &writes[t]))
      return -1;
  }

  std::vector<uint8_t> status(n, kCommitted);

  // too-old gate (SkipList.cpp:985): reads below the horizon
  for (int t = 0; t < n; ++t)
    if (snaps[t] < eng->oldest_version && !reads[t].empty()) status[t] = kTooOld;

  // reads vs. history (checkReadConflictRanges:1210)
  for (int t = 0; t < n; ++t) {
    if (status[t] != kCommitted) continue;
    for (const Range& r : reads[t]) {
      Key b = key_of(r.b, r.blen);
      bool hit;
      if (r.kind == 2) {
        hit = eng->map.version_strictly_below(b) > snaps[t];
      } else {
        hit = eng->map.range_max(b, end_key(r)) > snaps[t];
      }
      if (hit) {
        status[t] = kConflict;
        break;
      }
    }
  }

  // intra-batch, submission order, earlier wins
  // (checkIntraBatchConflicts:1133): committed writes accumulate in a
  // boolean interval map; a later read conflicts iff it overlaps any.
  IntervalMap written(0);
  bool any_written = false;
  for (int t = 0; t < n; ++t) {
    if (status[t] != kCommitted) continue;
    if (any_written) {
      bool hit = false;
      for (const Range& r : reads[t]) {
        if (r.kind == 2) continue;  // empty ranges never intra-conflict
        Key b = key_of(r.b, r.blen);
        if (written.range_max(b, end_key(r)) > 0) {
          hit = true;
          break;
        }
      }
      if (hit) {
        status[t] = kConflict;
        continue;
      }
    }
    for (const Range& w : writes[t]) {
      Key b = key_of(w.b, w.blen);
      Key e = end_key(w);
      if (b < e) {
        written.write(b, e, 1);
        any_written = true;
      }
    }
  }

  // apply committed writes at `now` (mergeWriteConflictRanges:1260)
  for (int t = 0; t < n; ++t) {
    if (status[t] != kCommitted) continue;
    for (const Range& w : writes[t])
      eng->map.write(key_of(w.b, w.blen), end_key(w), now);
  }

  // advance the horizon + GC (detectConflicts:1199-1206)
  if (new_oldest > eng->oldest_version) {
    eng->oldest_version = new_oldest;
    eng->map.gc(new_oldest);
  }

  std::memcpy(out, status.data(), n);
  return 0;
}

}  // extern "C"
