"""Structured event tracing.

Analog of the reference's TraceEvent system (flow/Trace.h, flow/Trace.cpp):
structured events with typed details, severity gating, and machine-readable
output (we use JSON lines rather than the reference's XML). SevError events
fail simulation tests, like the reference harness.
"""
from __future__ import annotations

import contextvars
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class Severity:
    DEBUG = 5
    INFO = 10
    WARN = 20
    WARN_ALWAYS = 30
    ERROR = 40


class TraceCollector:
    """Collects trace events; in simulation, registered observers (e.g. the
    test harness's SevError watchdog) see every event."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []
        self.observers: List[Callable[[Dict[str, Any]], None]] = []
        self.min_severity = Severity.INFO
        self.file = None
        self.buffer_limit = 100_000
        #: observer callbacks that raised (isolated, never re-raised into
        #: the emitting role — telemetry must not take down the commit path)
        self.observer_errors = 0
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.buffer_limit:
                del self.events[: self.buffer_limit // 2]
            if self.file is not None:
                try:
                    self.file.write(json.dumps(event, default=str) + "\n")
                    if event.get("Severity", 0) >= Severity.ERROR:
                        # a SevError may be the last thing this process logs:
                        # make sure it reaches the sink before anything dies
                        self.file.flush()
                except (OSError, ValueError):
                    pass
        for obs in list(self.observers):
            # One raising observer must neither break event emission nor
            # starve observers registered after it (the harness's SevError
            # watchdog must see the event even if a metrics bridge raised).
            try:
                obs(event)
            except Exception:
                self.observer_errors += 1

    def clear(self) -> None:
        with self._lock:
            self.events.clear()

    def close(self) -> None:
        """Flush and detach the JSON-lines file sink (events keep
        accumulating in memory)."""
        with self._lock:
            if self.file is not None:
                try:
                    self.file.flush()
                except (OSError, ValueError):
                    pass
                self.file = None

    def find(self, event_type: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [e for e in self.events if e.get("Type") == event_type]


g_trace = TraceCollector()

#: Virtual-time source, installed by the simulator so events carry sim time.
_now: Callable[[], float] = time.monotonic  # fdbtpu-lint: allow[determinism] wall-mode default only; set_time_source() installs the sim's virtual clock before any deterministic run


def set_time_source(now: Callable[[], float]) -> None:
    global _now
    _now = now


class TraceEvent:
    """`TraceEvent("Type", id).detail("K", v)...` — logs on destruction or
    explicit .log(), mirroring the reference's builder idiom."""

    def __init__(self, event_type: str, id: Any = None, severity: int = Severity.INFO):
        self._event: Dict[str, Any] = {
            "Severity": severity,
            "Time": round(_now(), 6),
            "Type": event_type,
        }
        if id is not None:
            self._event["ID"] = id
        self._logged = False

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self._event[key] = value
        return self

    def error(self, err: BaseException) -> "TraceEvent":
        self._event["Error"] = str(err)
        if self._event["Severity"] < Severity.WARN:
            self._event["Severity"] = Severity.WARN
        return self

    def log(self) -> None:
        if self._logged:
            return
        self._logged = True
        if self._event["Severity"] >= g_trace.min_severity:
            g_trace.emit(self._event)

    def __del__(self) -> None:
        try:
            self.log()
        except Exception:
            pass

    def __enter__(self) -> "TraceEvent":
        return self

    def __exit__(self, *exc) -> None:
        self.log()


# -- spans -------------------------------------------------------------------
#
# Lightweight latency spans for the commit path (docs/observability.md): a
# span is a named [t0, t1) segment tied to a trace id (the commit version of
# the batch it belongs to), emitted by the proxy's commit phases, the
# resolver's queue/service stages and the engine's pack/force halves, so a
# client-observed commit latency decomposes into named phase segments
# (bench.py `latency_attribution`). Sim-time and wall-time aware: span_now()
# reads the active deterministic scheduler's virtual clock when one is
# installed and the wall clock otherwise, so the same instrumentation serves
# the sim harness and the wall-clock ResolverPipeline.
#
# Cost discipline: collection is OFF unless the `trace_span_sample_rate`
# knob (core/knobs.py) or a harness enables it; disabled call sites pay one
# attribute check and allocate nothing (span() returns a shared null object
# — tests/test_trace_spans.py pins this).

_loop_mod = None


def span_now() -> float:
    """Virtual time under an active sim scheduler, wall time otherwise."""
    global _loop_mod
    if _loop_mod is None:
        from ..sim import loop as _loop
        _loop_mod = _loop
    s = _loop_mod._current
    return s.time if s is not None else time.perf_counter()


class SpanCollector:
    """Finished spans, bounded like the event buffer. `enabled` is the one
    fast-path gate every instrumented site checks."""

    def __init__(self) -> None:
        self.enabled = False
        self.spans: List[Dict[str, Any]] = []
        self.buffer_limit = 500_000

    def add(self, span: Dict[str, Any]) -> None:
        self.spans.append(span)
        if len(self.spans) > self.buffer_limit:
            del self.spans[: self.buffer_limit // 2]

    def clear(self) -> None:
        self.spans.clear()

    def by_name(self, name: str) -> List[Dict[str, Any]]:
        return [s for s in self.spans if s["Name"] == name]

    def for_trace(self, trace_id: Any) -> List[Dict[str, Any]]:
        return [s for s in self.spans if s.get("Trace") == trace_id]

    def durations_by_trace(self) -> Dict[Any, Dict[str, float]]:
        """trace id -> {span name: summed duration seconds} (+ `<name>.t0`:
        earliest start), the shape the latency-attribution math consumes."""
        out: Dict[Any, Dict[str, float]] = {}
        for s in self.spans:
            d = out.setdefault(s.get("Trace"), {})
            name = s["Name"]
            d[name] = d.get(name, 0.0) + (s["End"] - s["Begin"])
            k0 = name + ".t0"
            if k0 not in d or s["Begin"] < d[k0]:
                d[k0] = s["Begin"]
        return out


g_spans = SpanCollector()

#: spans allocated since process start — the tracing-disabled regression
#: guard asserts this stays flat across an instrumented run with sampling off
span_allocations = [0]


class Span:
    """One named phase segment. Created at its start; finish() records it.
    Only ever constructed when collection is enabled — disabled sites get
    NULL_SPAN from span() and allocate nothing."""

    __slots__ = ("name", "trace_id", "parent", "t0", "details")

    def __init__(self, name: str, trace_id: Any = None,
                 parent: Optional[str] = None, **details: Any):
        span_allocations[0] += 1
        self.name = name
        self.trace_id = trace_id
        self.parent = parent
        self.t0 = span_now()
        self.details = details or None

    def child(self, name: str, **details: Any) -> "Span":
        return Span(name, trace_id=self.trace_id, parent=self.name, **details)

    def finish(self, **details: Any) -> None:
        rec: Dict[str, Any] = {"Name": self.name, "Trace": self.trace_id,
                               "Begin": self.t0, "End": span_now()}
        if self.parent is not None:
            rec["Parent"] = self.parent
        if self.details:
            rec.update(self.details)
        if details:
            rec.update(details)
        if _process_name[0] and "Proc" not in rec:
            rec["Proc"] = _process_name[0]
        g_spans.add(rec)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class _NullSpan:
    """Shared no-op span for disabled collection: no allocation, no clock
    reads, no record."""

    __slots__ = ()

    def child(self, name: str, **details: Any) -> "_NullSpan":
        return self

    def finish(self, **details: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


def span(name: str, trace_id: Any = None, parent: Optional[str] = None,
         **details: Any):
    """Open a span if collection is enabled, else the shared null span."""
    if not g_spans.enabled:
        return NULL_SPAN
    return Span(name, trace_id=trace_id, parent=parent, **details)


def span_event(name: str, trace_id: Any, t0: float, t1: float,
               parent: Optional[str] = None, **details: Any) -> None:
    """Record a completed span retroactively from explicit timestamps
    (callers that only learn the trace id — e.g. the commit version — after
    the phase ran)."""
    if not g_spans.enabled:
        return
    rec: Dict[str, Any] = {"Name": name, "Trace": trace_id,
                           "Begin": t0, "End": t1}
    if parent is not None:
        rec["Parent"] = parent
    if details:
        rec.update(details)
    if _process_name[0] and "Proc" not in rec:
        rec["Proc"] = _process_name[0]
    g_spans.add(rec)


def spans_enabled() -> bool:
    return g_spans.enabled


def set_span_collection(enabled: bool) -> None:
    g_spans.enabled = bool(enabled)


# -- distributed trace context -----------------------------------------------
#
# Cross-process tracing (docs/observability.md "Distributed tracing"): a
# TraceContext is the tiny propagated half of a span — (trace id, parent
# span name, sampling bit) — that rides RPC frames under the "tc" key
# (real/transport.py attaches the caller's ambient context to every
# request/one-way frame; the serving side installs the inbound context
# around the handler), so spans recorded in different OS processes join
# into one causal tree. Trace ids follow the PR 4 convention: BATCH spans
# use the commit version; per-request client/server spans use a
# process-unique request id (next_trace_id), with the serving side's
# request span carrying the resolved commit version as a detail — the
# link the waterfall reconstruction (tools/trace_export.py) joins on.
#
# Ambient propagation is a contextvars.ContextVar: full task-local
# semantics under plain asyncio (each asyncio task runs in its own
# context copy). Handlers dispatched onto the cooperative scheduler
# (real/runtime.make_dispatcher) are wrapped so the inbound context is
# installed when the handler coroutine starts — but scheduler tasks
# interleave inside ONE asyncio task, so there the context is only
# guaranteed during a handler's SYNCHRONOUS PREFIX: capture it at entry
# (`ctx = current_trace_context()`) before the first await, as
# ChaosCommitServer._commit does.
#
# Cost discipline: context attach/install sites are gated on
# `g_spans.enabled` exactly like span sites — with sampling off, frames
# carry no "tc", nothing is installed, and nothing allocates (the
# allocation-counter regression guard covers the propagation sites too).
#
# Clock note: span timestamps are comparable ACROSS processes on one
# machine because time.perf_counter()/time.monotonic() both read
# CLOCK_MONOTONIC on Linux (shared epoch since boot); cross-machine
# traces would need an offset estimate this repo does not attempt.


@dataclasses.dataclass
class TraceContext:
    """The propagated context: trace id + parent span name + sampling bit.
    A wire-registered record, so it rides RPC frames as a typed,
    schema-evolvable payload (core/wire.py named records)."""

    trace_id: Any = None
    parent: Optional[str] = None
    sampled: bool = True


# registered at import (real/transport.py imports this module before any
# frame is built); core/wire.py also lists this module as a lazy
# registrar so a decode-first process resolves the record too
from . import wire as _wire  # noqa: E402  (leaf module; no import cycle)

_wire.register_record(TraceContext, "TraceContext")

#: this process's identity on span records ("Proc"), set once at startup
#: by wall-clock processes (demo_server --trace, nemesis --serve, smoke
#: drivers); "" (the default) stamps nothing
_process_name: List[str] = [""]


def set_process_name(name: str) -> None:
    _process_name[0] = str(name or "")


def process_name() -> str:
    return _process_name[0]


_trace_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "fdbtpu_trace_context", default=None)


def current_trace_context() -> Optional[TraceContext]:
    """The ambient inbound/outbound context (None when not tracing)."""
    return _trace_ctx.get()


def push_trace_context(ctx: Optional[TraceContext]):
    """Install `ctx` as the ambient context; returns the reset token."""
    return _trace_ctx.set(ctx)


def pop_trace_context(token) -> None:
    _trace_ctx.reset(token)


class use_trace_context:
    """`with use_trace_context(ctx): ...` — scoped ambient context."""

    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._token = _trace_ctx.set(self.ctx)
        return self.ctx

    def __exit__(self, *exc) -> None:
        _trace_ctx.reset(self._token)


_trace_seq = [0]


def next_trace_id(prefix: str = "r") -> str:
    """Process-unique request trace id (`r<pid-hex>.<seq>`): never collides
    with a commit-version (int) trace id, and two processes' ids never
    collide with each other's."""
    _trace_seq[0] += 1
    return f"{prefix}{os.getpid():x}.{_trace_seq[0]}"


#: the ONE RPC token every traced process serves its span ring on
#: (real/demo_server.py, real/nemesis.ChaosCommitServer register it; the
#: fetch side — tools/trace_export.fetch_spans, `cli trace fetch` — pulls
#: it); lives here, next to the ring it exports, so the runtime layer
#: never imports tools/ for a constant
SPANS_TOKEN = "trace.spans"


def export_spans(limit: int = 100_000) -> Dict[str, Any]:
    """This process's bounded span ring, for the `trace.spans` RPC
    endpoint (real/demo_server.py, real/nemesis.ChaosCommitServer) that
    `tools/cli.py trace fetch` and the campaign reconstruction pull:
    {"proc": <process name>, "spans": [span records]}."""
    spans = g_spans.spans
    if limit and len(spans) > limit:
        spans = spans[-limit:]
    return {"proc": _process_name[0], "spans": list(spans)}


class TraceBatch:
    """Latency micro-probes stitched per debug id across roles
    (reference: g_traceBatch, flow/Trace.h:55-60; used by the commit-path
    probes in Resolver.actor.cpp:84-131)."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []

    def add_event(self, name: str, debug_id: int, location: str) -> None:
        self.events.append(
            {"Type": name, "ID": debug_id, "Location": location, "Time": _now()}
        )

    def add_attach(self, name: str, from_id: int, to_id: int) -> None:
        self.events.append({"Type": name, "From": from_id, "To": to_id, "Time": _now()})

    def timeline(self, debug_id: int) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("ID") == debug_id]


g_trace_batch = TraceBatch()
