"""Structured event tracing.

Analog of the reference's TraceEvent system (flow/Trace.h, flow/Trace.cpp):
structured events with typed details, severity gating, and machine-readable
output (we use JSON lines rather than the reference's XML). SevError events
fail simulation tests, like the reference harness.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class Severity:
    DEBUG = 5
    INFO = 10
    WARN = 20
    WARN_ALWAYS = 30
    ERROR = 40


class TraceCollector:
    """Collects trace events; in simulation, registered observers (e.g. the
    test harness's SevError watchdog) see every event."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []
        self.observers: List[Callable[[Dict[str, Any]], None]] = []
        self.min_severity = Severity.INFO
        self.file = None
        self.buffer_limit = 100_000
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.buffer_limit:
                del self.events[: self.buffer_limit // 2]
            if self.file is not None:
                self.file.write(json.dumps(event, default=str) + "\n")
        for obs in list(self.observers):
            obs(event)

    def clear(self) -> None:
        with self._lock:
            self.events.clear()

    def find(self, event_type: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [e for e in self.events if e.get("Type") == event_type]


g_trace = TraceCollector()

#: Virtual-time source, installed by the simulator so events carry sim time.
_now: Callable[[], float] = time.monotonic


def set_time_source(now: Callable[[], float]) -> None:
    global _now
    _now = now


class TraceEvent:
    """`TraceEvent("Type", id).detail("K", v)...` — logs on destruction or
    explicit .log(), mirroring the reference's builder idiom."""

    def __init__(self, event_type: str, id: Any = None, severity: int = Severity.INFO):
        self._event: Dict[str, Any] = {
            "Severity": severity,
            "Time": round(_now(), 6),
            "Type": event_type,
        }
        if id is not None:
            self._event["ID"] = id
        self._logged = False

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self._event[key] = value
        return self

    def error(self, err: BaseException) -> "TraceEvent":
        self._event["Error"] = str(err)
        if self._event["Severity"] < Severity.WARN:
            self._event["Severity"] = Severity.WARN
        return self

    def log(self) -> None:
        if self._logged:
            return
        self._logged = True
        if self._event["Severity"] >= g_trace.min_severity:
            g_trace.emit(self._event)

    def __del__(self) -> None:
        try:
            self.log()
        except Exception:
            pass

    def __enter__(self) -> "TraceEvent":
        return self

    def __exit__(self, *exc) -> None:
        self.log()


class TraceBatch:
    """Latency micro-probes stitched per debug id across roles
    (reference: g_traceBatch, flow/Trace.h:55-60; used by the commit-path
    probes in Resolver.actor.cpp:84-131)."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []

    def add_event(self, name: str, debug_id: int, location: str) -> None:
        self.events.append(
            {"Type": name, "ID": debug_id, "Location": location, "Time": _now()}
        )

    def add_attach(self, name: str, from_id: int, to_id: int) -> None:
        self.events.append({"Type": name, "From": from_id, "To": to_id, "Time": _now()})

    def timeline(self, debug_id: int) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("ID") == debug_id]


g_trace_batch = TraceBatch()
