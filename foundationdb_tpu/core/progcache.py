"""On-disk AOT compiled-program cache: restart-warm in milliseconds.

The bucket-ladder engines (ops/host_engine.py, ops/device_loop.py) pay
seconds of XLA compile per (bucket, scan-size) program at warmup — the
price ROADMAP item 2 cites as the reason the spare pool exists, and the
dominant term in a crash-restarted resolver's blackout. This module
caches the compiled artifacts themselves on disk so a restarted,
failed-over, or spare-pool resolver warms by LOADING, not recompiling.

Mechanism: `jax.experimental.serialize_executable` round-trips the
already-compiled executable (the serialized XLA binary plus its I/O
pytree defs). Measured on the chaos ladder's top bucket this loads in
~85 ms against a ~2 s cold trace+lower+compile — ~25x, against the 5x
acceptance bar. (`jax.export` was evaluated and rejected for this cache:
its round trip re-lowers through StableHLO and XLA-compiles on load, so
a "hit" costs nearly as much as the miss it was meant to avoid.)

Keying: `(backend fingerprint, engine kind, bucket, n_chunks, search
mode, dispatch mode)` — the same tuple the perf ledger files compiles
under. The backend fingerprint folds in the jax/jaxlib versions and the
device platform/kind, so an artifact compiled by a different toolchain
or for a different device NEVER loads: a stale key is a miss and the
engine falls back to a normal compile (tests/test_recovery.py pins it).

Durability discipline mirrors the black-box journal: entries are
crc-framed (`FBPC` magic), verified by a decode round-trip BEFORE they
are published (see `store`), written via tmp-file + atomic rename, and
a poisoned entry (bit rot, torn write, version skew, unpickleable) is a
MISS that quarantines the file — the serving path degrades to compile,
never crashes. The `DiskFaults` hook (fault/inject.py) injects faults
into exactly these writes under the crash campaign.

Cost discipline: no cache installed = one list-index check in
`_build_and_record`; hits/misses/bytes are filed through the engine's
perf ledger (core/perfledger.py `record_progcache`), NOT the compile
counters — the zero-post-warmup-steady-compile assertions keep their
meaning, and a progcache-warm engine reports compiles == 0.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional

#: entry file header: magic + format version
MAGIC = b"FBPC"
FORMAT_VERSION = 1
_HEADER = MAGIC + bytes([FORMAT_VERSION])
#: per-entry frame: little-endian (payload length, crc32 of payload)
_FRAME = struct.Struct("<II")


def backend_fingerprint() -> str:
    """The toolchain + device identity a compiled artifact is only valid
    for. Folded into every cache key so upgrading jax/jaxlib or moving
    the directory to a different device kind turns every entry into a
    clean miss (fall back to compile), never a wrong-artifact load.

    The visible DEVICE COUNT is part of the identity: a shard_map program
    compiled against an 8-device mesh embeds that topology in the
    executable, and serving it to a resolver restarted with 1 visible
    device (or vice versa) would be a wrong-artifact load, not a slower
    one (tests/test_progcache_mesh.py flips
    xla_force_host_platform_device_count across processes and pins the
    clean miss)."""
    import jax
    import jaxlib

    devs = jax.devices()
    dev = devs[0]
    return "|".join((jax.__version__, jaxlib.__version__, dev.platform,
                     str(getattr(dev, "device_kind", "")),
                     f"ndev{len(devs)}"))


class ProgramCache:
    """Content-addressed directory of serialized compiled executables."""

    def __init__(self, directory: str, disk: Optional[Any] = None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        #: optional DiskFaults hook (fault/inject.py) — the nemesis'
        #: entry point into the cache's writes
        self.disk = disk
        self.stats: Dict[str, Any] = {
            "hits": 0, "misses": 0, "stores": 0, "poisoned": 0,
            "unverifiable": 0, "errors": 0, "hit_bytes": 0,
            "store_bytes": 0, "load_ms": 0.0, "store_ms": 0.0,
        }

    # -- keying ---------------------------------------------------------------
    def key(self, *, engine: str, bucket: int, n_chunks: int,
            search_mode: str, dispatch_mode: str, mesh: str = "",
            variant: str = "", structure: str = "") -> str:
        """`mesh` is the engine's sharding-layout fingerprint
        (RoutedConflictEngineBase._progcache_fingerprint): "" for the
        single-device families, "mesh:<S>/<ndev>"-shaped for engines whose
        programs bake a device mesh — two engines whose programs differ
        only in mesh topology must never share an entry. `variant` names
        one program of a multi-program dispatch unit (the mesh engine's
        split "scan" / "exchange" pair under one (bucket, n_chunks)).
        `structure` is the history-structure fingerprint
        (RoutedConflictEngineBase._history_fingerprint): "" for the
        monolithic table (so pre-existing entries keep their hashes),
        "tiered:<runs>x<rows>"-shaped when the program bakes the tiered
        sorted-run planes — a structure flip must be a clean miss, never
        a poisoned hit against mismatched state trees."""
        blob = "|".join(map(str, (backend_fingerprint(), engine, bucket,
                                  n_chunks, search_mode, dispatch_mode,
                                  mesh, variant)))
        if structure:
            blob += "|" + structure
        return hashlib.sha256(blob.encode()).hexdigest()[:40]

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.prog")

    # -- load -----------------------------------------------------------------
    def load(self, key: str):
        """The loaded, immediately-callable executable for `key`, or None
        (miss). Any corruption — bad magic, torn frame, crc mismatch,
        deserialize failure — quarantines the entry (unlinks it, counts
        `poisoned`) and reports a miss: the caller compiles."""
        path = self._path(key)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self.stats["misses"] += 1
            return None
        try:
            prog = self._decode(data)
        except Exception:                       # poisoned entry, any shape
            self.stats["poisoned"] += 1
            self.stats["misses"] += 1
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        self.stats["hits"] += 1
        self.stats["hit_bytes"] += len(data)
        self.stats["load_ms"] += (time.perf_counter() - t0) * 1e3
        return prog

    @staticmethod
    def _decode(data: bytes):
        if len(data) < len(_HEADER) + _FRAME.size or \
                data[:len(_HEADER)] != _HEADER:
            raise ValueError("bad progcache header")
        length, crc = _FRAME.unpack_from(data, len(_HEADER))
        raw = data[len(_HEADER) + _FRAME.size:
                   len(_HEADER) + _FRAME.size + length]
        if len(raw) != length or zlib.crc32(raw) != crc:
            raise ValueError("torn or rotted progcache entry")
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = pickle.loads(raw)
        return serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree)

    # -- store ----------------------------------------------------------------
    def store(self, key: str, compiled) -> bool:
        """Serialize `compiled` under `key` (tmp + atomic rename). Never
        raises: a full disk, an unserializable program or an injected
        disk fault degrade to a future compile, not a crash.

        Every artifact is VERIFIED by decoding it back before it is
        published: serialize_executable round-trips are not universally
        self-contained — an executable jax itself loaded from its
        persistent compilation cache re-serializes into bytes whose
        deserialize fails with "Symbols not found" — and publishing such
        an entry would poison every future restart's rewarm. An
        unverifiable artifact is counted and dropped (the next boot
        compiles); verification runs on the pre-fault bytes, so injected
        bit rot is still discovered at read time by the crc, the
        quarantine path the nemesis exercises."""
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            raw = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            self.stats["errors"] += 1
            return False
        data = _HEADER + _FRAME.pack(len(raw), zlib.crc32(raw)) + raw
        try:
            self._decode(data)
        except Exception:
            self.stats["unverifiable"] += 1
            self.stats["errors"] += 1
            return False
        path = self._path(key)
        tmp = path + ".tmp"
        try:
            if self.disk is not None:
                data = self.disk.apply("progcache", data)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            self.stats["errors"] += 1
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self.stats["stores"] += 1
        self.stats["store_bytes"] += len(data)
        self.stats["store_ms"] += (time.perf_counter() - t0) * 1e3
        return True

    # -- read model -----------------------------------------------------------
    def entries(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.endswith(".prog"))
        except OSError:
            return []

    def summary(self) -> dict:
        s = dict(self.stats)
        s["load_ms"] = round(s["load_ms"], 3)
        s["store_ms"] = round(s["store_ms"], 3)
        return {"dir": self.directory, "entries": len(self.entries()), **s}


# -- process-global installation ----------------------------------------------
#: the one installed cache (None = disabled: `_build_and_record` pays one
#: list-index check and compiles exactly as before)
_g: List[Optional[ProgramCache]] = [None]


def enabled() -> bool:
    return _g[0] is not None


def active() -> Optional[ProgramCache]:
    return _g[0]


def install(cache: ProgramCache) -> ProgramCache:
    _g[0] = cache
    return cache


def uninstall() -> Optional[ProgramCache]:
    c, _g[0] = _g[0], None
    return c


def knob_directory() -> Optional[str]:
    """The cache directory the `resolver_progcache` knob selects: None
    when off ("" / "off"); `resolver_progcache_dir` when "on"; any other
    value is itself the directory (the resolver_blackbox pattern)."""
    from .knobs import SERVER_KNOBS

    sel = str(SERVER_KNOBS.resolver_progcache or "").strip()
    if not sel or sel.lower() == "off":
        return None
    return (str(SERVER_KNOBS.resolver_progcache_dir)
            if sel.lower() == "on" else sel)


def cache_from_knobs(disk: Optional[Any] = None) -> Optional[ProgramCache]:
    directory = knob_directory()
    if directory is None:
        return None
    return ProgramCache(directory, disk=disk)
