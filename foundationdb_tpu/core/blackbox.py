"""Durable black-box journal: the cluster's flight data recorder.

Every observability layer before this one dies with its process — spans
live in a bounded ring, watchdog incidents in the engine, the
ResilientEngine journal in test-harness memory, reshard ops in the
controller. Nothing could answer "why did transaction T abort at version
V" an hour later. This module is the narration substrate: a bounded,
segment-rotated, strictly append-only ON-DISK structured event log into
which the existing producers sink records they already compute —

  * per-batch resolution records (the transactions, verdict vector and
    GC horizon — enough to DIFFERENTIALLY REPLAY any persisted window
    through the clean serial oracle, tools/forensics.py);
  * span records past the tail sampler (the campaign's retained
    waterfalls), watchdog alert lifecycle transitions and correlated
    incidents, ResilientEngine health transitions and flight-recorder
    dumps, reshard phase arcs and epoch flips, admission/shed counters,
    keyspace-heat briefs, injected fault windows.

Every event rides one `BBEnvelope` stamped {seq, t, commit_version,
epoch, shard, proc, trace_id}, so heterogeneous signals join on
version + trace id (the Canopy per-request-fusion idea, applied to
commit forensics). Payload schemas are CLOSED: `BLACKBOX_EVENT_REGISTRY`
maps every event kind to its wire-registered record type, and the
fdbtpu-lint `blackbox-registry` rule rejects `record_event` sites whose
kind is not in the table (the span-registry precedent).

Format: each segment file is `MAGIC + version` then a run of frames
`[u32 length][u32 crc32][wire payload]` (core/wire.py named records —
byte-stable, schema-evolvable). Writes are append-only and flushed per
record; a crash mid-frame leaves a partial tail the reader TOLERATES
(it returns every complete, crc-clean prefix record and stops).
Segments rotate at `resolver_blackbox_segment_bytes` and the oldest is
deleted past `resolver_blackbox_segments` — the retention window is
sized in the same spirit as the MVCC window, so a replayed slice's
too-old gate still holds (forensics reports `coverage_ok` honestly).

Clock: `now_fn` defaults to `span_now()` — the sim's virtual clock when
a deterministic scheduler is installed, the wall clock otherwise — so
same-seed deterministic runs produce BYTE-IDENTICAL journals
(tests/test_blackbox.py pins this).

Cost discipline: the disabled path (`resolver_blackbox` knob off, no
journal installed) is one list-index check per producer site; nothing
allocates (`blackbox_allocations` is the regression counter, the
NULL_SPAN pattern). Recording never touches a device and never raises
into the serving path — abort sets are bit-identical on/off.
"""
from __future__ import annotations

import os
import struct
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import buggify, wire
from .trace import span_now

#: allocation counter for the disabled-path regression guard (the
#: core/trace.py span_allocations pattern): bumped whenever the journal
#: allocates a record — with no journal installed, a full resolve loop
#: must leave it untouched (tests/test_blackbox.py).
blackbox_allocations = [0]

#: segment file header: magic + format version
MAGIC = b"FBBX"
SEGMENT_VERSION = 1
_HEADER = MAGIC + bytes([SEGMENT_VERSION])
#: per-record frame: little-endian (payload length, crc32 of payload)
_FRAME = struct.Struct("<II")


# -- event records -------------------------------------------------------------
# One dataclass per event kind; all wire-registered named records, so a
# vN journal read by a vN+1 binary tolerates added/dropped fields.

@dataclass
class BBEnvelope:
    """The stamp every event carries — the join surface of the journal."""

    seq: int = 0
    t: float = 0.0
    kind: str = ""
    commit_version: int = -1
    epoch: int = -1
    shard: int = -1
    proc: str = ""
    trace_id: Any = None
    payload: Any = None


@dataclass
class BBBatch:
    """One resolved batch — the differential-replay unit: transactions +
    verdicts + horizon reproduce the serial oracle's state machine."""

    version: int = 0
    new_oldest: int = 0
    txns: Tuple = ()
    verdicts: Tuple = ()
    engine: str = ""
    served_by: str = ""
    witness: Tuple = ()   # sampled first-witness attribution dicts


@dataclass
class BBSpan:
    """A span record past the tail sampler (core/trace.py layout)."""

    name: str = ""
    trace: Any = None
    begin: float = 0.0
    end: float = 0.0
    proc: str = ""
    detail: Dict = field(default_factory=dict)


@dataclass
class BBHealth:
    """A ResilientEngine health-state transition (fault/resilient.py)."""

    label: str = ""
    prev: str = ""
    state: str = ""


@dataclass
class BBFlight:
    """A flight-recorder dump at a failover/quarantine boundary."""

    reason: str = ""
    version: int = -1
    records: Tuple = ()


@dataclass
class BBAlert:
    """One watchdog alert lifecycle edge (core/watchdog.py ring entry)."""

    alert: str = ""
    series: str = ""
    state: str = ""
    value: float = 0.0
    detail: str = ""


@dataclass
class BBIncident:
    """A correlated incident at campaign close (core/watchdog.py)."""

    id: int = 0
    t0: float = 0.0
    t1: Optional[float] = None
    alerts: Tuple = ()
    windows: Tuple = ()
    explained: bool = False
    explanation: Optional[str] = None
    summary: str = ""


@dataclass
class BBReshard:
    """One reshard phase edge (server/reshard.py ReshardOp arc); the
    `flip` phase carries the new epoch + flip version + split keys, so
    routing is reconstructible from the journal alone."""

    op_id: int = 0
    kind: str = ""
    phase: str = ""
    begin: str = ""
    end: Optional[str] = None
    epoch: int = -1
    flip_version: int = -1
    splits: Tuple = ()
    blackout_ms: float = 0.0
    donor_sids: Tuple = ()
    recipient_sid: int = -1
    error: Optional[str] = None


@dataclass
class BBAdmission:
    """Admission/shed counter snapshot (server/ratekeeper.py totals)."""

    label: str = ""
    admitted: int = 0
    rejected: int = 0
    rate: float = 0.0
    weights: Dict = field(default_factory=dict)


@dataclass
class BBHeat:
    """A keyspace-heat brief (core/heatmap.py brief() fields)."""

    conflicts: int = 0
    occupancy_frac: float = 0.0
    concentration: float = 0.0
    top_range: Optional[str] = None
    top_share: float = 0.0


@dataclass
class BBSched:
    """One scheduling tick's decisions against the batch version it
    produced (pipeline/scheduler.py SchedPlan): how many transactions
    were dispatched / deferred / laned / pre-aborted / probed this tick,
    and WHICH ranges convicted the pre-aborts and hosted the lanes — the
    `why` behind a deferred or refused transaction that `cli explain`
    renders for the version."""

    version: int = 0
    dispatched: int = 0
    deferred: int = 0
    laned: int = 0
    preaborted: int = 0
    probes: int = 0
    forced: int = 0
    lanes: int = 0
    pending: int = 0
    epoch: int = -1
    preabort_ranges: Tuple = ()
    lane_ranges: Tuple = ()


@dataclass
class BBSnapshotEvt:
    """One engine-state snapshot written beside the journal segments
    (fault/recovery.py SnapshotManager): the recovery floor moves to
    `version`, bounded by `entries` distinct-version write batches (the
    handoff pre-copy coalescing, NOT history length)."""

    version: int = 0
    oldest: int = 0
    entries: int = 0
    bytes: int = 0
    ms: float = 0.0
    path: str = ""


@dataclass
class BBRecovery:
    """One crash-stop recovery arc (fault/recovery.py recover()): where
    the state came from (snapshot version + replayed journal suffix),
    whether retained history fully covered the gap (`coverage_ok` /
    `mode`), verdict parity of the differential replay, and the blackout
    the restart cost — `cli recovery` renders exactly this record."""

    mode: str = ""
    coverage_ok: bool = True
    snapshot_version: int = -1
    recovered_version: int = -1
    oldest: int = 0
    snapshot_entries: int = 0
    replayed_batches: int = 0
    verdict_mismatches: int = 0
    blackout_ms: float = 0.0
    progcache_hits: int = 0
    progcache_misses: int = 0
    warm_ms: float = 0.0
    error: Optional[str] = None


@dataclass
class BBWindow:
    """An injected fault / maintenance window (the nemesis' kinded
    records — partition, device_incident, reshard, warmup, ...)."""

    kind: str = ""
    t0: float = 0.0
    t1: float = 0.0
    detail: Dict = field(default_factory=dict)


@dataclass
class BBScenario:
    """The scenario-atlas stamp (real/scenarios.py): which named
    production recipe this campaign ran, with its measured heat/abort
    signature — load concentration, the top range's identity and share,
    the verdict mix — so forensics over a bare journal can answer
    "which workload shape produced these batches?"."""

    name: str = ""
    seed: int = 0
    engine_mode: str = ""
    concentration: float = 0.0
    top_range: Optional[str] = None
    top_share: float = 0.0
    abort_frac: float = 0.0
    throttle_frac: float = 0.0
    witnesses: int = 0


#: The CLOSED event schema: kind -> wire record type. Policed by the
#: fdbtpu-lint `blackbox-registry` rule — a `record_event("<kind>", ...)`
#: whose kind is not a key here is a lint finding, so the journal format
#: can only grow through this table (and its doc row in
#: docs/observability.md).
BLACKBOX_EVENT_REGISTRY = {
    "batch": BBBatch,
    "span": BBSpan,
    "health": BBHealth,
    "flight": BBFlight,
    "alert": BBAlert,
    "incident": BBIncident,
    "reshard": BBReshard,
    "admission": BBAdmission,
    "heat": BBHeat,
    "fault_window": BBWindow,
    "sched": BBSched,
    "snapshot": BBSnapshotEvt,
    "recovery": BBRecovery,
    "scenario": BBScenario,
}

for _cls in (BBEnvelope, *BLACKBOX_EVENT_REGISTRY.values()):
    wire.register_record(_cls)


# -- the journal ---------------------------------------------------------------

class BlackboxJournal:
    """Bounded, segment-rotated, append-only on-disk event log."""

    def __init__(self, directory: str,
                 segment_bytes: Optional[int] = None,
                 max_segments: Optional[int] = None,
                 ring: Optional[int] = None,
                 now_fn=span_now, proc: str = "",
                 fresh: bool = False,
                 fsync_interval: Optional[int] = None,
                 disk: Optional[Any] = None):
        """`fresh=True` truncates any retained segments first — a
        campaign reusing a deterministic directory (`make chaos-drift`
        re-run) must not append a second event stream whose commit
        versions collide with the first run's; reopening to CONTINUE a
        journal (a restarted long-lived resolver) keeps the default."""
        from .knobs import SERVER_KNOBS

        self.directory = str(directory)
        if fresh:
            for p in _segment_paths(self.directory):
                try:
                    os.remove(p)
                except OSError:
                    pass
        self.segment_bytes = int(
            segment_bytes if segment_bytes is not None
            else SERVER_KNOBS.resolver_blackbox_segment_bytes)
        self.max_segments = int(
            max_segments if max_segments is not None
            else SERVER_KNOBS.resolver_blackbox_segments)
        self.now_fn = now_fn
        self.proc = proc
        os.makedirs(self.directory, exist_ok=True)
        #: in-memory ring of recent envelopes (live explain on a running
        #: process reads this instead of round-tripping the disk)
        self.ring: deque = deque(maxlen=int(
            ring if ring is not None
            else SERVER_KNOBS.resolver_blackbox_ring))
        self.events_written = 0
        self.dropped_errors = 0
        #: fsync cadence (resolver_blackbox_fsync_interval): 0 = flush
        #: per record only (the OS may buffer a crash-window tail); N>=1
        #: = os.fsync every N records — acked implies durable at N=1
        #: (docs/observability.md "crash-window contract")
        self.fsync_interval = int(
            fsync_interval if fsync_interval is not None
            else SERVER_KNOBS.resolver_blackbox_fsync_interval)
        self.fsyncs = 0
        self.fsync_ms = 0.0
        self._since_fsync = 0
        #: optional DiskFaults hook (fault/inject.py) — the disk nemesis'
        #: entry point into the journal's writes
        self.disk = disk
        #: shed-to-memory accounting: events the DISK refused but the
        #: in-memory ring kept — live explain still sees them, and
        #: summary() reports the durability gap honestly instead of
        #: silently narrowing the journal's coverage
        self.shed_events = 0
        self.durability_gap = False
        #: whole-journal accounting for summary() — the ring is bounded,
        #: so kind counts and the version range are tracked at record()
        #: time, never derived from whatever the ring still holds
        self._kind_counts: Dict[str, int] = {}
        self._v_min: Optional[int] = None
        self._v_max: Optional[int] = None
        existing = _segment_paths(self.directory)
        self._seg_index = (
            _segment_index(existing[-1]) + 1 if existing else 1)
        if existing:
            # reopening a directory: sequence numbers continue past the
            # newest retained record (rotation may have dropped seq 0)
            evs = read_journal(self.directory)
            self._seq = evs[-1].seq + 1 if evs else 0
        else:
            self._seq = 0
        self._file = None
        self._seg_bytes_written = 0
        self._open_segment()

    # -- writing -------------------------------------------------------------
    def _seg_path(self, index: int) -> str:
        return os.path.join(self.directory, f"bbox-{index:06d}.seg")

    def _open_segment(self) -> None:
        path = self._seg_path(self._seg_index)
        self._file = open(path, "ab")
        if self._file.tell() == 0:
            self._file.write(_HEADER)
            self._file.flush()
        self._seg_bytes_written = self._file.tell()

    def _rotate(self) -> None:
        if buggify.buggify():
            # BUGGIFY: rotation mid-append — the process died after
            # starting a frame but before completing it, then rotated on
            # restart: the closed segment carries a torn junk tail every
            # reader (read_segment, strict_parse, recovery replay) must
            # absorb without losing the complete frames before it
            try:
                self._file.write(_FRAME.pack(1 << 20, 0) + b"\xde\xad")
                self._file.flush()
            except OSError:
                pass
        self._file.close()
        self._seg_index += 1
        self._open_segment()
        paths = _segment_paths(self.directory)
        while len(paths) > max(1, self.max_segments):
            try:
                os.remove(paths.pop(0))
            except OSError:
                self.dropped_errors += 1
                break

    def _flush(self) -> None:
        """Flush, then fsync every `fsync_interval` records. fsync_ms is
        wall-clock observability only (never journaled), so same-seed
        byte-identical journals are unaffected."""
        self._file.flush()
        if self.fsync_interval > 0:
            self._since_fsync += 1
            if self._since_fsync >= self.fsync_interval:
                t0 = time.perf_counter()
                os.fsync(self._file.fileno())
                self.fsync_ms += (time.perf_counter() - t0) * 1e3
                self.fsyncs += 1
                self._since_fsync = 0

    def _append(self, data: bytes) -> bool:
        """One framed record to the segment file; False = the disk did
        not take it (the caller sheds the event to the memory ring)."""
        try:
            if self.disk is not None:
                # the disk nemesis: may stall (sleep), raise ENOSPC, tear
                # the write (OSError carrying the prefix that DID land),
                # or bit-rot the payload in passing (crc catches at read)
                data = self.disk.apply("journal", data)
            if buggify.buggify():
                # BUGGIFY: short write — only a prefix of the frame
                # reaches the segment (the crash-mid-append shape); the
                # reader must tolerate the torn tail and the journal must
                # rotate so later records stay parseable
                self._file.write(data[:max(1, len(data) // 2)])
                self._file.flush()
                raise OSError("buggify: short segment write")
            self._file.write(data)
            self._flush()
            return True
        except (OSError, ValueError) as e:
            # ValueError covers a write on a file another layer already
            # closed (teardown races, the nemesis killing the handle) —
            # same shedding contract as a disk refusal
            prefix = getattr(e, "prefix", None)
            if prefix:
                # a torn write persists the prefix that reached the disk
                # before failing — exactly what the crc-framed reader
                # tolerates (read_segment stops at the torn frame)
                try:
                    self._file.write(prefix)
                    self._file.flush()
                except (OSError, ValueError):
                    pass
            return False

    def record(self, kind: str, payload: Any, commit_version: int = -1,
               epoch: int = -1, shard: int = -1, trace_id: Any = None,
               proc: Optional[str] = None) -> None:
        """Append one event. Never raises into the caller: the journal is
        observational — a full disk degrades forensics, not serving. A
        write the disk refuses is SHED TO MEMORY: the bounded ring keeps
        the envelope for live explain, `shed_events`/`durability_gap`
        report the coverage hole honestly, and the on-disk sequence stays
        contiguous (the shed event's seq is reused by the next durable
        record, so strict_parse still proves no silent gaps)."""
        blackbox_allocations[0] += 1
        env = BBEnvelope(
            seq=self._seq, t=round(float(self.now_fn()), 6), kind=kind,
            commit_version=int(commit_version), epoch=int(epoch),
            shard=int(shard), proc=self.proc if proc is None else proc,
            trace_id=trace_id, payload=payload)
        try:
            raw = wire.dumps(env)
        except (ValueError, TypeError):
            self.dropped_errors += 1
            return
        data = _FRAME.pack(len(raw), zlib.crc32(raw)) + raw
        self.ring.append(env)
        self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
        if kind == "batch":
            v = int(payload.version)
            self._v_min = v if self._v_min is None else min(self._v_min, v)
            self._v_max = v if self._v_max is None else max(self._v_max, v)
        if not self._append(data):
            # a failed write may have left a torn frame mid-segment, and
            # the reader stops at the first torn frame — rotate so later
            # records land in a fresh segment instead of appending
            # unreadably after the garbage
            self.dropped_errors += 1
            self.shed_events += 1
            self.durability_gap = True
            try:
                self._rotate()
            except OSError:
                pass
            return
        self._seq += 1
        self.events_written += 1
        self._seg_bytes_written += len(data)
        if self._seg_bytes_written >= self.segment_bytes:
            self._rotate()

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.flush()
                self._file.close()
            except OSError:
                pass
            self._file = None

    # -- read model ----------------------------------------------------------
    def events(self) -> List[BBEnvelope]:
        """Recent envelopes from the in-memory ring (live explain)."""
        return list(self.ring)

    def summary(self) -> dict:
        """The campaign-report `blackbox` fragment (`cli blackbox`).
        Counts cover the WHOLE journal's lifetime (tracked at record()
        time), not just what the bounded ring still holds; note
        version_range spans written history — rotation may have dropped
        its low end from disk (`cli blackbox` shows retained coverage)."""
        return {
            "dir": self.directory,
            "events": self.events_written,
            "segments": len(_segment_paths(self.directory)),
            "dropped_errors": self.dropped_errors,
            "kinds": dict(self._kind_counts),
            "version_range": ([self._v_min, self._v_max]
                              if self._v_min is not None else None),
            # durability accounting (docs/observability.md "crash-window
            # contract"): fsync cadence + cost, and the honest flag for
            # events the disk refused but the memory ring kept
            "fsyncs": self.fsyncs,
            "fsync_ms": round(self.fsync_ms, 3),
            "fsync_interval": self.fsync_interval,
            "shed_events": self.shed_events,
            "durability_gap": self.durability_gap,
        }


# -- reading -------------------------------------------------------------------

def _segment_index(path: str) -> int:
    name = os.path.basename(path)
    return int(name[len("bbox-"):-len(".seg")])


def _segment_paths(directory: str) -> List[str]:
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("bbox-") and n.endswith(".seg")]
    except OSError:
        return []
    return [os.path.join(directory, n) for n in sorted(names)]


def read_segment(path: str) -> List[BBEnvelope]:
    """Every complete, crc-clean record of one segment; a torn or
    truncated tail (crash mid-append) ends the read without raising."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    if len(data) < len(_HEADER) or data[:len(MAGIC)] != MAGIC:
        return []
    out: List[BBEnvelope] = []
    off = len(_HEADER)
    n = len(data)
    while off + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(data, off)
        end = off + _FRAME.size + length
        if end > n:
            break                       # truncated tail frame
        raw = data[off + _FRAME.size:end]
        if zlib.crc32(raw) != crc:
            break                       # torn tail frame
        try:
            env = wire.loads(raw)
        except (ValueError, KeyError, TypeError):
            break
        out.append(env)
        off = end
    return out


def read_journal(directory: str) -> List[BBEnvelope]:
    """Every readable event across the retained segments, oldest first."""
    out: List[BBEnvelope] = []
    for path in _segment_paths(directory):
        out.extend(read_segment(path))
    return out


# -- process-global installation ----------------------------------------------
#: the one installed journal (None = disabled: every producer site pays
#: one list-index check and allocates nothing)
_g: List[Optional[BlackboxJournal]] = [None]


def enabled() -> bool:
    return _g[0] is not None


def active() -> Optional[BlackboxJournal]:
    return _g[0]


def install(journal: BlackboxJournal) -> BlackboxJournal:
    _g[0] = journal
    # the installed journal is the process's durable record — register
    # its durability accounting with the telemetry hub (weakly, like
    # every other source) so `blackbox.<label>.*` series exist wherever
    # a journal is writing (docs/observability.md crash-window contract)
    from . import telemetry

    journal.label = telemetry.hub().register_blackbox(
        journal, journal.proc or "blackbox")
    return journal


def uninstall() -> Optional[BlackboxJournal]:
    """Detach and close the installed journal (idempotent)."""
    j, _g[0] = _g[0], None
    if j is not None:
        j.close()
    return j


def knob_directory() -> Optional[str]:
    """The journal directory the `resolver_blackbox` knob selects: None
    when off ("" / "off"); `resolver_blackbox_dir` when "on"; any other
    value is itself the directory. Callers that run REPEATEDLY with
    restarting version streams (the chaos campaigns) must place each run
    in its own subdirectory of this — a shared directory opened fresh
    per run would leave every earlier run's report pointing at a wiped
    journal."""
    from .knobs import SERVER_KNOBS

    sel = str(SERVER_KNOBS.resolver_blackbox or "").strip()
    if not sel or sel.lower() == "off":
        return None
    return (str(SERVER_KNOBS.resolver_blackbox_dir)
            if sel.lower() == "on" else sel)


def journal_from_knobs(proc: str = "",
                       fresh: bool = False) -> Optional[BlackboxJournal]:
    """A journal per the `resolver_blackbox` knob (see knob_directory);
    `fresh` truncates retained segments first."""
    directory = knob_directory()
    if directory is None:
        return None
    return BlackboxJournal(directory, proc=proc, fresh=fresh)


# -- producer sinks ------------------------------------------------------------
# Each helper is the ONE way its producer records: check-first (no
# payload is built when disabled), never raising, stamped consistently.

def record_event(kind: str, payload: Any, **stamp: Any) -> None:
    j = _g[0]
    if j is None:
        return
    j.record(kind, payload, **stamp)


def record_batch(transactions, version, new_oldest, verdicts,
                 epoch: int = -1, shard: int = -1, engine: str = "",
                 served_by: str = "", witness=(), proc=None) -> None:
    """One resolved batch from the resolution tier's TOP level (the sim
    Resolver, the ElasticResolverGroup, or a non-elastic commit server) —
    exactly once per version, so differential replay never double-applies."""
    j = _g[0]
    if j is None:
        return
    j.record(
        "batch",
        BBBatch(version=int(version), new_oldest=int(new_oldest),
                txns=tuple(transactions),
                verdicts=tuple(int(v) for v in verdicts),
                engine=engine, served_by=served_by,
                witness=tuple(witness)),
        commit_version=int(version), epoch=epoch, shard=shard, proc=proc)


def record_span(rec: Dict[str, Any]) -> None:
    """One span record past the tail sampler (core/trace.py layout)."""
    j = _g[0]
    if j is None:
        return
    trace = rec.get("Trace")
    detail = {k: v for k, v in rec.items()
              if k not in ("Name", "Trace", "Begin", "End", "Proc")}
    j.record(
        "span",
        BBSpan(name=rec.get("Name", ""), trace=trace,
               begin=float(rec.get("Begin", 0.0)),
               end=float(rec.get("End", 0.0)),
               proc=rec.get("Proc", ""), detail=detail),
        commit_version=(trace if isinstance(trace, int)
                        else int(detail.get("version") or -1)),
        trace_id=trace)


def record_health(label: str, prev: str, state: str) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("health", BBHealth(label=label, prev=prev, state=state))


def record_flight(reason: str, version, records) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("flight",
             BBFlight(reason=reason, version=int(version),
                      records=tuple(records)),
             commit_version=int(version))


def record_alert(alert: str, series: str, state: str, value,
                 detail: str) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("alert", BBAlert(alert=alert, series=series, state=state,
                              value=float(value), detail=detail))


def record_incident(inc: Dict[str, Any]) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("incident", BBIncident(
        id=int(inc.get("id", 0)), t0=float(inc.get("t0", 0.0)),
        t1=inc.get("t1"),
        alerts=tuple(a.get("name") for a in inc.get("alerts") or ()),
        windows=tuple(w.get("kind") for w in inc.get("windows") or ()),
        explained=bool(inc.get("explained")),
        explanation=inc.get("explanation"),
        summary=inc.get("summary", "")))


def record_reshard(op, phase: str, epoch: int = -1, flip_version: int = -1,
                   splits=()) -> None:
    """One phase edge of a reshard op (server/reshard.py)."""
    j = _g[0]
    if j is None:
        return
    j.record(
        "reshard",
        BBReshard(op_id=op.id, kind=op.kind, phase=phase, begin=op.begin,
                  end=op.end, epoch=epoch, flip_version=flip_version,
                  splits=tuple(splits),
                  blackout_ms=round(float(op.blackout_ms), 3),
                  donor_sids=tuple(op.donor_sids),
                  recipient_sid=op.recipient_sid, error=op.error),
        commit_version=flip_version, epoch=epoch)


def record_admission(label: str, admitted: int, rejected: int,
                     rate: float = 0.0, weights=None) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("admission",
             BBAdmission(label=label, admitted=int(admitted),
                         rejected=int(rejected), rate=float(rate),
                         weights=dict(weights or {})))


def record_heat(brief: Dict[str, Any]) -> None:
    j = _g[0]
    if j is None:
        return
    j.record("heat", BBHeat(
        conflicts=int(brief.get("conflicts", 0)),
        occupancy_frac=float(brief.get("occupancy_frac", 0.0)),
        concentration=float(brief.get("concentration", 0.0)),
        top_range=brief.get("top_range"),
        top_share=float(brief.get("top_share", 0.0))))


def record_sched(plan, version, lanes: int, pending: int,
                 epoch: int = -1) -> None:
    """One scheduling tick's decisions (pipeline/scheduler.py SchedPlan)
    stamped with the batch version the tick produced — recorded only for
    ticks that DECIDED something, so an idle scheduler writes nothing."""
    j = _g[0]
    if j is None:
        return
    d = plan.decided
    j.record(
        "sched",
        BBSched(version=int(version),
                dispatched=int(d.get("dispatch", 0)),
                deferred=int(d.get("defer", 0)),
                laned=int(d.get("lane", 0)),
                preaborted=int(d.get("preabort", 0)),
                probes=int(d.get("probe", 0)),
                forced=int(d.get("forced", 0)),
                lanes=int(lanes), pending=int(pending), epoch=int(epoch),
                preabort_ranges=tuple(plan.preabort_ranges),
                lane_ranges=tuple(plan.lane_ranges)),
        commit_version=int(version), epoch=int(epoch))


def record_window(w: Dict[str, Any]) -> None:
    """One injected fault / maintenance window (nemesis kinded record)."""
    j = _g[0]
    if j is None:
        return
    detail = {k: v for k, v in w.items() if k not in ("kind", "t0", "t1")}
    j.record("fault_window",
             BBWindow(kind=str(w.get("kind", "fault")),
                      t0=float(w.get("t0", 0.0)),
                      t1=float(w.get("t1", w.get("t0", 0.0))),
                      detail=detail))


def record_scenario(name: str, seed: int, engine_mode: str,
                    signature: Dict[str, Any]) -> None:
    """The scenario-atlas stamp (real/scenarios.py build_signature):
    written once per named campaign while the journal is still
    installed, so a bare journal directory identifies the production
    recipe — and its measured heat/abort signature — that produced it."""
    j = _g[0]
    if j is None:
        return
    j.record("scenario",
             BBScenario(
                 name=str(name), seed=int(seed),
                 engine_mode=str(engine_mode),
                 concentration=float(signature.get("concentration", 0.0)),
                 top_range=signature.get("top_range"),
                 top_share=float(signature.get("top_share", 0.0)),
                 abort_frac=float(signature.get("abort_frac", 0.0)),
                 throttle_frac=float(signature.get("throttle_frac", 0.0)),
                 witnesses=int(signature.get("witnesses", 0))))


def record_snapshot(version: int, oldest: int, entries: int,
                    nbytes: int, ms: float, path: str = "") -> None:
    """One engine-state snapshot written (fault/recovery.py): the
    journaled marker recovery + `cli recovery` anchor the floor on."""
    j = _g[0]
    if j is None:
        return
    j.record("snapshot",
             BBSnapshotEvt(version=int(version), oldest=int(oldest),
                           entries=int(entries), bytes=int(nbytes),
                           ms=round(float(ms), 3), path=path),
             commit_version=int(version))


def record_recovery(res: Dict[str, Any]) -> None:
    """One completed crash-stop recovery arc (fault/recovery.py
    RecoveryResult.as_dict()) — the record `cli recovery` renders."""
    j = _g[0]
    if j is None:
        return
    j.record("recovery",
             BBRecovery(
                 mode=str(res.get("mode", "")),
                 coverage_ok=bool(res.get("coverage_ok", True)),
                 snapshot_version=int(res.get("snapshot_version", -1)),
                 recovered_version=int(res.get("recovered_version", -1)),
                 oldest=int(res.get("oldest", 0)),
                 snapshot_entries=int(res.get("snapshot_entries", 0)),
                 replayed_batches=int(res.get("replayed_batches", 0)),
                 verdict_mismatches=int(res.get("verdict_mismatches", 0)),
                 blackout_ms=round(float(res.get("blackout_ms", 0.0)), 3),
                 progcache_hits=int(res.get("progcache_hits", 0)),
                 progcache_misses=int(res.get("progcache_misses", 0)),
                 warm_ms=round(float(res.get("warm_ms", 0.0)), 3),
                 error=res.get("error")),
             commit_version=int(res.get("recovered_version", -1)))
