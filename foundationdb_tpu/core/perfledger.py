"""Compile & memory ledger: every device-program build, priced.

The serving path's compile story was scattered until now: `EnginePerf`
counted HOW MANY programs an engine built (`compiles`, the
zero-steady-state-compile guard) and `warmup_ms` said what the whole
warmup cost, but nothing recorded what each compile WAS — which (bucket,
history-search mode, dispatch mode) shape, how long the build took, and
what the compiled artifact costs to run: XLA's own `cost_analysis()`
flops/bytes-accessed estimate and `memory_analysis()` peak-memory
breakdown (argument + output + temp + alias bytes — the HBM the program
pins while it runs). Those numbers are the before/after evidence the
EngineSpec refactor and the PAM-style history table (ROADMAP items 2-3)
need, and the per-compile durations are exactly the rewarm bill the
chaos campaigns price at 3x budget on every ResilientEngine swap-back.

`PerfLedger` is a bounded ring of per-compile records plus running
totals, registered with the telemetry hub like every other source
(`perf.<label>.*` series -> the `fdbtpu_perf` Prometheus family), riding
engine_health -> ratekeeper -> CC status doc -> `tools/cli.py perf`,
which joins it with the PR 11 `state_bytes` pressure gauge into one
memory view. Recording draws no rng and costs two dict updates — the
analysis is read off the ALREADY-compiled artifact, never triggering a
compile itself — so the layer is observational by construction.

Sampled device timing lives next door (ops/host_engine.py): the
`resolver_device_time_sample_rate` knob makes every Nth dispatch stamp
its enqueue time and record the enqueue->ready wall interval when its
results land on the ALREADY-non-blocking drain paths (step force, fused
scans, the device loop's `poll()`); `sample_every_from_rate` converts
the knob's fraction into that deterministic 1-in-N cadence (counter
based — no rng draw, so enabling sampling can never shift a simulation's
random stream, and abort sets are bit-identical either way).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

#: ledger-ring size fallback when the knob registry is unavailable
DEFAULT_LEDGER_SIZE = 128

#: the per-record fields every ledger row carries (tests pin the schema;
#: analysis fields may be None when the backend exposes no analysis —
#: e.g. jit-warm mesh programs, where the build is not an AOT artifact)
RECORD_FIELDS = ("engine", "bucket", "n_chunks", "search_mode",
                 "dispatch_mode", "kind", "duration_ms", "flops",
                 "bytes_accessed", "peak_bytes", "generated_code_bytes")


def ledger_size_from_knobs() -> int:
    from .knobs import SERVER_KNOBS

    try:
        return int(getattr(SERVER_KNOBS, "resolver_perf_ledger_size"))
    except (AttributeError, TypeError, ValueError):
        return DEFAULT_LEDGER_SIZE


def sample_every_from_rate(rate: Optional[float]) -> int:
    """The `resolver_device_time_sample_rate` knob (or a constructor
    override) as a deterministic 1-in-N dispatch cadence: 0 disables
    (returns 0), otherwise every `round(1/rate)`-th dispatch is sampled
    (1.0 -> every dispatch). Counter-based on purpose — a rng draw here
    would shift every simulation's random stream for a knob that only
    reads clocks."""
    if rate is None:
        from .knobs import SERVER_KNOBS

        rate = float(getattr(SERVER_KNOBS, "resolver_device_time_sample_rate",
                             0.0) or 0.0)
    rate = float(rate)
    if rate <= 0.0:
        return 0
    return max(1, round(1.0 / min(rate, 1.0)))


def analyze_compiled(compiled: Any) -> Dict[str, Optional[int]]:
    """Cost/memory analysis off an already-compiled jax artifact:
    `cost_analysis()` flops + bytes accessed, `memory_analysis()` peak
    device bytes (argument + output + temp + alias — what the program
    pins in HBM while it runs) and generated-code size. Every field is
    None when the handle is not an AOT artifact (jit-warm mesh programs)
    or the backend withholds the analysis; reading the analysis never
    compiles anything."""
    out: Dict[str, Optional[int]] = {"flops": None, "bytes_accessed": None,
                                     "peak_bytes": None,
                                     "generated_code_bytes": None}
    try:
        ca = compiled.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        if d:
            if d.get("flops") is not None:
                out["flops"] = int(d["flops"])
            if d.get("bytes accessed") is not None:
                out["bytes_accessed"] = int(d["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        peak = 0
        for f in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes"):
            peak += int(getattr(ma, f, 0) or 0)
        out["peak_bytes"] = peak
        out["generated_code_bytes"] = int(
            getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    except Exception:
        pass
    return out


class PerfLedger:
    """Bounded ring of per-compile records + running totals for one
    engine (registered per engine like EnginePerf, so a process hosting
    several engines keeps their compile bills apart)."""

    def __init__(self, size: Optional[int] = None):
        self.records: deque = deque(maxlen=size if size is not None
                                    else ledger_size_from_knobs())
        #: compile counts / total build ms split by kind ("warmup" =
        #: inside warmup()/ensure_warm, "steady" = a serving-path build —
        #: the compile-stall the AOT ladder exists to prevent)
        self.compiles: Dict[str, int] = {}
        self.compile_ms: Dict[str, float] = {}
        #: max peak_bytes over every analyzed record — the engine's
        #: largest single-program HBM pin
        self.peak_bytes = 0
        self.flops_total = 0
        self.bytes_accessed_total = 0
        #: on-disk program-cache accounting (core/progcache.py): event
        #: counts ("hit"/"miss"/"store") + bytes moved + wall ms — kept
        #: OUT of `compiles` so a progcache-warm engine still reports
        #: zero compiles
        self.progcache: Dict[str, int] = {}
        self.progcache_bytes = 0
        self.progcache_ms = 0.0

    def record_compile(self, *, engine: str, bucket: int, n_chunks: int,
                       search_mode: str, dispatch_mode: str, kind: str,
                       duration_ms: float,
                       compiled: Any = None,
                       analysis: Optional[Dict[str, Optional[int]]] = None
                       ) -> dict:
        """File one program build. `compiled` (preferred) is analyzed in
        place; `analysis` lets callers pass a precomputed dict."""
        if analysis is None:
            analysis = (analyze_compiled(compiled) if compiled is not None
                        else {"flops": None, "bytes_accessed": None,
                              "peak_bytes": None,
                              "generated_code_bytes": None})
        rec = {"engine": engine, "bucket": int(bucket),
               "n_chunks": int(n_chunks), "search_mode": search_mode,
               "dispatch_mode": dispatch_mode, "kind": kind,
               "duration_ms": round(float(duration_ms), 3), **analysis}
        self.records.append(rec)
        self.compiles[kind] = self.compiles.get(kind, 0) + 1
        self.compile_ms[kind] = (self.compile_ms.get(kind, 0.0)
                                 + float(duration_ms))
        if analysis.get("peak_bytes"):
            self.peak_bytes = max(self.peak_bytes, analysis["peak_bytes"])
        if analysis.get("flops"):
            self.flops_total += analysis["flops"]
        if analysis.get("bytes_accessed"):
            self.bytes_accessed_total += analysis["bytes_accessed"]
        return rec

    def record_progcache(self, *, engine: str, bucket: int, event: str,
                         nbytes: int = 0, duration_ms: float = 0.0) -> None:
        """File one on-disk program-cache event (`hit` / `miss` /
        `store`, core/progcache.py). Deliberately NOT a compile record:
        a cache hit is the absence of a compile, so it must not touch
        `compiles` (the zero-steady-state-compile guard keeps its
        meaning) or the pinned RECORD_FIELDS row schema."""
        del engine, bucket  # keyed per-ledger already (one ledger/engine)
        self.progcache[event] = self.progcache.get(event, 0) + 1
        self.progcache_bytes += int(nbytes)
        self.progcache_ms += float(duration_ms)

    def rows(self) -> List[dict]:
        return list(self.records)

    def snapshot(self, max_rows: int = 16) -> dict:
        """The status-document fragment (engine_health -> ratekeeper ->
        CC status doc -> `cli perf`): totals plus the newest rows."""
        return {
            "compiles": dict(sorted(self.compiles.items())),
            "compile_ms": {k: round(v, 1)
                           for k, v in sorted(self.compile_ms.items())},
            "peak_bytes": self.peak_bytes,
            "flops_total": self.flops_total,
            "bytes_accessed_total": self.bytes_accessed_total,
            "progcache": dict(sorted(self.progcache.items())),
            "progcache_bytes": self.progcache_bytes,
            "progcache_ms": round(self.progcache_ms, 1),
            "rows": list(self.records)[-max_rows:],
        }
