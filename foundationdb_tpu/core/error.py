"""Numbered error codes.

Analog of the reference's error system (flow/error_definitions.h, flow/Error.h):
every recoverable failure is a numbered error, and the client's on_error retry
loop keys off specific codes. Numbers match the reference where the concept
maps 1:1 so users of the reference find familiar codes.
"""
from __future__ import annotations


class FDBError(Exception):
    def __init__(self, code: int, name: str, message: str = ""):
        super().__init__(f"{name} ({code})" + (f": {message}" if message else ""))
        self.code = code
        self.name = name

    def is_retryable(self) -> bool:
        return self.code in _RETRYABLE

    def is_maybe_committed(self) -> bool:
        return self.code in _MAYBE_COMMITTED


_REGISTRY: dict[int, tuple[str, str]] = {}
_RETRYABLE: set[int] = set()
_MAYBE_COMMITTED: set[int] = set()


def _define(code: int, name: str, desc: str, retryable: bool = False, maybe_committed: bool = False):
    _REGISTRY[code] = (name, desc)
    if retryable:
        _RETRYABLE.add(code)
    if maybe_committed:
        _MAYBE_COMMITTED.add(code)

    def make(message: str = "") -> FDBError:
        return FDBError(code, name, message)

    return make


class OperationCancelled(BaseException):
    """Actor cancellation (flow: actor_cancelled). Deliberately NOT an
    FDBError/Exception subclass: the reference's actor compiler propagates
    cancellation through user catch blocks automatically, and retry loops
    written as `except FDBError` must never swallow a cancellation and keep
    looping. Carries the same shape as FDBError for uniform reporting."""

    def __init__(self, message: str = ""):
        super().__init__("operation_cancelled (1101)" + (f": {message}" if message else ""))
        self.code = 1101
        self.name = "operation_cancelled"

    def is_retryable(self) -> bool:
        return False

    def is_maybe_committed(self) -> bool:
        return False


def operation_cancelled(message: str = "") -> OperationCancelled:
    return OperationCancelled(message)


# Codes mirror flow/error_definitions.h where applicable.
operation_failed = _define(1000, "operation_failed", "Operation failed")
timed_out = _define(1004, "timed_out", "Operation timed out")
watch_cancelled = _define(1101, "watch_cancelled", "Watch expired by the server", retryable=True)
transaction_too_old = _define(1007, "transaction_too_old", "Read version is too old", retryable=True)
future_version = _define(1009, "future_version", "Version is ahead of storage", retryable=True)
wrong_shard_server = _define(1001, "wrong_shard_server", "Shard is on another server", retryable=True)
not_committed = _define(1020, "not_committed", "Transaction conflicted, not committed", retryable=True)
commit_unknown_result = _define(
    1021, "commit_unknown_result", "Commit result unknown", retryable=True, maybe_committed=True
)
transaction_cancelled = _define(1025, "transaction_cancelled", "Transaction cancelled")
connection_failed = _define(1026, "connection_failed", "Connection failed", retryable=True)
coordinators_changed = _define(1027, "coordinators_changed", "Coordinators changed", retryable=True)
request_maybe_delivered = _define(1030, "request_maybe_delivered", "Request may or may not have been delivered")
broken_promise = _define(1100, "broken_promise", "The promise was dropped before being set")
master_recovery_failed = _define(1203, "master_recovery_failed", "Master recovery failed")
tlog_stopped = _define(1011, "tlog_stopped", "TLog stopped")
worker_removed = _define(1202, "worker_removed", "Worker removed by cluster controller")
recruitment_failed = _define(1200, "recruitment_failed", "Role recruitment failed")
master_tlog_failed = _define(1205, "master_tlog_failed", "Master terminating because a TLog failed")
movekeys_conflict = _define(1010, "movekeys_conflict", "Concurrent data-distribution move")
database_locked = _define(1038, "database_locked", "Database is locked (DR switchover / management)")
transaction_throttled = _define(
    1213, "transaction_throttled",
    "Tenant over its admission rate; retry after backoff", retryable=True)
transaction_conflict_predicted = _define(
    1214, "transaction_conflict_predicted",
    "Conflict scheduler predicts this transaction is doomed; refresh read "
    "version and retry", retryable=True)
please_reboot = _define(1207, "please_reboot", "Process should reboot")
io_error = _define(1510, "io_error", "Disk i/o operation failed")
file_not_found = _define(1511, "file_not_found", "File not found")
key_outside_legal_range = _define(2004, "key_outside_legal_range", "Key outside legal range")
inverted_range = _define(2005, "inverted_range", "Range begin key exceeds end key")
used_during_commit = _define(2017, "used_during_commit", "Operation issued while a commit was outstanding")
accessed_unreadable = _define(1036, "accessed_unreadable", "Read or wrote an unreadable key (versionstamped this transaction)")
client_invalid_operation = _define(2000, "client_invalid_operation", "Invalid API operation")
conflict_capacity_exceeded = _define(
    2101, "conflict_capacity_exceeded", "Device conflict table capacity exceeded"
)
device_fault = _define(
    2103, "device_fault", "Conflict engine device dispatch failed", retryable=True
)
key_too_large = _define(2102, "key_too_large", "Key exceeds the engine's exact-compare width")
end_of_stream = _define(1, "end_of_stream", "End of stream")
internal_error = _define(4100, "internal_error", "An internal error occurred")
