from .types import (
    Version,
    Key,
    Value,
    KeyRange,
    Mutation,
    MutationType,
    CommitTransaction,
    TransactionCommitResult,
    key_after,
    strinc,
    single_key_range,
    ALL_KEYS,
    VERSIONS_PER_SECOND,
    MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
)
from .error import FDBError
from .rng import DeterministicRandom, g_random, g_nondeterministic_random
from .knobs import SERVER_KNOBS, CLIENT_KNOBS, FLOW_KNOBS
from .trace import (TraceEvent, TraceBatch, g_trace, g_trace_batch, Severity,
                    Span, g_spans, span, span_event, span_now)
