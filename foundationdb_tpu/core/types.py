"""Core value types of the framework.

TPU-first re-design of the reference's value-type layer
(reference: fdbclient/FDBTypes.h, fdbclient/CommitTransaction.h:29-121).

Keys are plain ``bytes`` ordered bytewise (shorter-is-less on equal prefix),
exactly the ordering of the reference comparator (fdbserver/SkipList.cpp:113-120).
Versions are int64, advancing ~1e6 per wall-clock second like the reference
master's version authority (fdbserver/masterserver.actor.cpp:786).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

Version = int  # int64 semantics
Key = bytes
Value = bytes

INVALID_VERSION: Version = -1
MAX_VERSION: Version = (1 << 62)

#: Versions per wall-clock second handed out by the version authority
#: (reference: VERSIONS_PER_SECOND, fdbserver/Knobs.cpp).
VERSIONS_PER_SECOND: int = 1_000_000

#: The MVCC / conflict-detection window: 5 seconds of versions
#: (reference: MAX_WRITE_TRANSACTION_LIFE_VERSIONS, fdbserver/Knobs.cpp).
MAX_WRITE_TRANSACTION_LIFE_VERSIONS: int = 5 * VERSIONS_PER_SECOND

#: End of the user keyspace; system keys live in [SYSTEM_KEY_PREFIX, \xff\xff).
USER_KEY_END: Key = b"\xff"
SYSTEM_KEY_PREFIX: Key = b"\xff"


def is_point_range(begin: Key, end: Key) -> bool:
    """True iff the half-open range is exactly [k, k+'\\x00') — the conflict
    kernel's cheap POINT row shape (its end key is synthesized on device).
    The single definition shared by the wire encoder and the host router."""
    return len(end) == len(begin) + 1 and end[-1] == 0 and end[:-1] == begin


def key_after(key: Key) -> Key:
    """Smallest key strictly greater than ``key`` (reference: keyAfter, FDBTypes.h)."""
    return key + b"\x00"


def strinc(key: Key) -> Key:
    """Smallest key strictly greater than every key having ``key`` as a prefix
    (reference: strinc, fdbclient/NativeAPI / flow)."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("strinc of all-\\xff key has no finite answer")
    return k[:-1] + bytes([k[-1] + 1])


@dataclass(frozen=True, order=True)
class KeyRange:
    """Half-open key range [begin, end). Empty when begin >= end."""

    begin: Key
    end: Key

    def __post_init__(self) -> None:
        assert isinstance(self.begin, bytes) and isinstance(self.end, bytes)

    @property
    def empty(self) -> bool:
        return self.begin >= self.end

    def contains(self, key: Key) -> bool:
        return self.begin <= key < self.end

    def intersects(self, other: "KeyRange") -> bool:
        return self.begin < other.end and other.begin < self.end

    def intersection(self, other: "KeyRange") -> "KeyRange":
        return KeyRange(max(self.begin, other.begin), min(self.end, other.end))


def single_key_range(key: Key) -> KeyRange:
    return KeyRange(key, key_after(key))


ALL_KEYS = KeyRange(b"", b"\xff\xff")


class MutationType(enum.IntEnum):
    """Mutation opcodes (reference: MutationRef::Type, fdbclient/CommitTransaction.h:31)."""

    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD_VALUE = 2
    DEBUG_KEY_RANGE = 3
    DEBUG_KEY = 4
    NO_OP = 5
    AND = 6
    OR = 7
    XOR = 8
    APPEND_IF_FITS = 9
    AVAILABLE_FOR_REUSE = 10
    RESERVED_FOR_LOG_PROTOCOL_MESSAGE = 11
    MAX = 12
    MIN = 13
    SET_VERSIONSTAMPED_KEY = 14
    SET_VERSIONSTAMPED_VALUE = 15
    BYTE_MIN = 16
    BYTE_MAX = 17
    MIN_V2 = 18
    AND_V2 = 19


ATOMIC_MUTATIONS = frozenset(
    {
        MutationType.ADD_VALUE,
        MutationType.AND,
        MutationType.OR,
        MutationType.XOR,
        MutationType.APPEND_IF_FITS,
        MutationType.MAX,
        MutationType.MIN,
        MutationType.SET_VERSIONSTAMPED_KEY,
        MutationType.SET_VERSIONSTAMPED_VALUE,
        MutationType.BYTE_MIN,
        MutationType.BYTE_MAX,
        MutationType.MIN_V2,
        MutationType.AND_V2,
    }
)

SINGLE_KEY_MUTATIONS = ATOMIC_MUTATIONS | {MutationType.SET_VALUE}

#: Mutations the proxy rewrites into SET_VALUE at commit time; storage
#: servers must never see them (fdbclient/Atomic.h:258-271).
VERSIONSTAMP_MUTATIONS = frozenset(
    {MutationType.SET_VERSIONSTAMPED_KEY, MutationType.SET_VERSIONSTAMPED_VALUE}
)

#: Atomic ops evaluable at a storage server (everything except versionstamps).
STORAGE_ATOMIC_MUTATIONS = ATOMIC_MUTATIONS - VERSIONSTAMP_MUTATIONS

VERSIONSTAMP_SIZE = 10


def place_versionstamp(version: Version, batch_index: int) -> bytes:
    """The 10-byte versionstamp: 8-byte big-endian commit version + 2-byte
    big-endian transaction number within the batch (reference:
    placeVersionstamp, fdbclient/Atomic.h:250-256)."""
    return version.to_bytes(8, "big") + (batch_index & 0xFFFF).to_bytes(2, "big")


def validate_versionstamp_param(param: bytes) -> bool:
    """True iff a SET_VERSIONSTAMPED_* param is well-formed: a trailing
    little-endian int32 naming a stamp position fully inside the remaining
    bytes (reference: the client rejects bad offsets in
    ReadYourWrites.actor.cpp before the mutation ever reaches a proxy)."""
    if len(param) < 4 + VERSIONSTAMP_SIZE:
        return False
    pos = int.from_bytes(param[-4:], "little", signed=True)
    return 0 <= pos and pos + VERSIONSTAMP_SIZE <= len(param) - 4


def transform_versionstamp_mutation(m: "Mutation", version: Version, batch_index: int) -> "Mutation":
    """Rewrite a SET_VERSIONSTAMPED_{KEY,VALUE} mutation into a plain
    SET_VALUE with the stamp substituted, at the position named by the
    little-endian int32 trailing the stamped param (reference:
    transformVersionstampMutation, fdbclient/Atomic.h:258-271; applied by the
    proxy at MasterProxyServer.actor.cpp:270-275)."""
    stamped_key = m.type == MutationType.SET_VERSIONSTAMPED_KEY
    param = m.param1 if stamped_key else m.param2
    if len(param) >= 4:
        pos = int.from_bytes(param[-4:], "little", signed=True)
        param = param[:-4]
        if 0 <= pos and pos + VERSIONSTAMP_SIZE <= len(param):
            stamp = place_versionstamp(version, batch_index)
            param = param[:pos] + stamp + param[pos + VERSIONSTAMP_SIZE:]
    if stamped_key:
        return Mutation(MutationType.SET_VALUE, param, m.param2)
    return Mutation(MutationType.SET_VALUE, m.param1, param)


@dataclass(frozen=True)
class Mutation:
    """One mutation: (type, param1, param2) — param1 is the key (or range begin),
    param2 the value (or range end)."""

    type: MutationType
    param1: bytes
    param2: bytes

    def expected_size(self) -> int:
        return len(self.param1) + len(self.param2)


@dataclass
class CommitTransaction:
    """Wire form of a transaction submitted for commit
    (reference: CommitTransactionRef, fdbclient/CommitTransaction.h:89-121)."""

    read_conflict_ranges: List[KeyRange] = field(default_factory=list)
    write_conflict_ranges: List[KeyRange] = field(default_factory=list)
    mutations: List[Mutation] = field(default_factory=list)
    read_snapshot: Version = 0
    #: commits through a database lock (the LOCK_AWARE transaction option;
    #: management/DR transactions set it — reference: lockDatabase,
    #: fdbclient/ManagementAPI.actor.cpp)
    lock_aware: bool = False

    def set(self, key: Key, value: Value) -> None:
        self.mutations.append(Mutation(MutationType.SET_VALUE, key, value))
        self.write_conflict_ranges.append(single_key_range(key))

    def clear(self, rng: KeyRange) -> None:
        self.mutations.append(Mutation(MutationType.CLEAR_RANGE, rng.begin, rng.end))
        self.write_conflict_ranges.append(rng)

    def atomic_op(self, key: Key, value: Value, op: MutationType) -> None:
        assert op in ATOMIC_MUTATIONS
        self.mutations.append(Mutation(op, key, value))
        self.write_conflict_ranges.append(single_key_range(key))

    def expected_size(self) -> int:
        n = sum(len(r.begin) + len(r.end) for r in self.read_conflict_ranges)
        n += sum(len(r.begin) + len(r.end) for r in self.write_conflict_ranges)
        n += sum(m.expected_size() for m in self.mutations)
        return n

    def conflict_wire_info(self) -> Tuple[bytes, bool, int]:
        """This transaction's conflict ranges as one columnar wire block
        (core/wire.py) plus (all_point, max_key_len) classification computed
        during the encode. Client-side work, cached against the range tuples
        themselves (tuple compare is identity-shortcut pointer checks, so a
        cache hit is O(ranges) pointer compares — in-place range replacement
        invalidates correctly)."""
        from . import wire

        key = (tuple(self.read_conflict_ranges), tuple(self.write_conflict_ranges))
        cached = getattr(self, "_wire_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        info = wire.conflict_wire_ex(key[0], key[1])
        self._wire_cache = (key, info)
        return info

    def conflict_wire_block(self) -> bytes:
        return self.conflict_wire_info()[0]


class TransactionCommitResult(enum.IntEnum):
    """Per-transaction resolution verdict (reference: ConflictSet.h:36-40).

    The integer values are load-bearing: the proxy combines votes from all
    touched resolver shards with ``min`` (MasterProxyServer.actor.cpp:489-500),
    so CONFLICT < TOO_OLD < COMMITTED must hold.
    """

    CONFLICT = 0
    TOO_OLD = 1
    COMMITTED = 2


#: reference: CLIENT_KNOBS->VALUE_SIZE_LIMIT (fdbclient/Knobs.cpp:56)
VALUE_SIZE_LIMIT: int = 100_000


def apply_atomic_op(op: MutationType, existing: Optional[Value], param: Value) -> Value:
    """Pure atomic-op evaluation applied at storage servers — reference-exact
    (fdbclient/Atomic.h). Except for APPEND_IF_FITS and the BYTE_* winners,
    the result always has len(param): the existing value is implicitly
    truncated/zero-extended to the operand's width ("the window")."""
    old = existing if existing is not None else b""
    n = len(param)
    m = min(len(old), n)

    def old_window() -> Value:
        # existing truncated to len(param) and zero-extended (doMax/doMin's
        # copy loops, Atomic.h:146-155,176-199).
        return old[:n] + b"\x00" * (n - m)

    if op == MutationType.ADD_VALUE:
        # doLittleEndianAdd (Atomic.h:27-48): carry propagates through all of
        # param's bytes; result is len(param).
        if not old or not param:
            return param
        a = int.from_bytes(old[:n], "little")
        b = int.from_bytes(param, "little")
        return ((a + b) & ((1 << (8 * n)) - 1)).to_bytes(n, "little")
    if op in (MutationType.AND, MutationType.AND_V2):
        # doAnd (Atomic.h:50-63): bytes beyond the existing value are 0; an
        # absent/empty existing value yields all-zeros. V2 (Atomic.h:65-70)
        # returns param when the key is missing.
        if op == MutationType.AND_V2 and existing is None:
            return param
        if not param:
            return param
        return bytes(x & y for x, y in zip(old, param)) + b"\x00" * (n - m)
    if op == MutationType.OR:
        if not old or not param:
            return param
        return bytes(x | y for x, y in zip(old, param)) + param[m:]
    if op == MutationType.XOR:
        if not old or not param:
            return param
        return bytes(x ^ y for x, y in zip(old, param)) + param[m:]
    if op == MutationType.APPEND_IF_FITS:
        # doAppendIfFits (Atomic.h:107-126)
        if not old:
            return param
        if not param:
            return old
        return old + param if len(old) + len(param) <= VALUE_SIZE_LIMIT else old
    if op == MutationType.MAX:
        # doMax (Atomic.h:128-158): little-endian compare over param's width;
        # param wins ties; existing wins as its zero-extended window.
        if not old or not param:
            return param
        pw = int.from_bytes(param, "little")
        ow = int.from_bytes(old_window(), "little")
        return param if pw >= ow else old_window()
    if op == MutationType.BYTE_MAX:
        # doByteMax (Atomic.h:160-168): winner returned verbatim (full length).
        if existing is None:
            return param
        return old if old > param else param
    if op in (MutationType.MIN, MutationType.MIN_V2):
        # doMin (Atomic.h:170-213); V2 (Atomic.h:215-220) returns param when
        # the key is missing. An absent key in MIN behaves as zeros.
        if op == MutationType.MIN_V2 and existing is None:
            return param
        if not param:
            return param
        pw = int.from_bytes(param, "little")
        ow = int.from_bytes(old_window(), "little")
        return param if pw <= ow else old_window()
    if op == MutationType.BYTE_MIN:
        # doByteMin (Atomic.h:222-230)
        if existing is None:
            return param
        return old if old < param else param
    raise ValueError(f"not an atomic op: {op}")


# -- wire registration (core/wire.py named records for disk state) ----------
from . import wire as _wire

_wire.register_record(Mutation)
_wire.register_record(KeyRange)
# whole transactions ride the black-box journal's batch records
# (core/blackbox.py) — the differential-replay unit
_wire.register_record(CommitTransaction)
_wire.register_enum(MutationType)
