"""Counters: per-role operational metrics with periodic trace emission.

Analog of flow/Stats.h (Counter, CounterCollection, traceCounters): roles
register named counters in a collection; a recurring actor emits one
`*Metrics` trace event per interval with the values and rates, and the
status document surfaces the same numbers. Counters are plain ints — the
deterministic sim needs no atomics (SURVEY.md §5 race-detection strategy).
"""
from __future__ import annotations

from typing import Dict, Optional

from .trace import TraceEvent


class Counter:
    __slots__ = ("name", "value", "_last_value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._last_value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def rate_since_last(self, dt: float) -> float:
        d = self.value - self._last_value
        self._last_value = self.value
        return d / dt if dt > 0 else 0.0


class CounterCollection:
    """reference: CounterCollection + traceCounters (flow/Stats.h:112).
    With `tdmetrics` attached (a TDMetricCollection), every periodic
    trace also records each counter's level into the time-series registry
    — one hookup instruments every role for the MetricLogger."""

    def __init__(self, role: str, id: object = None, tdmetrics=None):
        self.role = role
        self.id = id
        self.counters: Dict[str, Counter] = {}
        self.tdmetrics = tdmetrics

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def add(self, name: str, n: int = 1) -> None:
        self.counter(name).add(n)

    def as_dict(self) -> Dict[str, int]:
        return {name: c.value for name, c in sorted(self.counters.items())}

    def trace(self, dt: float) -> None:
        ev = TraceEvent(f"{self.role}Metrics", id=self.id)
        for name, c in sorted(self.counters.items()):
            ev.detail(name, c.value)
            ev.detail(f"{name}Rate", round(c.rate_since_last(dt), 2))
            if self.tdmetrics is not None:
                mid = f".{self.id}" if self.id is not None else ""
                self.tdmetrics.int64(f"{self.role}{mid}.{name}").set(c.value)
        ev.log()

    async def run_logger(self, interval: float = 5.0):
        """Periodic traceCounters actor; spawn on the owning process."""
        from ..sim.loop import delay

        while True:
            await delay(interval)
            self.trace(interval)
