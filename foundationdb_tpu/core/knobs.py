"""Typed, name-addressed tunables.

Analog of the reference's three knob registries (flow/Knobs.h:31-45,
fdbserver/Knobs.cpp). Knobs default in one place, can be overridden by name
(`--knob_name=value` style), and in simulation BUGGIFY may randomize marked
knobs so rare configurations get exercised.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .rng import DeterministicRandom


class Knobs:
    def __init__(self) -> None:
        self._values: Dict[str, Any] = {}
        self._randomizers: Dict[str, Callable[[DeterministicRandom], Any]] = {}

    def init(self, name: str, value: Any, buggify: Optional[Callable[[DeterministicRandom], Any]] = None):
        self._values[name] = value
        if buggify is not None:
            self._randomizers[name] = buggify
        return value

    def set_knob(self, name: str, value: str) -> None:
        if name not in self._values:
            raise KeyError(f"unknown knob: {name}")
        cur = self._values[name]
        if isinstance(cur, bool):
            self._values[name] = value.lower() in ("1", "true", "on")
        elif isinstance(cur, int):
            self._values[name] = int(value)
        elif isinstance(cur, float):
            self._values[name] = float(value)
        else:
            self._values[name] = value

    def randomize(self, rng: DeterministicRandom, probability: float = 0.25) -> None:
        """BUGGIFY-style knob randomization, applied per-simulation
        (reference pattern: `init(KNOB, v); if(randomize && BUGGIFY) ...`,
        fdbserver/Knobs.cpp)."""
        for name, fn in self._randomizers.items():
            if rng.random01() < probability:
                self._values[name] = fn(rng)

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["_values"][name.lower()]
        except KeyError:
            raise AttributeError(name)

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._values)


def _make_server_knobs() -> Knobs:
    k = Knobs()
    # Version / MVCC window (reference: fdbserver/Knobs.cpp)
    k.init("versions_per_second", 1_000_000)
    k.init("max_write_transaction_life_versions", 5_000_000)
    k.init("max_read_transaction_life_versions", 5_000_000)
    # Proxy commit batching (reference: COMMIT_TRANSACTION_BATCH_* knobs)
    k.init("commit_transaction_batch_interval", 0.0005, lambda r: r.random01() * 0.005)
    k.init("commit_transaction_batch_count_max", 32768, lambda r: r.random_int(1, 100))
    k.init("commit_transaction_batch_bytes_max", 8 << 20)
    #: bound on a resolver's conflict-history state footprint (reference:
    #: RESOLVER_STATE_MEMORY_LIMIT). Ours is the device interval table —
    #: capacity H x K key words plus versions is a few MB at the default
    #: shape — so the bound is sized with headroom above that; the
    #: resolver reports `state_bytes` and a `state_memory_pressure` flag
    #: in engine_health (server/resolver.py) when the footprint exceeds it
    k.init("resolver_state_memory_limit", 64 << 20)
    k.init("grv_batch_interval", 0.0005, lambda r: r.random01() * 0.005)
    # Ratekeeper (reference: fdbserver/Knobs.cpp ratekeeper section)
    k.init("ratekeeper_update_interval", 0.25)
    k.init("target_storage_queue_bytes", 4 << 20)
    k.init("spring_storage_queue_bytes", 2 << 20)
    k.init("target_tlog_queue_bytes", 1 << 30)
    # TLog spill (reference: updatePersistentData, TLogServer.actor.cpp:539)
    k.init("tlog_spill_bytes", 2 << 20, lambda r: r.random_int(2_000, 200_000))
    #: simulated fsync for diskless tlog roles (the static sim cluster);
    #: the default models a conservative SSD — benchmark profiles
    #: (pipeline/latency_harness.py) set a datacenter-NVMe figure
    k.init("tlog_fsync_seconds", 0.0005)
    k.init("max_transactions_per_second", 1e7)
    # Storage
    k.init("storage_durability_lag_versions", 2_000_000)
    k.init("desired_total_bytes", 150_000)
    #: byte-sample granularity (reference: BYTE_SAMPLING_FACTOR — keys are
    #: sampled with probability size/factor and carry weight `factor`)
    k.init("dd_byte_sample_factor", 200)
    # DataDistribution (reference: DataDistributionTracker split/merge +
    # DataDistributionQueue priorities/parallelism)
    k.init("dd_tracker_interval", 2.0)
    k.init("dd_shard_split_bytes", 100_000, lambda r: r.random_int(4_000, 50_000))
    k.init("dd_shard_merge_bytes", 2_000)
    #: write-bandwidth split trigger (bytes/sec of applied mutations; the
    #: reference splits on SHARD_MAX_BYTES_PER_KSEC); a hot-WRITE shard
    #: splits even while its size is under dd_shard_split_bytes
    k.init("dd_shard_split_bandwidth", 200_000)
    #: concurrent relocations the DD queue may run (reference:
    #: DD_MOVE_KEYS_PARALLELISM)
    k.init("dd_move_parallelism", 2)
    # Failure detection (reference: CC failureDetectionServer)
    k.init("failure_detection_delay", 1.0, lambda r: 0.2 + r.random01() * 2)
    k.init("heartbeat_interval", 0.25)
    # Device-fault tolerance (fault/resilient.py; docs/fault_tolerance.md).
    # Deliberately no BUGGIFY randomizers: the nemesis campaign stresses
    # these directly, and randomizer draws would shift every sim's rng
    # stream for knobs that only matter when a device is sick.
    #: watchdog: a dispatch outstanding longer than this is a fault
    k.init("resolver_dispatch_timeout", 0.5)
    #: retries after the first failed dispatch before failing over
    k.init("resolver_retry_budget", 2)
    #: initial retry backoff (exponential, jittered x[0.5, 1.5))
    k.init("resolver_retry_backoff", 0.05)
    #: fraction of healthy device batches cross-validated against a
    #: shadow-rebuilt oracle (corruption detector)
    k.init("resolver_probe_rate", 0.05)
    #: clean device-vs-oracle batches required to swap back after re-warm
    k.init("resolver_probation_batches", 4)
    #: batches served on the failover oracle before attempting a re-warm
    k.init("resolver_failover_min_batches", 4)
    #: admission fraction while any resolver engine is degraded
    k.init("resolver_degraded_tps_fraction", 0.25)
    # TPU conflict engine capacities (ours)
    k.init("conflict_table_capacity", 1 << 16)
    k.init("conflict_key_words", 4)
    k.init("conflict_max_batch_txns", 1 << 12)
    k.init("conflict_max_batch_ranges", 1 << 13)
    # Bucketed kernel ladder + budget-driven batching (docs/perf.md).
    #: comma-separated sub-capacity batch sizes compiled alongside the top
    #: shape ("512,1024"); empty = single bucket. Each must be a multiple
    #: of 32; an engine keeps only sizes below its own top shape (the
    #: global knob serves engines of every size), so oversized entries are
    #: ignored, not errors.
    k.init("resolver_bucket_ladder", "")
    #: client-observed p99 commit budget the adaptive batcher fits batches
    #: into — the resolver-inclusive share of the reference's < 3 ms
    #: end-to-end commit target (performance.rst:36,49; BASELINE.md's
    #: 1.5-2.5 ms window). bench.py's latency_under_load production-point
    #: filter reads the same knob.
    k.init("resolver_p99_budget_ms", 2.5)
    #: EWMA smoothing for observed per-bucket device latency (0 < a <= 1)
    k.init("resolver_latency_ewma_alpha", 0.25)
    #: history-query strategy of the conflict kernel (docs/perf.md
    #: "History search modes"): "fused_sort" re-sorts the capacity-H
    #: boundary table with every batch; "bsearch" sorts only the batch
    #: rows and binary-searches the already-sorted table; "auto" (default)
    #: picks per compiled bucket — bsearch when the batch rows are small
    #: relative to the table (T << H). Abort sets are bit-identical either
    #: way (the parity suite cross-checks the modes); this knob only moves
    #: device time. Engines take a `history_search=` constructor override.
    #: Deliberately no BUGGIFY randomizer: the modes are proven equivalent
    #: directly, and a randomizer draw would shift every sim's rng stream.
    k.init("resolver_history_search_mode", "auto")
    #: history STRUCTURE of the device interval table (docs/perf.md
    #: "Incremental history maintenance"): "monolithic" (default) re-merges
    #: the full capacity-H boundary table every batch; "tiered" appends
    #: each batch's committed-write union as a sorted run and compacts
    #: runs into the base table only when the run slots fill, so
    #: steady-state apply cost scales with the batch, not capacity, and
    #: MVCC-horizon/TTL GC becomes a range deletion (an elementwise
    #: horizon rebase; physical reclamation rides the lazy merge). Abort
    #: sets are bit-identical either way (the cross-structure parity
    #: suite pins it); this knob moves apply/GC device time. Engines take
    #: a `history_structure=` constructor override; a flip is a clean
    #: progcache miss (core/progcache.py key(structure=)). Deliberately
    #: no BUGGIFY randomizer: equivalence is proven directly, and a
    #: randomizer draw would shift every sim's rng stream.
    k.init("resolver_history_structure", "monolithic")
    #: run slots of the tiered history structure (KernelConfig
    #: .history_runs): how many sorted runs accumulate before the lazy
    #: device-side merge compacts them into the base table. More slots =
    #: cheaper steady-state applies but more run probes per query; >= 2
    #: required (one slot would merge every batch). Only read when the
    #: structure is "tiered".
    k.init("resolver_history_runs", 8)
    #: device-resident resolver loop (docs/perf.md "Device-resident
    #: loop"), consulted by the engine-mode router
    #: (host_engine.default_engine_mode — wall-clock nodes pick it up via
    #: `real/node.py --engine auto`): "" keeps step dispatch; "on" routes
    #: the single-chip engine through ops/device_loop.DeviceLoopEngine
    #: (persistent on-device server step, double-buffered queue,
    #: non-blocking result-ring drain); "pallas" additionally bakes the
    #: fused Pallas commit fixpoint (ops/fixpoint_pallas.py) into every
    #: loop body, with the interpreter fallback off-TPU. Abort sets are bit-identical in every
    #: mode (tests/test_device_loop.py); this knob only moves per-batch
    #: host/dispatch time. Deliberately no BUGGIFY randomizer: the modes
    #: are proven equivalent directly, and a draw would shift sim rng.
    k.init("resolver_device_loop", "")
    # Measured multi-device mesh resolution (docs/perf.md "Measured mesh
    # resolution"). Deliberately no BUGGIFY randomizers: the mesh modes
    # are proven verdict-identical to the serial oracle directly
    # (tests/test_mesh_parity.py) and a randomizer draw would shift
    # every sim's rng stream.
    #: devices the mesh engine spans: 0 = every visible XLA device; an
    #: explicit N takes the first N. Tests and `make mesh-smoke` force 8
    #: virtual CPU devices via XLA_FLAGS=--xla_force_host_platform_
    #: device_count=8, so mesh shapes are exercised without hardware.
    k.init("resolver_mesh_devices", 0)
    #: dispatch units the mesh result ring holds before the host drains
    #: the oldest — the double buffer: 2 keeps one batch's exchange
    #: collectives draining while the next batch's shard-local scan is
    #: already dispatched (parallel/mesh_engine.py)
    k.init("resolver_mesh_queue_depth", 2)
    #: "on" (default): overlapped dispatch — scan/exchange enqueue
    #: async, results drain through the non-blocking ring; "serial"
    #: forces every dispatch unit's outputs before the next enqueue (the
    #: A/B baseline tools/mesh_bench.py records as serialized_ms —
    #: overlapped must beat it)
    k.init("resolver_mesh_overlap", "on")
    # Observability (docs/observability.md).
    #: commit-path span collection (core/trace.py): 0 disables span
    #: recording entirely — instrumented sites pay one attribute check and
    #: allocate nothing (the near-zero-cost guarantee the regression test
    #: pins); > 0 enables collection (the value is reserved for per-batch
    #: sampling). Deliberately no BUGGIFY randomizer: span recording draws
    #: no rng, but enabling it mid-battery would grow the span buffer for
    #: nothing.
    k.init("trace_span_sample_rate", 0.0)
    # Distributed tracing (docs/observability.md "Distributed tracing").
    # Tail-based retention: the trace export decides WHICH traces to keep
    # after the outcome is known — every faulted/retried/throttled request
    # is always kept, plus the slowest clean acks as p99 candidates.
    # Deliberately no BUGGIFY randomizers: retention draws no rng and only
    # matters to wall-clock exports.
    #: fraction of the slowest clean acks retained as p99-candidate traces
    #: (0.02 = every ack at or above ~p98 — a margin around p99 wide
    #: enough that the p99 ack itself is always in the export)
    k.init("trace_tail_latency_frac", 0.02)
    #: hard cap on retained traces per export (report/JSON size bound;
    #: forced-retain error traces take precedence under the cap)
    k.init("trace_tail_max_traces", 512)
    #: dispatch records the ResilientEngine's flight recorder retains — the
    #: bounded ring dumped into quarantine/failover trace events for
    #: post-mortem replay (fault/resilient.py)
    k.init("resolver_flight_recorder_size", 64)
    # Keyspace heat & history-occupancy observability
    # (docs/observability.md "Keyspace heat & occupancy"). Deliberately no
    # BUGGIFY randomizers: heat is proven observational (bit-identical
    # abort sets either way) and a randomizer draw would shift sim rng.
    #: key-range histogram buckets the resolve step aggregates ON DEVICE
    #: per batch (boundary keys sampled from the interval table delimit
    #: them). 0 disables the whole layer: programs emit no heat outputs,
    #: engines build no aggregator, nothing allocates. Default 64 — the
    #: aggregate is a few KB riding an already-async readback, and the
    #: `conflict_heat` bench pins the device-time overhead < 3% at the
    #: production point.
    k.init("resolver_heat_buckets", 64)
    #: per-batch multiplicative decay of the host aggregator's key-range
    #: weights (core/heatmap.py): 1.0 = lifetime totals; 0.98 forgets a
    #: shifted hot spot in ~50 batches so split planning tracks the
    #: CURRENT load, the same windowing rationale as resolution_metrics
    k.init("resolver_heat_decay", 0.98)
    #: shards the aggregator proposes equal-load split points for — the
    #: measured input to multi-chip key-range sharding (ROADMAP item 1)
    k.init("resolver_heat_split_shards", 8)
    #: split-point hysteresis: a freshly derived equal-load split set
    #: replaces the last adopted one only when it improves the measured
    #: worst per-shard imbalance by at least this fraction — two adjacent
    #: scrapes of a stationary stream must not flap the resharding
    #: controller by one bucket (core/heatmap.py split_points)
    k.init("resolver_heat_split_hysteresis", 0.05)
    # Live elasticity: heat-driven online resolver resharding
    # (server/reshard.py; docs/elasticity.md). Deliberately no BUGGIFY
    # randomizers: the drift campaign stresses the controller directly,
    # and these only matter in wall-clock mode where buggify is off.
    #: admission fraction while a reshard is in flight — the ratekeeper
    #: clamps the published rate alongside watchdog_burn_tps_fraction
    #: until the handoff completes (server/ratekeeper.py)
    k.init("reshard_tps_fraction", 0.5)
    #: per-range blackout budget: the freeze -> cutover interval of one
    #: range handoff (the only window the moving range cannot serve) must
    #: stay under this, machine-asserted per executed reshard via the
    #: reshard.blackout trace segments (docs/elasticity.md "Blackout SLO")
    k.init("reshard_blackout_budget_ms", 250.0)
    #: controller evaluation cadence (heat scrape -> plan decision)
    k.init("reshard_eval_interval_s", 0.5)
    #: minimum wall-clock spacing between executed reshards — composes
    #: with the split-point hysteresis to keep the control loop stable
    k.init("reshard_min_interval_s", 1.0)
    #: split trigger: hottest shard's measured write+conflict load share
    #: above this plans a split of that shard at the heat-suggested key
    k.init("reshard_split_share", 0.55)
    #: merge trigger: an adjacent shard pair whose combined share is
    #: below this folds into one engine (frees capacity for hot splits)
    k.init("reshard_merge_share", 0.25)
    #: upper bound on live resolver shards the controller may create
    k.init("reshard_max_shards", 4)
    #: a reshard in flight longer than this is STALLED — the watchdog's
    #: ReshardStalledRule fires and the incident names the frozen range
    #: and the donor engine's health state (core/watchdog.py)
    k.init("reshard_stall_s", 3.0)
    # Performance observatory (docs/observability.md "Performance
    # observatory"). Deliberately no BUGGIFY randomizers: both layers are
    # observational (the ledger reads analysis off already-compiled
    # artifacts; sampling is counter-based and draws no rng), and a
    # randomizer draw would shift every sim's rng stream.
    #: per-compile records the engine's PerfLedger ring retains
    #: (core/perfledger.py: build duration + cost_analysis flops/bytes +
    #: memory_analysis peak HBM per (bucket, search mode, dispatch mode))
    k.init("resolver_perf_ledger_size", 128)
    #: fraction of dispatches that record a measured enqueue->ready
    #: device interval on the already-non-blocking drain paths (step
    #: force, fused scans, device-loop poll) — 1/rate rounds to a
    #: deterministic 1-in-N cadence, no rng; 0 disables. Abort sets are
    #: bit-identical on/off (tests/test_perf_ledger.py); engines take a
    #: `device_time_sample_rate=` constructor override.
    k.init("resolver_device_time_sample_rate", 0.0625)
    # Black-box journal & forensics (core/blackbox.py;
    # docs/observability.md "Black-box journal & forensics").
    # Deliberately no BUGGIFY randomizers: recording is observational
    # (abort sets bit-identical on/off) and draws no rng.
    #: master switch: "" = off (producer sites pay one list-index check
    #: and allocate nothing); "on" = journal into resolver_blackbox_dir;
    #: any other value is itself the journal directory
    k.init("resolver_blackbox", "")
    #: journal directory when resolver_blackbox is "on"
    k.init("resolver_blackbox_dir", "blackbox")
    #: segment rotation threshold: a segment reaching this many bytes is
    #: closed and a new one opened (append-only within a segment)
    k.init("resolver_blackbox_segment_bytes", 1 << 20)
    #: retained segments; the oldest is deleted past this (the journal's
    #: retention window — size it like the MVCC window so a replayed
    #: slice's too-old gate still covers the retained history)
    k.init("resolver_blackbox_segments", 8)
    #: in-memory ring of recent envelopes for live explain / summaries
    k.init("resolver_blackbox_ring", 4096)
    #: journal durability cadence: fsync the segment file every N records
    #: (1 = every record: acked implies durable — the crash campaign's
    #: child sets this so recovery never serves behind an ack). 0 keeps
    #: today's contract: flush per record (no data buffered in process)
    #: but no fsync — a power loss may eat the OS-buffered tail
    #: (docs/observability.md "crash-window contract")
    k.init("resolver_blackbox_fsync_interval", 0)
    # Crash-stop recovery (fault/recovery.py; docs/fault_tolerance.md
    # "Crash-stop recovery"). Deliberately no BUGGIFY randomizers: the
    # snapshot writer is observational and the crash campaign stresses
    # the recovery path directly.
    #: engine-state snapshot cadence in commit versions: the recovery
    #: manager writes a coalesced snapshot beside the journal segments
    #: every this-many versions (0 disables snapshotting — recovery
    #: falls back to full journal replay)
    k.init("resolver_recovery_snapshot_interval", 5000)
    #: recovery blackout SLO in ms: kill -> serving again, measured by
    #: the recovery.blackout span — the crash campaign machine-asserts
    #: every recovery under this (real/nemesis.py --crash)
    k.init("resolver_recovery_budget_ms", 5000.0)
    #: a recovery in flight longer than this is STALLED — the watchdog's
    #: RecoveryStalledRule fires (core/watchdog.py)
    k.init("resolver_recovery_stall_s", 10.0)
    # On-disk AOT program cache (core/progcache.py). Deliberately no
    # BUGGIFY randomizers: cache misses only cost a compile.
    #: master switch: "" = off (every program compiles); "on" = cache
    #: compiled artifacts under resolver_progcache_dir; any other value
    #: is itself the cache directory
    k.init("resolver_progcache", "")
    #: cache directory when resolver_progcache is "on"
    k.init("resolver_progcache_dir", "progcache")
    # Conflict-aware scheduler (pipeline/scheduler.py; docs/scheduling.md).
    # Deliberately no BUGGIFY randomizers: scheduling is deterministic
    # (counter-based probing, no rng) and the fully-off path must stay
    # byte-identical — a randomizer draw would shift every sim's stream.
    #: master switch: "" = off (admission hands batches through untouched,
    #: compiled programs byte-identical); "on" = predictive reorder +
    #: serialization lanes + pre-abort between admission and the batcher
    k.init("resolver_sched", "")
    #: max pending transactions the scheduler examines per batching tick
    #: (the reorder window; pendings beyond it keep arrival order)
    k.init("resolver_sched_window", 256)
    #: decayed conflict score at which a key range is HOT — hot ranges get
    #: a serialization lane and feed the doom predictor
    k.init("resolver_sched_hot_score", 4.0)
    #: per-tick multiplicative decay of range conflict scores (forgets
    #: cooled hot spots; pairs with resolver_heat_decay upstream). Ticks
    #: run at the batch cadence — hundreds per second — so the half-life
    #: at the default is tens of milliseconds, not seconds
    k.init("resolver_sched_decay", 0.98)
    #: pre-abort predicted-doomed transactions with
    #: transaction_conflict_predicted before device dispatch (clients
    #: refresh their read version and retry); False = predict + lane only
    k.init("resolver_sched_preabort", True)
    #: deterministic 1-in-N probe cadence: every Nth predicted-doomed
    #: transaction is dispatched anyway; a probe that COMMITS increments
    #: the mispredict counter the watchdog alerts on (no rng)
    k.init("resolver_sched_probe_interval", 16)
    #: upper bound on live serialization lanes (hottest ranges win;
    #: excess hot ranges fall back to reorder-only handling)
    k.init("resolver_sched_lane_max", 8)
    #: max transactions queued in one lane; a full lane stops capturing
    #: (overflow keeps normal batching) so lanes bound, never grow, work
    k.init("resolver_sched_lane_depth", 32)
    #: starvation bound: a transaction deferred this many ticks is
    #: dispatched regardless of predicted conflicts
    k.init("resolver_sched_defer_max", 4)
    #: watchdog threshold: probes that commit / probes dispatched above
    #: this fraction means the predictor has gone stale — sched_mispredict
    #: fires and the incident names the counter pair (core/watchdog.py)
    k.init("resolver_sched_mispredict_frac", 0.5)
    # Cluster watchdog (core/watchdog.py; docs/observability.md
    # "Watchdog, burn rates & incidents"). Deliberately no BUGGIFY
    # randomizers: evaluation is observational (host-side reads only,
    # no rng), and a randomizer draw would shift every sim's rng stream.
    #: master switch: off = `hub().sync()` pays one attribute check and
    #: allocates nothing (the NULL_SPAN-style regression guard); on = a
    #: default-ruleset watchdog attaches at hub construction and every
    #: sync evaluates the rules
    k.init("watchdog_enabled", False)
    #: bounded ring of alert lifecycle transitions the watchdog retains
    k.init("watchdog_alert_ring", 256)
    #: a rule's condition must hold this long before pending -> firing
    #: (discipline rules like blocking_syncs override to 0: a blocking
    #: sync is a fact, not a rate)
    k.init("watchdog_hold_s", 0.1)
    #: a firing rule's condition must stay clear this long to resolve
    k.init("watchdog_clear_s", 0.5)
    #: burn-rate fast/slow trailing windows — BOTH must burn above the
    #: threshold to fire (fast = detection latency, slow = flap guard)
    k.init("watchdog_burn_fast_s", 0.5)
    k.init("watchdog_burn_slow_s", 2.0)
    #: burn-rate multiplier over the error budget that fires (1.0 =
    #: budget spent exactly at the sustainable rate)
    k.init("watchdog_burn_threshold", 2.0)
    #: p99-vs-budget SLO error budget: allowed fraction of acks over the
    #: latency budget (0.01 = the p99 contract)
    k.init("watchdog_slo_bad_frac", 0.01)
    #: abort-fraction error budget (conflicts / resolved) — optimistic
    #: concurrency makes SOME aborts normal; a burn over this is hot-key
    #: collapse (the Zipf sweep measured 16%->43% with skew)
    k.init("watchdog_abort_budget_frac", 0.25)
    #: tenant throttle-rate error budget (rejected / offered)
    k.init("watchdog_throttle_budget_frac", 0.2)
    #: EWMA z-score band width for anomaly rules (heat concentration)
    k.init("watchdog_z_threshold", 3.5)
    #: a must-advance series (commit SLI total) frozen longer than this
    #: under evaluation is a stall
    k.init("watchdog_staleness_s", 1.5)
    #: admission fraction while a burn-rate alert is firing — the
    #: ratekeeper consumes the firing signal as a rate clamp alongside
    #: resolver_degraded (server/ratekeeper.py)
    k.init("watchdog_burn_tps_fraction", 0.5)
    # Wall-clock chaos (real/chaos.py; docs/real_cluster.md). Defaults for
    # the seeded NetworkNemesis' background fault mix — a campaign's
    # ChaosConfig reads these so `--knob`-style overrides steer injection
    # without touching campaign code. Deliberately no BUGGIFY randomizers:
    # these only matter in wall-clock mode, where buggify is off anyway.
    #: probability a request draws added one-way latency
    k.init("chaos_net_latency_prob", 0.05)
    #: the added latency when the draw fires (uniform in [0.5x, 1.5x])
    k.init("chaos_net_latency_ms", 2.0)
    #: probability a request frame is dropped on the floor (the client sees
    #: request_maybe_delivered, the redelivery semantics of the transport)
    k.init("chaos_net_drop_prob", 0.02)
    #: probability the peer connection is reset under a request
    k.init("chaos_net_reset_prob", 0.01)
    #: probability a fresh connection's handshake stalls (the peer accepts
    #: but never answers the hello; real_handshake_timeout_s must bound it)
    k.init("chaos_handshake_stall_prob", 0.05)
    # Disk nemesis (fault/inject.py DiskFaults + real/chaos.py
    # DiskNemesis): seeded fault mix for the durability surfaces — the
    # journal writer, the snapshot writer and the program cache. All
    # default 0: disk faults are campaign-armed, never ambient.
    #: probability a durable write stalls (a slow/contended fsync)
    k.init("chaos_disk_stall_prob", 0.0)
    #: stall length in ms when the draw fires (uniform in [0.5x, 1.5x])
    k.init("chaos_disk_stall_ms", 20.0)
    #: probability a write is TORN: only a prefix reaches the disk and
    #: the writer sees an IO error (the crash-mid-append shape)
    k.init("chaos_disk_torn_prob", 0.0)
    #: probability a write fails with ENOSPC (disk full)
    k.init("chaos_disk_enospc_prob", 0.0)
    #: probability a written payload suffers silent bit-rot (crc framing
    #: must catch it at read time and quarantine the data)
    k.init("chaos_disk_rot_prob", 0.0)
    #: wall-clock SLO scale: the chaos campaign's p99 budget is
    #: resolver_p99_budget_ms x this factor. The 2.5 ms budget prices a
    #: chip-adjacent resolver (sub-ms device time, in-rack RTT); the
    #: wall-clock mini-cluster the campaign drives pays ~1 ms in-process
    #: TCP RTT per hop plus a ~8 ms modeled service slot on a CI box, so
    #: its serving point sits ~24x higher. The ASSERTION CONTRACT is
    #: identical — p99 outside injected-fault windows <= the budget knob
    #: product — only the deployment's latency floor differs
    #: (docs/real_cluster.md).
    k.init("real_chaos_budget_factor", 24.0)
    #: per-tenant admission burst window in seconds (server/ratekeeper.py
    #: TenantAdmission token bucket: a tenant may burst rate*burst ahead)
    k.init("tenant_admission_burst_s", 0.5)
    return k


def _make_client_knobs() -> Knobs:
    k = Knobs()
    k.init("max_backoff", 1.0)
    k.init("initial_backoff", 0.01)
    k.init("backoff_growth_rate", 2.0)
    k.init("grv_batch_size_max", 1024)
    k.init("location_cache_size", 100_000)
    #: hedged reads (LoadBalance.actor.h second requests): after this long
    #: with no reply, race a second replica
    k.init("read_hedge_delay", 0.05, lambda r: 0.005 + r.random01() * 0.1)
    #: sampled transactions carry a debug id traced through the commit
    #: pipeline (g_traceBatch probes); 0 disables
    k.init("commit_sample_rate", 0.01, lambda r: r.random01() * 0.5)
    return k


def _make_flow_knobs() -> Knobs:
    k = Knobs()
    k.init("min_delay", 0.0001)
    k.init("max_buggified_delay", 0.2)
    k.init("connection_latency", 0.0005)
    # Real transport (real/transport.py; docs/real_cluster.md). These were
    # three hardcoded `timeout=5.0` sites and a magic sleep — promoted so a
    # chaos campaign (or an operator on a lossy link) can tune the failure
    # detection window without editing the transport.
    #: default per-request RPC timeout; request() callers may still pass an
    #: explicit timeout, which also rides the frame as a propagated
    #: deadline the server sheds expired work against
    k.init("real_rpc_timeout_s", 5.0)
    #: bound on the protocol-version handshake (a stalled or mismatched
    #: peer surfaces as connection_failed within this, never a hang)
    k.init("real_handshake_timeout_s", 5.0)
    #: first reconnect backoff after a failed connect (doubles per
    #: consecutive failure, jittered, until the max below; a request that
    #: lands inside the backoff window fails fast instead of hammering a
    #: dead peer with SYNs)
    k.init("real_reconnect_backoff_initial_s", 0.05)
    k.init("real_reconnect_backoff_max_s", 2.0)
    #: jitter half-width as a fraction of the backoff (0.5 = x[0.5, 1.5)),
    #: so a fleet of clients never reconnects in lockstep
    k.init("real_reconnect_backoff_jitter", 0.5)
    #: bound on the whole-cluster boot probe (real/cluster.py: every
    #: spawned node must accept a connection within this) — was a
    #: hardcoded `time.time() + 60`; promoted alongside the
    #: real_rpc_timeout_s family so slow CI boxes tune it by name
    k.init("real_cluster_boot_timeout_s", 60.0)
    return k


SERVER_KNOBS = _make_server_knobs()
CLIENT_KNOBS = _make_client_knobs()
FLOW_KNOBS = _make_flow_knobs()


def reset_all() -> None:
    """Restore every global registry to its defaults (undo per-simulation
    BUGGIFY randomization; the reference re-inits knobs per process)."""
    for live, make in ((SERVER_KNOBS, _make_server_knobs),
                       (CLIENT_KNOBS, _make_client_knobs),
                       (FLOW_KNOBS, _make_flow_knobs)):
        live._values.update(make()._values)


def randomize_all(rng, probability: float = 0.25) -> None:
    """BUGGIFY-randomize every registry (fdbserver/Knobs.cpp pattern:
    `init(KNOB, v); if(randomize && BUGGIFY) ...`)."""
    for k in (SERVER_KNOBS, CLIENT_KNOBS, FLOW_KNOBS):
        k.randomize(rng, probability)
