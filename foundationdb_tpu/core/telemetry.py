"""Unified telemetry registry: one bridge from the serving path's disjoint
counter islands into the TDMetric time-series machinery.

Before this module, four telemetry sources lived apart with no common
drain: `EnginePerf` (ops/host_engine.py compile/bucket/scan counters),
per-bucket `BudgetBatcher` EWMAs (pipeline/resolver_pipeline.py),
`ResilientEngine` health-state transitions (fault/resilient.py) and the
role `CounterCollection`s. The hub gives every source one registration
call and one `TDMetricCollection` (core/tdmetric.py), so:

  * `client/metric_logger.run_metric_logger(db, hub().tdmetrics, ...)`
    persists all of it into the `\\xff/metrics/` keyspace, queryable by
    (metric, time range) like any other TDMetric series;
  * `snapshot()` is the live status fragment the resolver's engine-health
    endpoint attaches, flowing resolver -> ratekeeper -> master status ->
    CC status doc -> `tools/cli.py telemetry`;
  * `prometheus_text()` renders the current values as a Prometheus-style
    text exposition (real/demo_server.py serves it).

Sim hygiene: `Simulator.__init__` calls `reset()` (like the fault-engine
registry and sim/validation), so one simulation's engines never leak into
the next run's telemetry. Registration is append-only and draws no rng —
registering a source can never perturb a deterministic simulation.
"""
from __future__ import annotations

import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .tdmetric import TDMetricCollection
from .trace import span_now

#: health states in transition-metric encoding (fault/resilient.py's
#: state machine; the Int64 series records the index at each transition)
HEALTH_STATE_INDEX = {"healthy": 0, "suspect": 1, "failed": 2,
                      "probation": 3, "quarantined": 4}


def _engine_state_bytes(engine) -> Optional[int]:
    """Footprint of an engine's resolved-history state, in bytes — the
    device interval table for kernel engines (a dict of arrays), reached
    through a ResilientEngine's wrapped device when supervised. None when
    the engine keeps no array state (the serial oracle).
    server/resolver.py uses the same helper for its engine_health
    fragment."""
    dev = getattr(engine, "device", engine)
    st = getattr(dev, "state", None)
    if not isinstance(st, dict):
        return None
    try:
        return int(sum(int(getattr(v, "nbytes", 0)) for v in st.values()))
    except (TypeError, ValueError):
        return None


class TelemetryHub:
    """Per-process registry of serving-path telemetry sources.

    Registries hold WEAK references: engines/batchers register at
    construction with no unregister path, and a long-lived wall-clock
    process (real demo server, bench drivers, repeated pipeline
    construction) must not pin every discarded engine — and its device
    state — forever, nor pay sync() cost scaling with process lifetime.
    A collected source simply stops updating; its last synced values
    remain in the TDMetric series. (In simulation the cluster and the
    fault registry keep live sources strongly reachable anyway.)"""

    def __init__(self) -> None:
        self.tdmetrics = TDMetricCollection(now=span_now)
        #: label -> weakref to EnginePerf
        self._engine_perf: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to BudgetBatcher
        self._batchers: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to ResilientEngine
        self._health: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to DeviceLoopEngine (queue/ring gauges —
        #: ops/device_loop.py loop_stats + occupancy)
        self._loops: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to MeshShardedConflictEngine (device-mesh
        #: gauges — parallel/mesh_engine.py mesh_stats + ring drain
        #: accounting)
        self._meshes: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to KeyRangeHeatAggregator (core/heatmap.py —
        #: keyspace heat, occupancy headroom, split planning)
        self._heat: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to PerfLedger (core/perfledger.py — compile &
        #: memory ledger: build durations, flops/bytes, peak HBM)
        self._perf_ledgers: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to TenantAdmission (server/ratekeeper.py —
        #: admitted/rejected totals feed the throttle burn-rate rule)
        self._admissions: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to ReshardController (server/reshard.py —
        #: executed/stalled/blackout gauges feed the `fdbtpu_reshard`
        #: family and the watchdog's reshard rules)
        self._reshards: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to ConflictScheduler (pipeline/scheduler.py —
        #: decision counters, probe/mispredict pair and lane gauges feed
        #: the `fdbtpu_sched` family and the sched_mispredict rule)
        self._scheds: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to BlackBoxJournal (core/blackbox.py —
        #: durable-write accounting: events, fsync cadence cost, shed
        #: events and the durability-gap flag)
        self._blackboxes: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to RecoveryTracker (fault/recovery.py —
        #: in-flight recovery age feeds the `recovery_stalled` rule,
        #: completed arcs feed the blackout gauges)
        self._recoveries: Dict[str, "weakref.ref"] = {}
        #: label -> weakref to a tiered-history engine (ops/host_engine.py
        #: — run/merge accounting mirrored from the heat aggregate's
        #: `runs` leaf, synced as `history.<label>.*` / fdbtpu_history)
        self._histories: Dict[str, "weakref.ref"] = {}
        self._seq = 0
        #: bounded ring of recent nemesis/chaos events (real/chaos.py,
        #: real/nemesis.py) — rendered by `tools/cli.py chaos-status`
        self.chaos_events: deque = deque(maxlen=256)
        #: the cluster watchdog (core/watchdog.py): None (default) = the
        #: disabled path — sync() pays ONE attribute check and allocates
        #: nothing. The `watchdog_enabled` knob auto-attaches a
        #: default-ruleset engine at hub construction; campaigns attach
        #: their own via attach_watchdog().
        from .watchdog import watchdog_from_knobs

        self._watchdog = watchdog_from_knobs()

    # -- registration --------------------------------------------------------
    def _label(self, kind: str, name: str) -> str:
        self._seq += 1
        return f"{name or kind}.{self._seq}"

    def register_engine_perf(self, perf, name: str = "engine") -> str:
        label = self._label("engine", name)
        self._engine_perf[label] = weakref.ref(perf)
        return label

    def register_batcher(self, batcher, name: str = "batcher") -> str:
        label = self._label("batcher", name)
        self._batchers[label] = weakref.ref(batcher)
        return label

    def register_health(self, engine, name: str = "resilient") -> str:
        label = self._label("resilient", name)
        self._health[label] = weakref.ref(engine)
        return label

    def register_loop(self, engine, name: str = "loop") -> str:
        """A device-resident loop engine's queue/ring gauges
        (ops/device_loop.py): slot occupancy, result-ring depth and the
        sync-accounting counters, synced as `loop.<label>.*` series."""
        label = self._label("loop", name)
        self._loops[label] = weakref.ref(engine)
        return label

    def register_mesh(self, engine, name: str = "mesh") -> str:
        """A multi-device mesh engine's topology + exchange gauges
        (parallel/mesh_engine.py): device count, per-shard table bytes,
        the measured cross-shard exchange interval and the same
        non-blocking drain accounting as the device loop, synced as
        `mesh.<label>.*` series (the `fdbtpu_mesh` exposition family)."""
        label = self._label("mesh", name)
        self._meshes[label] = weakref.ref(engine)
        return label

    def register_perf_ledger(self, ledger, name: str = "perf") -> str:
        """An engine's compile & memory ledger (core/perfledger.py):
        warmup/steady compile counts and durations, cost-analysis
        flops/bytes and peak compiled-program HBM, synced as
        `perf.<label>.*` series (the `fdbtpu_perf` Prometheus family)."""
        label = self._label("perf", name)
        self._perf_ledgers[label] = weakref.ref(ledger)
        return label

    def register_admission(self, admission, name: str = "admission") -> str:
        """A per-tenant admission controller (server/ratekeeper.py
        TenantAdmission): admitted/rejected totals synced as
        `admission.<label>.*` series — the good/bad pair the watchdog's
        tenant_throttle_burn rule consumes."""
        label = self._label("admission", name)
        self._admissions[label] = weakref.ref(admission)
        return label

    def register_reshard(self, controller, name: str = "reshard") -> str:
        """An online-resharding controller (server/reshard.py): executed
        and stalled counts, in-flight age and blackout accounting synced
        as `reshard.<label>.*` series — the `fdbtpu_reshard` exposition
        family, and the series the watchdog's ReshardStalledRule and
        blackout-overrun rule evaluate."""
        label = self._label("reshard", name)
        self._reshards[label] = weakref.ref(controller)
        return label

    def register_scheduler(self, scheduler, name: str = "sched") -> str:
        """A conflict scheduler (pipeline/scheduler.py ConflictScheduler):
        per-decision counters (dispatched/deferred/laned/pre-aborted),
        the probe vs mispredict pair the watchdog's sched_mispredict
        burn rule consumes, and lane/predictor gauges, synced as
        `sched.<label>.*` series — the `fdbtpu_sched` family."""
        label = self._label("sched", name)
        self._scheds[label] = weakref.ref(scheduler)
        return label

    def register_blackbox(self, journal, name: str = "blackbox") -> str:
        """A durable black-box journal (core/blackbox.py): event/fsync
        counts, fsync wall cost and the shed-to-memory accounting
        (`shed_events` / `durability_gap`), synced as
        `blackbox.<label>.*` series — the crash-window contract's eyes
        (docs/observability.md)."""
        label = self._label("blackbox", name)
        self._blackboxes[label] = weakref.ref(journal)
        return label

    def register_recovery(self, tracker, name: str = "recovery") -> str:
        """A crash-stop recovery tracker (fault/recovery.py
        RecoveryTracker): recovery counts, worst blackout and the
        in-flight age the watchdog's `recovery_stalled` rule evaluates,
        synced as `recovery.<label>.*` series."""
        label = self._label("recovery", name)
        self._recoveries[label] = weakref.ref(tracker)
        return label

    def recovery_source(self, label: str):
        """The live RecoveryTracker registered under `label` (None if
        collected) — the stalled-recovery rule reads its in-flight
        detail through this to compose a speakable incident line."""
        ref = self._recoveries.get(label)
        return ref() if ref is not None else None

    def reshard_source(self, label: str):
        """The live controller registered under `label` (None if
        collected) — the stalled-reshard rule reads its range/donor
        detail through this to compose a speakable incident line."""
        ref = self._reshards.get(label)
        return ref() if ref is not None else None

    # -- the cluster watchdog (core/watchdog.py) -----------------------------
    @property
    def watchdog(self):
        return self._watchdog

    def attach_watchdog(self, wd) -> None:
        """Install (or replace) the process watchdog; None detaches. The
        attached engine evaluates on every sync()."""
        self._watchdog = wd

    def register_heat(self, aggregator, name: str = "heat") -> str:
        """An engine's keyspace-heat aggregator (core/heatmap.py): hot-range
        concentration, occupancy headroom, GC pressure and verdict totals,
        synced as `heat.<label>.*` series."""
        label = self._label("heat", name)
        self._heat[label] = weakref.ref(aggregator)
        return label

    def register_history(self, engine, name: str = "history") -> str:
        """An engine running the TIERED history structure
        (ops/host_engine.py): structure identity plus the run
        append/merge counters its heat aggregator derives from the
        device heat aggregate's run-depth leaf, synced as
        `history.<label>.*` series — the `fdbtpu_history` family.
        Monolithic engines never register (the exposition stays
        byte-stable for the fleet that hasn't flipped the knob)."""
        label = self._label("history", name)
        self._histories[label] = weakref.ref(engine)
        return label

    @staticmethod
    def _live(registry: Dict[str, "weakref.ref"]):
        """(label, source) for live sources; dead entries are pruned."""
        dead = [label for label, ref in registry.items() if ref() is None]
        for label in dead:
            del registry[label]
        return [(label, ref()) for label, ref in registry.items()
                if ref() is not None]

    def record_health_transition(self, label: str, state: str) -> None:
        """Called by ResilientEngine._set_state on every transition: the
        change history IS the incident timeline (TDMetric read model).
        Recorded unconditionally — the construction-time entry indexes 0
        (healthy), which a level metric's change-only set() would swallow,
        and an engine's very existence belongs in the timeline."""
        m = self.tdmetrics.int64(f"resolver.{label}.state")
        m.value = HEALTH_STATE_INDEX.get(state, -1)
        m._record(m.value)

    def chaos_event(self, kind: str, **detail: Any) -> None:
        """Record one injected fault / nemesis action: an Int64 counter per
        kind (`chaos.<kind>` — rides every hub frontend: Prometheus text,
        metric logger, status snapshots) plus a bounded event ring with the
        details, so `tools/cli.py chaos-status` can show WHAT the nemesis
        did, not just how often."""
        m = self.tdmetrics.int64(f"chaos.{kind}")
        m.increment()
        self.chaos_events.append({"kind": kind, "t": span_now(), **detail})

    def chaos_counts(self) -> Dict[str, int]:
        """kind -> count for every chaos.* counter this process recorded."""
        out: Dict[str, int] = {}
        for name, m in self.tdmetrics.metrics.items():
            if name.startswith("chaos."):
                out[name[len("chaos."):]] = int(getattr(m, "value", 0))
        return out

    # -- bridging ------------------------------------------------------------
    def sync(self) -> None:
        """Pull every registered source's current values into the TDMetric
        collection (level metrics record only on change, so a quiet sync is
        free). Run before each MetricLogger drain or status snapshot."""
        from . import buggify

        if buggify.buggify():
            # stale telemetry: one sync silently skipped — the change-history
            # metric model must tolerate a lagging bridge (values catch up on
            # the next sync; level metrics record no spurious entries)
            return
        td = self.tdmetrics
        for label, perf in self._live(self._engine_perf):
            td.int64(f"engine.{label}.compiles").set(perf.compiles)
            for bucket, hits in perf.bucket_hits.items():
                td.int64(f"engine.{label}.bucket_hits.{bucket}").set(hits)
            for scan, n in perf.scan_dispatches.items():
                td.int64(f"engine.{label}.scan_dispatches.{scan}").set(n)
            # history-search mode picks (docs/perf.md): chunks dispatched
            # per mode, so `tools/cli.py telemetry` and the Prometheus
            # exposition surface `search_mode_hits_*` with no extra wiring
            for mode, n in getattr(perf, "search_mode_hits", {}).items():
                td.int64(f"engine.{label}.search_mode_hits.{mode}").set(n)
            # dispatch mode (docs/perf.md "Device-resident loop"): step vs
            # loop chunk counts, same frontends as the search-mode picks
            for mode, n in getattr(perf, "dispatch_mode_hits", {}).items():
                td.int64(f"engine.{label}.dispatch_mode_hits.{mode}").set(n)
            # abort-cause split (docs/observability.md "Keyspace heat &
            # occupancy"): committed vs conflicts vs too_old, aggregated —
            # previously only visible per batch in status_of
            for kind, n in getattr(perf, "verdicts", {}).items():
                td.int64(f"engine.{label}.verdicts.{kind}").set(n)
            # sampled measured device timing (docs/observability.md
            # "Performance observatory"): mean per-chunk enqueue->ready
            # microseconds and sample counts per bucket
            if getattr(perf, "device_time", None):
                for b, ms in perf.device_time_ms_by_bucket().items():
                    td.int64(f"engine.{label}.device_time_us.{b}").set(
                        int(ms * 1000))
                for b, d in perf.device_time.items():
                    td.int64(f"engine.{label}.device_time_samples.{b}").set(
                        int(d["samples"]))
        for label, b in self._live(self._batchers):
            # EWMAs are floats; the Int64 series stores microseconds so the
            # persisted change history stays integral. Keys are per
            # (bucket, history-search mode, dispatch mode) — search modes
            # have different device-time floors for the same shape, and
            # the device loop removes per-batch dispatch cost the step
            # path pays (docs/perf.md)
            for (bucket, mode, dispatch), ms in b.ewma_ms.items():
                td.int64(
                    f"batcher.{label}.ewma_us.{bucket}.{mode}.{dispatch}"
                ).set(int(ms * 1000))
        for label, eng in self._live(self._health):
            st = eng.stats
            for key in ("batches", "dispatch_faults", "retries", "failovers",
                        "swap_backs", "probes", "probe_mismatches",
                        "oracle_batches"):
                td.int64(f"resolver.{label}.{key}").set(st.get(key, 0))
            # state-memory accounting (reference: RESOLVER_STATE_MEMORY_
            # LIMIT): the supervised device table's footprint vs the knob,
            # as a series so the watchdog's state_memory_pressure rule
            # evaluates it live (server/resolver.py mirrors the same
            # figures into engine_health for the status doc)
            sb = _engine_state_bytes(eng)
            if sb is not None:
                from .knobs import SERVER_KNOBS

                td.int64(f"resolver.{label}.state_bytes").set(sb)
                td.int64(f"resolver.{label}.state_memory_pressure").set(
                    1 if sb > int(SERVER_KNOBS.resolver_state_memory_limit)
                    else 0)
        for label, rc in self._live(self._reshards):
            # online-resharding gauges (server/reshard.py): epoch + shard
            # count for the live map, executed/stalled op counts, the
            # worst observed blackout vs budget, and the in-flight age
            # the ReshardStalledRule evaluates
            td.int64(f"reshard.{label}.epoch").set(rc.group.emap.epoch)
            td.int64(f"reshard.{label}.shards").set(
                len(rc.group.active_sids()))
            td.int64(f"reshard.{label}.executed").set(rc.executed)
            td.int64(f"reshard.{label}.stalled").set(rc.stalled)
            td.int64(f"reshard.{label}.in_flight").set(
                1 if rc.in_flight() else 0)
            td.int64(f"reshard.{label}.in_flight_age_us").set(
                int(rc.in_flight_age_s() * 1e6))
            td.int64(f"reshard.{label}.blackout_us_max").set(
                int(rc.blackout_ms_max * 1000))
            td.int64(f"reshard.{label}.blackout_over_budget").set(
                rc.blackout_over_budget)
        for label, adm in self._live(self._admissions):
            # per-tenant admission totals (server/ratekeeper.py): the
            # offered split into admitted vs shed — the watchdog's
            # tenant_throttle_burn good/bad pair
            td.int64(f"admission.{label}.admitted").set(
                sum(adm.admitted.values()))
            td.int64(f"admission.{label}.rejected").set(
                sum(adm.rejected.values()))
        for label, sch in self._live(self._scheds):
            # conflict-scheduler eyes (pipeline/scheduler.py): every
            # decision counter, the probe_ok/mispredicts pair the
            # watchdog's sched_mispredict rule burns against, live lane
            # depth and the predictor's tracked-range count
            for key, n in sch.counters.items():
                td.int64(f"sched.{label}.{key}").set(int(n))
            td.int64(f"sched.{label}.lanes").set(len(sch.lanes))
            td.int64(f"sched.{label}.pending_laned").set(
                sch.pending_laned())
            td.int64(f"sched.{label}.tracked_ranges").set(
                len(sch.predictor.scores))
            td.int64(f"sched.{label}.mispredict_frac_x1000").set(
                int(sch.mispredict_frac() * 1000))
        for label, eng in self._live(self._loops):
            # device-loop eyes (ops/device_loop.py): the double buffer's
            # slot occupancy, the result ring's depth, and every
            # sync-accounting counter — blocking_syncs must read 0 on any
            # healthy scrape
            st = eng.loop_stats
            for key in ("enqueued_chunks", "units", "drained_nonblocking",
                        "forced_waits", "blocking_syncs"):
                td.int64(f"loop.{label}.{key}").set(int(st.get(key, 0)))
            td.int64(f"loop.{label}.wait_us").set(
                int(st.get("wait_ms", 0.0) * 1000))
            td.int64(f"loop.{label}.ring_depth").set(eng.ring_depth())
            td.int64(f"loop.{label}.slots_in_flight").set(
                eng.slots_in_flight())
        for label, eng in self._live(self._meshes):
            # mesh eyes (parallel/mesh_engine.py): the device topology,
            # per-shard table residency, the measured exchange interval
            # and the same sync accounting as the loop family —
            # blocking_syncs must read 0 on any healthy scrape
            st = eng.loop_stats
            for key in ("enqueued_chunks", "units", "drained_nonblocking",
                        "forced_waits", "blocking_syncs"):
                td.int64(f"mesh.{label}.{key}").set(int(st.get(key, 0)))
            td.int64(f"mesh.{label}.wait_us").set(
                int(st.get("wait_ms", 0.0) * 1000))
            td.int64(f"mesh.{label}.ring_depth").set(eng.ring_depth())
            ms = eng.mesh_stats
            td.int64(f"mesh.{label}.n_devices").set(int(ms["n_devices"]))
            td.int64(f"mesh.{label}.exchanges").set(int(ms["exchanges"]))
            td.int64(f"mesh.{label}.table_bytes_per_shard").set(
                int(ms["table_bytes_per_shard"]))
            td.int64(f"mesh.{label}.last_collective_us").set(
                int(ms.get("last_collective_ms", 0.0) * 1000))
        for label, led in self._live(self._perf_ledgers):
            # compile & memory ledger (core/perfledger.py): warmup/steady
            # compile counts + total build time, the cost-analysis
            # totals, and the largest single-program HBM pin — the
            # `fdbtpu_perf` exposition family
            for kind in ("warmup", "steady"):
                td.int64(f"perf.{label}.compiles_{kind}").set(
                    led.compiles.get(kind, 0))
                td.int64(f"perf.{label}.compile_us_{kind}").set(
                    int(led.compile_ms.get(kind, 0.0) * 1000))
            td.int64(f"perf.{label}.peak_hbm_bytes").set(led.peak_bytes)
            td.int64(f"perf.{label}.flops_total").set(led.flops_total)
            td.int64(f"perf.{label}.bytes_accessed_total").set(
                led.bytes_accessed_total)
        for label, agg in self._live(self._heat):
            # keyspace heat & occupancy (core/heatmap.py): contention
            # concentration, table headroom and GC pressure as integer
            # gauges (x1000 fixed-point for the [0,1] fractions). brief()
            # is the single-pass read (one argmax, one key formatted) —
            # hot_ranges would sort and format every retained range per
            # sync tick
            b = agg.brief()
            td.int64(f"heat.{label}.batches").set(agg.batches)
            td.int64(f"heat.{label}.occupancy").set(agg.occupancy)
            td.int64(f"heat.{label}.occupancy_frac_x1000").set(
                int(b["occupancy_frac"] * 1000))
            td.int64(f"heat.{label}.gc_reclaimed").set(
                agg.gc_reclaimed_total)
            td.int64(f"heat.{label}.concentration_x1000").set(
                int(b["concentration"] * 1000))
            td.int64(f"heat.{label}.top_range_share_x1000").set(
                int(b["top_share"] * 1000))
        for label, bb in self._live(self._blackboxes):
            # durable-journal eyes (core/blackbox.py): event/segment
            # counts, the knobbed fsync cadence's wall cost, and the
            # shed-to-memory accounting — `durability_gap` reading 1
            # means the on-disk suffix is honest-but-incomplete
            td.int64(f"blackbox.{label}.events").set(
                int(bb.events_written))
            td.int64(f"blackbox.{label}.fsyncs").set(int(bb.fsyncs))
            td.int64(f"blackbox.{label}.fsync_us").set(
                int(bb.fsync_ms * 1000))
            td.int64(f"blackbox.{label}.dropped_errors").set(
                int(bb.dropped_errors))
            td.int64(f"blackbox.{label}.shed_events").set(
                int(bb.shed_events))
            td.int64(f"blackbox.{label}.durability_gap").set(
                1 if bb.durability_gap else 0)
        for label, eng in self._live(self._histories):
            # tiered-history eyes (ops/host_engine.py
            # history_stats_snapshot): run-stack depth, append/merge
            # counters and live tier occupancy — all mirrored from the
            # per-batch heat aggregate, zero extra device syncs
            h = eng.history_stats_snapshot()
            td.int64(f"history.{label}.tiered").set(
                1 if h.get("structure") == "tiered" else 0)
            td.int64(f"history.{label}.run_slots").set(
                int(h.get("run_slots", 0)))
            td.int64(f"history.{label}.run_rows").set(
                int(h.get("run_rows", 0)))
            td.int64(f"history.{label}.appends").set(
                int(h.get("appends", 0)))
            td.int64(f"history.{label}.merges").set(
                int(h.get("merges", 0)))
            td.int64(f"history.{label}.runs_live").set(
                int(h.get("runs_live", 0)))
            td.int64(f"history.{label}.run_rows_live").set(
                int(h.get("run_rows_live", 0)))
        for label, rt in self._live(self._recoveries):
            # crash-stop recovery eyes (fault/recovery.py): completed
            # and failed recoveries, the worst observed blackout, and
            # the in-flight age the RecoveryStalledRule evaluates
            td.int64(f"recovery.{label}.recoveries").set(
                int(rt.recoveries))
            td.int64(f"recovery.{label}.failures").set(int(rt.failures))
            td.int64(f"recovery.{label}.in_flight").set(
                1 if rt.in_flight() else 0)
            td.int64(f"recovery.{label}.in_flight_age_us").set(
                int(rt.in_flight_age_s() * 1e6))
            td.int64(f"recovery.{label}.blackout_us_max").set(
                int(rt.blackout_ms_max * 1000))
        # cluster watchdog (core/watchdog.py): evaluate the rule set over
        # the series refreshed above. The disabled path is this one
        # attribute check — no call, no allocation (the <5 µs/call
        # regression guard in tests/test_watchdog.py)
        wd = self._watchdog
        if wd is not None:
            wd.evaluate(self)

    def snapshot(self) -> dict:
        """Live values for status documents (no TDMetric round trip)."""
        return {
            "engines": {label: perf.as_dict()
                        for label, perf in self._live(self._engine_perf)},
            "batchers": {label: b.as_dict()
                         for label, b in self._live(self._batchers)},
            "health": {label: eng.health_stats()
                       for label, eng in self._live(self._health)},
            "loops": {label: eng.loop_stats_snapshot()
                      for label, eng in self._live(self._loops)},
            "meshes": {label: eng.mesh_stats_snapshot()
                       for label, eng in self._live(self._meshes)},
            "heat": {label: agg.snapshot()
                     for label, agg in self._live(self._heat)},
            "history": {label: eng.history_stats_snapshot()
                        for label, eng in self._live(self._histories)},
            "perf_ledgers": {label: led.snapshot()
                             for label, led in self._live(self._perf_ledgers)},
            "admission": {label: adm.as_dict()
                          for label, adm in self._live(self._admissions)},
            "reshard": {label: rc.snapshot()
                        for label, rc in self._live(self._reshards)},
            "sched": {label: sch.snapshot()
                      for label, sch in self._live(self._scheds)},
            "blackbox": {label: bb.summary()
                         for label, bb in self._live(self._blackboxes)},
            "recovery": {label: {"recoveries": rt.recoveries,
                                 "failures": rt.failures,
                                 "in_flight": rt.in_flight(),
                                 "blackout_ms_max":
                                     round(rt.blackout_ms_max, 3),
                                 "last": rt.last}
                         for label, rt in self._live(self._recoveries)},
            "watchdog": (self._watchdog.snapshot()
                         if self._watchdog is not None else None),
        }

    #: per-family HELP strings for the exposition (families are the first
    #: dotted component of a series name; anything else gets the generic)
    _PROM_HELP = {
        "engine": "conflict-engine perf counters (compiles, bucket/scan/"
                  "search/dispatch-mode hits); series label = the dotted "
                  "series name under engine.",
        "batcher": "budget-batcher latency EWMAs in microseconds, keyed "
                   "(bucket, search mode, dispatch mode)",
        "resolver": "supervised-resolver health counters and state index "
                    "(fault/resilient.py)",
        "loop": "device-resident loop queue/ring gauges "
                "(ops/device_loop.py; blocking_syncs must be 0)",
        "mesh": "multi-device mesh engine gauges (parallel/mesh_engine"
                ".py: device topology, per-shard table bytes, measured "
                "exchange interval; blocking_syncs must be 0)",
        "heat": "keyspace heat & history-occupancy gauges "
                "(core/heatmap.py; fractions are x1000 fixed-point)",
        "history": "tiered-history structure gauges (ops/conflict_kernel"
                   ".py tiered sorted runs: run-stack depth, append/merge "
                   "counters, live tier rows — mirrored from the heat "
                   "aggregate with zero extra syncs)",
        "perf": "compile & memory ledger gauges (core/perfledger.py: "
                "warmup/steady compile counts and microseconds, "
                "cost-analysis totals, peak compiled-program HBM bytes)",
        "chaos": "injected nemesis fault events (real/chaos.py)",
        "demo": "demo KV per-op counters (real/demo_server.py)",
        "alerts": "cluster-watchdog alert states (core/watchdog.py: 0 ok, "
                  "1 pending, 2 firing; `alerts.firing` counts the live "
                  "firing set — the ALERTS-style family)",
        "sli": "commit SLO indicator counters (core/watchdog.py "
               "record_commit_sli: acks within/over the latency budget)",
        "admission": "per-tenant admission totals (server/ratekeeper.py "
                     "TenantAdmission: admitted vs shed)",
        "reshard": "online-resharding controller gauges "
                   "(server/reshard.py: live epoch/shard count, executed/"
                   "stalled ops, in-flight age, blackout vs budget)",
        "sched": "conflict-scheduler gauges (pipeline/scheduler.py: "
                 "decision counters, probe vs mispredict pair, lane "
                 "depth, tracked predictor ranges; fractions are x1000 "
                 "fixed-point)",
        "blackbox": "durable black-box journal gauges (core/blackbox.py: "
                    "event/segment/fsync counts, fsync microseconds, "
                    "shed-to-memory events; durability_gap=1 means the "
                    "on-disk suffix is honest-but-incomplete)",
        "recovery": "crash-stop recovery gauges (fault/recovery.py: "
                    "recovery/failure counts, in-flight age, worst "
                    "blackout microseconds — the recovery_stalled "
                    "rule's series)",
        "scenario": "scenario-atlas scorecard gauges (real/scenarios.py "
                    "publish_scenario: per-scenario p99 microseconds, "
                    "abort/throttle fractions and heat concentration as "
                    "x1000 fixed-point, slo_pass 0/1)",
    }

    @staticmethod
    def _prom_name(s: str) -> str:
        """Sanitize to the metric-name charset [a-zA-Z0-9_:]."""
        out = "".join(c if (c.isascii() and (c.isalnum() or c == "_"))
                      else "_" for c in s)
        return out if out and not out[0].isdigit() else "_" + out

    @staticmethod
    def _prom_escape(s: str) -> str:
        """Label-value escaping per the exposition format: backslash,
        double quote and newline must be escaped or a scraper rejects
        (or silently mis-parses) the whole exposition."""
        return (s.replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def prometheus_text(self) -> str:
        """Current value of every registered series as a Prometheus text
        exposition a REAL scraper parses cleanly: one metric family per
        first dotted component (`fdbtpu_engine`, `fdbtpu_chaos`, ...),
        each preceded by its `# HELP`/`# TYPE` lines, with the full
        dotted series name carried in the `series` label — label VALUES
        may contain dots, slashes, quotes or anything else an engine
        label picked up, so they are escaped, not sanitized away."""
        self.sync()
        groups: Dict[str, List[tuple]] = {}
        for name in sorted(self.tdmetrics.metrics):
            m = self.tdmetrics.metrics[name]
            value = getattr(m, "value", None)
            if value is None:   # ContinuousMetric: expose the event count
                value = len(m.buffer)
            family, _, rest = name.partition(".")
            groups.setdefault(family, []).append((rest, value))
        lines: List[str] = []
        for family in sorted(groups):
            fam = "fdbtpu_" + self._prom_name(family)
            help_text = self._PROM_HELP.get(
                family, f"fdb-tpu telemetry series under '{family}.'")
            lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} gauge")
            for rest, value in groups[family]:
                if rest:
                    lines.append(
                        f'{fam}{{series="{self._prom_escape(rest)}"}} {value}')
                else:
                    lines.append(f"{fam} {value}")
        return "\n".join(lines) + "\n"


_hub = TelemetryHub()


def hub() -> TelemetryHub:
    return _hub


def reset() -> None:
    """Fresh hub (Simulator.__init__, like fault.reset_registry)."""
    global _hub
    _hub = TelemetryHub()
