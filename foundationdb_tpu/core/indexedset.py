"""IndexedSet: an ordered map with metric sums (order statistics).

Re-design of flow/IndexedSet.h (1114 LoC): a balanced search tree where
every node carries a METRIC and every subtree its metric sum, so
"total metric", "metric of everything below k", and "the key where the
running metric crosses m" are O(log n) — the primitives behind the
reference's byte samples and range accounting (StorageMetrics,
KeyRangeMap's metric uses).

Implementation: a treap with DETERMINISTIC priorities (a hash of the
key), so tree shape — and thus iteration cost and any tie-sensitive
query — is identical across runs and processes (the repo's determinism
rule; a random-priority treap would not be). Every operation is
ITERATIVE: a degenerate priority sequence makes the tree a chain, and a
recursive walk would then blow the interpreter's frame limit out of the
storage server's per-mutation sampling path."""
from __future__ import annotations

import zlib
from typing import Iterator, List, Optional, Tuple

from .types import key_after


def _priority(key: bytes) -> int:
    return zlib.crc32(key, 0x9E3779B9)


class _Node:
    __slots__ = ("key", "metric", "prio", "left", "right", "sum", "size")

    def __init__(self, key: bytes, metric: int):
        self.key = key
        self.metric = metric
        self.prio = _priority(key)
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None
        self.sum = metric
        self.size = 1

    def pull(self) -> None:
        s = self.metric
        c = 1
        if self.left is not None:
            s += self.left.sum
            c += self.left.size
        if self.right is not None:
            s += self.right.sum
            c += self.right.size
        self.sum = s
        self.size = c


def _split(n: Optional[_Node], key: bytes) -> Tuple[Optional[_Node], Optional[_Node]]:
    """(everything < key, everything >= key). Iterative spine walk."""
    left_root = right_root = None
    left_tail = right_tail = None
    touched: List[_Node] = []
    while n is not None:
        if n.key < key:
            touched.append(n)
            if left_tail is None:
                left_root = n
            else:
                left_tail.right = n
            left_tail = n
            n = n.right
        else:
            touched.append(n)
            if right_tail is None:
                right_root = n
            else:
                right_tail.left = n
            right_tail = n
            n = n.left
    if left_tail is not None:
        left_tail.right = None
    if right_tail is not None:
        right_tail.left = None
    for node in reversed(touched):
        node.pull()
    return left_root, right_root


def _merge(a: Optional[_Node], b: Optional[_Node]) -> Optional[_Node]:
    """Merge (all keys of a < all keys of b). Iterative spine splice."""
    if a is None:
        return b
    if b is None:
        return a
    root: Optional[_Node] = None
    tail: Optional[_Node] = None
    tail_side = ""
    touched: List[_Node] = []
    while a is not None and b is not None:
        if a.prio >= b.prio:
            nxt = a.right
            node, side = a, "r"
            a = nxt
        else:
            nxt = b.left
            node, side = b, "l"
            b = nxt
        touched.append(node)
        if tail is None:
            root = node
        elif tail_side == "r":
            tail.right = node
        else:
            tail.left = node
        tail, tail_side = node, side
    rest = a if a is not None else b
    if tail_side == "r":
        tail.right = rest
    else:
        tail.left = rest
    for node in reversed(touched):
        node.pull()
    return root


class IndexedSet:
    def __init__(self) -> None:
        self._root: Optional[_Node] = None

    def __len__(self) -> int:
        return self._root.size if self._root is not None else 0

    def total(self) -> int:
        return self._root.sum if self._root is not None else 0

    def get(self, key: bytes) -> Optional[int]:
        n = self._root
        while n is not None:
            if key == n.key:
                return n.metric
            n = n.left if key < n.key else n.right
        return None

    def insert(self, key: bytes, metric: int) -> Optional[int]:
        """Set key's metric (single pass: the old node, if any, is removed
        by the same pair of splits that places the new one); returns the
        previous metric (None if new)."""
        a, rest = _split(self._root, key)
        mid, b = _split(rest, key_after(key))
        self._root = _merge(_merge(a, _Node(key, metric)), b)
        return mid.metric if mid is not None else None

    def erase(self, key: bytes) -> Optional[int]:
        """Remove key; returns its metric (None if absent). A miss costs
        one non-mutating descent, not the split/merge spine surgery —
        erase-of-absent is the common case on the storage sampling path."""
        if self.get(key) is None:
            return None
        a, rest = _split(self._root, key)
        mid, b = _split(rest, key_after(key))
        self._root = _merge(a, b)
        return mid.metric if mid is not None else None

    def erase_range(self, begin: bytes, end: bytes) -> int:
        """Remove every key in [begin, end); returns the erased metric sum."""
        a, rest = _split(self._root, begin)
        mid, b = _split(rest, end)
        self._root = _merge(a, b)
        return mid.sum if mid is not None else 0

    def sum_below(self, key: bytes) -> int:
        """Metric sum of every entry with key < `key` (sumTo)."""
        n = self._root
        acc = 0
        while n is not None:
            if n.key < key:
                acc += n.metric
                if n.left is not None:
                    acc += n.left.sum
                n = n.right
            else:
                n = n.left
        return acc

    def split_key(self) -> Optional[bytes]:
        """The FIRST key (ascending) whose inclusive prefix sum doubles to
        at least the total — the byte-sample median split point
        (StorageMetrics' splitEstimate)."""
        total = self.total()
        if total <= 0 or self._root is None:
            return None
        n = self._root
        acc = 0   # metric strictly left of the current subtree
        best: Optional[bytes] = None
        while n is not None:
            left_sum = n.left.sum if n.left is not None else 0
            inclusive = acc + left_sum + n.metric
            if 2 * inclusive >= total:
                best = n.key
                n = n.left
            else:
                acc += left_sum + n.metric
                n = n.right
        return best

    def items(self) -> Iterator[Tuple[bytes, int]]:
        """Ascending (key, metric) pairs (iterative; no recursion limit)."""
        stack: List[_Node] = []
        n = self._root
        while stack or n is not None:
            while n is not None:
                stack.append(n)
                n = n.left
            n = stack.pop()
            yield n.key, n.metric
            n = n.right
