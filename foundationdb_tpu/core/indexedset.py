"""IndexedSet: an ordered map with metric sums (order statistics).

Re-design of flow/IndexedSet.h (1114 LoC): a balanced search tree where
every node carries a METRIC and every subtree its metric sum, so
"total metric", "metric of everything below k", and "the key where the
running metric crosses m" are O(log n) — the primitives behind the
reference's byte samples and range accounting (StorageMetrics,
KeyRangeMap's metric uses).

Implementation: a treap with DETERMINISTIC priorities (a hash of the
key), so tree shape — and thus iteration cost and any tie-sensitive
query — is identical across runs and processes (the repo's determinism
rule; a random-priority treap would not be)."""
from __future__ import annotations

import zlib
from typing import Iterator, List, Optional, Tuple

from .types import key_after


class _Node:
    __slots__ = ("key", "metric", "prio", "left", "right", "sum")

    def __init__(self, key: bytes, metric: int):
        self.key = key
        self.metric = metric
        # deterministic pseudo-priority from the key bytes
        self.prio = zlib.crc32(key, 0x9E3779B9)
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None
        self.sum = metric

    def pull(self) -> None:
        s = self.metric
        if self.left is not None:
            s += self.left.sum
        if self.right is not None:
            s += self.right.sum
        self.sum = s


def _split(n: Optional[_Node], key: bytes) -> Tuple[Optional[_Node], Optional[_Node]]:
    """(everything < key, everything >= key)."""
    if n is None:
        return None, None
    if n.key < key:
        a, b = _split(n.right, key)
        n.right = a
        n.pull()
        return n, b
    a, b = _split(n.left, key)
    n.left = b
    n.pull()
    return a, n


def _merge(a: Optional[_Node], b: Optional[_Node]) -> Optional[_Node]:
    if a is None:
        return b
    if b is None:
        return a
    if a.prio >= b.prio:
        a.right = _merge(a.right, b)
        a.pull()
        return a
    b.left = _merge(a, b.left)
    b.pull()
    return b


class IndexedSet:
    def __init__(self) -> None:
        self._root: Optional[_Node] = None
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def total(self) -> int:
        return self._root.sum if self._root is not None else 0

    def get(self, key: bytes) -> Optional[int]:
        n = self._root
        while n is not None:
            if key == n.key:
                return n.metric
            n = n.left if key < n.key else n.right
        return None

    def insert(self, key: bytes, metric: int) -> Optional[int]:
        """Set key's metric; returns the previous metric (None if new)."""
        old = self.erase(key)
        node = _Node(key, metric)
        a, b = _split(self._root, key)
        self._root = _merge(_merge(a, node), b)
        self._n += 1
        return old

    def erase(self, key: bytes) -> Optional[int]:
        """Remove key; returns its metric (None if absent)."""
        a, rest = _split(self._root, key)
        mid, b = _split(rest, key_after(key))
        self._root = _merge(a, b)
        if mid is None:
            return None
        self._n -= 1
        return mid.metric

    def erase_range(self, begin: bytes, end: bytes) -> int:
        """Remove every key in [begin, end); returns the erased metric sum."""
        a, rest = _split(self._root, begin)
        mid, b = _split(rest, end)
        self._root = _merge(a, b)
        if mid is None:
            return 0
        # count erased nodes
        def count(n):
            return 0 if n is None else 1 + count(n.left) + count(n.right)
        self._n -= count(mid)
        return mid.sum

    def sum_below(self, key: bytes) -> int:
        """Metric sum of every entry with key < `key` (sumTo)."""
        n = self._root
        acc = 0
        while n is not None:
            if n.key < key:
                acc += n.metric
                if n.left is not None:
                    acc += n.left.sum
                n = n.right
            else:
                n = n.left
        return acc

    def split_key(self) -> Optional[bytes]:
        """The FIRST key (ascending) whose inclusive prefix sum doubles to
        at least the total — the byte-sample median split point
        (StorageMetrics' splitEstimate)."""
        total = self.total()
        if total <= 0 or self._root is None:
            return None
        n = self._root
        acc = 0   # metric strictly left of the current subtree
        best: Optional[bytes] = None
        while n is not None:
            left_sum = n.left.sum if n.left is not None else 0
            inclusive = acc + left_sum + n.metric
            if 2 * inclusive >= total:
                best = n.key
                n = n.left
            else:
                acc += left_sum + n.metric
                n = n.right
        return best

    def items(self) -> Iterator[Tuple[bytes, int]]:
        """Ascending (key, metric) pairs (iterative; no recursion limit)."""
        stack: List[_Node] = []
        n = self._root
        while stack or n is not None:
            while n is not None:
                stack.append(n)
                n = n.left
            n = stack.pop()
            yield n.key, n.metric
            n = n.right
