"""Fault-injection coin flips and coverage probes.

Analog of the reference's BUGGIFY macro (flow/flow.h:65-66) and TEST coverage
probes (220 call sites): per-call-site randomized fault triggers, enabled only
in simulation, each site firing with an independently-chosen probability so a
long simulation eventually exercises every rare branch. Coverage is harvested
per site like flow/coveragetool.
"""
from __future__ import annotations

import inspect
from typing import Dict, Optional, Tuple

from .rng import DeterministicRandom

_enabled = False
_rng: Optional[DeterministicRandom] = None
#: site -> (activated?, fire probability)
_sites: Dict[Tuple[str, int], Tuple[bool, float]] = {}
#: coverage: site/comment -> times condition held
coverage: Dict[Tuple[str, int, str], int] = {}
#: buggify sites that actually FIRED (returned True); NOT cleared by
#: enable(), so a coverage harvest can union firings across many seeds
#: (the flow/coveragetool role for fault-injection sites)
fired: set = set()

SITE_ACTIVATED_PROBABILITY = 0.25
FIRE_PROBABILITY = 0.05


def enable(rng: DeterministicRandom) -> None:
    global _enabled, _rng
    _enabled = True
    _rng = rng
    _sites.clear()


def disable() -> None:
    global _enabled, _rng
    _enabled = False
    _rng = None


def is_enabled() -> bool:
    return _enabled


def buggify() -> bool:
    """True at randomly-activated call sites with small probability.

    Mirrors the reference's two-level scheme: each site is first activated
    with probability P_activate for the whole simulation, then fires per-call
    with probability P_fire (flow/FaultInjection.cpp)."""
    if not _enabled or _rng is None:
        return False
    frame = inspect.currentframe()
    caller = frame.f_back if frame else None
    site = (caller.f_code.co_filename, caller.f_lineno) if caller else ("?", 0)
    if site not in _sites:
        _sites[site] = (_rng.random01() < SITE_ACTIVATED_PROBABILITY, FIRE_PROBABILITY)
    activated, p = _sites[site]
    hit = activated and _rng.random01() < p
    if hit:
        fired.add(site)
    return hit


def test_probe(condition: bool, comment: str) -> bool:
    """Coverage probe: records that a rare branch was reached
    (reference: TEST(condition) macro)."""
    if condition:
        frame = inspect.currentframe()
        caller = frame.f_back if frame else None
        site = (
            caller.f_code.co_filename if caller else "?",
            caller.f_lineno if caller else 0,
            comment,
        )
        coverage[site] = coverage.get(site, 0) + 1
    return condition
