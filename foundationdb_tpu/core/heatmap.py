"""Decaying keyspace-heat aggregator: the host half of the resolver-state
observability layer (docs/observability.md "Keyspace heat & occupancy").

The device side (`ops/conflict_kernel.heat_of`) emits one small packed
aggregate per resolved batch — a read/write/conflict histogram over B
bucket-boundary keys sampled from the interval table, verdict counts,
table occupancy, GC-reclaimed rows, and a first-witness abort attribution
per transaction. This module merges those aggregates across batches into
a decayed per-key-range weight map and answers the questions the device
cannot:

  * where in the keyspace do conflicts concentrate (`hot_ranges`,
    `concentration` — a normalized Herfindahl index of the load split);
  * how full is the history table and how hard is GC working
    (`occupancy`, headroom, reclaimed totals);
  * where should key-range shard boundaries go (`split_points` — the
    direct input to ROADMAP item 1's multi-chip key-range sharding:
    Harmonia-style partitioned conflict detection needs a measured load
    split, and this IS the measurement).

Merging is keyed by the decoded boundary BEGIN key, not the bucket index:
the device's bucket grid shifts as the table evolves (and differs per
sub-shard), but a key is a key — so step, sub-sharded, mesh and loop
engines all merge through the same path, and multi-shard aggregates
interleave correctly.

Bit-safety: the aggregator only ever consumes outputs; it can never touch
a verdict. Everything here is plain numpy/python — no jax import, so the
disabled path (`resolver_heat_buckets = 0`) costs nothing and imports
nothing device-side.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: heat histogram lanes (must match ops/conflict_kernel.HEAT_HIST_LANES)
LANE_READS, LANE_WRITES, LANE_CONFLICTS = 0, 1, 2
#: counts lanes (ops/conflict_kernel.HEAT_COUNT_LANES)
C_COMMITTED, C_CONFLICTS, C_TOO_OLD, C_RECLAIMED = 0, 1, 2, 3


# the one boundary-key renderer (printable ASCII as text, else 0x-hex),
# shared with the shard map's report dicts
from .keyshard import _fmt_key  # noqa: E402  (re-export, existing users)


def _unpack_key(row: np.ndarray, key_words: int) -> bytes:
    """Packed (words..., length) row -> key bytes (keypack inverse,
    numpy-only so the aggregator never imports the ops package)."""
    length = int(row[key_words])
    raw = np.ascontiguousarray(row[:key_words], dtype=np.uint32) \
        .astype(">u4").tobytes()
    return raw[: min(length, 4 * key_words)]


def _unpack_keys(bounds: np.ndarray, key_words: int) -> List[bytes]:
    """All B boundary rows decoded in one vectorized pass — this runs on
    the serving force/drain path once per merged chunk, so no per-word
    Python byte juggling."""
    kw4 = 4 * key_words
    raw = np.ascontiguousarray(bounds[:, :key_words], dtype=np.uint32) \
        .astype(">u4").tobytes()
    lens = np.minimum(bounds[:, key_words].astype(np.int64), kw4)
    return [raw[b * kw4: b * kw4 + int(lens[b])]
            for b in range(bounds.shape[0])]


class KeyRangeHeatAggregator:
    """Decayed per-key-range weights merged from per-batch device heat
    aggregates. One instance per engine (ops/host_engine.py constructs it
    when the config's heat_buckets > 0); thread-safe enough for the
    pipeline's pack/force interleave because merge() and readers only
    touch python dicts under the GIL and never iterate while mutating."""

    #: retained key-range entries (boundary grids shift as the table
    #: evolves; pruning keeps the map bounded without losing hot ranges)
    MAX_RANGES = 512
    #: retained first-witness attribution samples
    MAX_ATTRIBUTION = 64

    def __init__(self, key_words: int, capacity: int,
                 buckets: int, decay: float = 0.98):
        self.key_words = int(key_words)
        self.capacity = int(capacity)
        self.buckets = int(buckets)
        #: per-merge multiplicative decay of every existing weight — the
        #: `resolver_heat_decay` knob; 1.0 = lifetime totals, smaller =
        #: faster forgetting (a diurnal hot-spot shift stops dominating
        #: split planning after ~1/(1-decay) batches)
        self.decay = float(decay)
        #: begin-key bytes -> float64 [reads, writes, conflicts]
        self._w: Dict[bytes, np.ndarray] = {}
        self.batches = 0
        self.occupancy = 0
        self.gc_reclaimed_total = 0
        self.verdict_totals = {"committed": 0, "conflicts": 0, "too_old": 0}
        # tiered-history run accounting (docs/perf.md "Incremental history
        # maintenance"): mirrored host-side from the heat aggregate's
        # `runs` leaf — the live run-stack depth each batch leaves behind.
        # Appends/merges are derived from per-shard depth TRANSITIONS
        # (depth up by d = d appends; depth down = one lazy merge
        # compacted the stack, and the post-merge depth is the appends it
        # was left with), so the counters are exact with zero device
        # syncs. Monolithic engines never emit the leaf; everything stays 0.
        self.history_appends_total = 0
        self.history_merges_total = 0
        self.history_runs_live = 0
        self.history_run_rows_live = 0
        self._hist_nruns: Dict[int, int] = {}
        #: recent first-witness abort attributions: which prior write
        #: (version) killed a transaction, and in which key range
        self.attribution: deque = deque(maxlen=self.MAX_ATTRIBUTION)
        #: consumable copy of the witness stream for drain_witnesses():
        #: `attribution` above is a DISPLAY ring (cli heat, blackbox,
        #: attribution_for) that readers peek without consuming; a second
        #: reader that also peeked it would double-count samples, so
        #: consumers (the conflict scheduler) get their own queue that
        #: drains atomically. Raw begin-key bytes, not formatted.
        self._pending_witnesses: deque = deque(maxlen=4 * self.MAX_ATTRIBUTION)
        #: last ADOPTED split points (split-point hysteresis: a fresh
        #: equal-load derivation replaces these only when it improves the
        #: measured imbalance by at least the hysteresis knob — two
        #: adjacent scrapes of a stationary stream must not flap the
        #: resharding controller by one bucket)
        self._last_splits: Optional[List[bytes]] = None

    # -- merging -------------------------------------------------------------
    def merge(self, heat: Dict[str, np.ndarray], base: int = 0,
              version: Optional[int] = None) -> None:
        """Fold ONE single-shard batch's device heat aggregate (unstacked
        leaves, as emitted by resolve_step) into the decayed map. `base`
        is the engine's version base (device versions are base-relative);
        `version` is the batch's commit version when the caller knows it
        (attribution samples carry it)."""
        self.merge_shards([heat], base=base, version=version)

    def merge_shards(self, per_shard: Sequence[Dict[str, np.ndarray]],
                     base: int = 0, version: Optional[int] = None) -> None:
        """Fold ONE batch resolved across `len(per_shard)` key-range
        shards (sub-sharded / mesh engines: each shard's own table
        delimits its buckets, all for the SAME transactions). The
        histogram merges per shard keyed by boundary key, but the
        batch-GLOBAL lanes are counted once: committed/conflicts/too_old
        are replicated across shards (the stacked-batch contract), decay
        ticks once per batch, and occupancy SUMS the shard tables (the
        capacity passed at construction is the summed capacity too).
        gc_reclaimed is shard-local and sums."""
        self.batches += 1
        counts0 = np.asarray(per_shard[0]["counts"], dtype=np.int64)
        self.verdict_totals["committed"] += int(counts0[C_COMMITTED])
        self.verdict_totals["conflicts"] += int(counts0[C_CONFLICTS])
        self.verdict_totals["too_old"] += int(counts0[C_TOO_OLD])
        self.occupancy = sum(int(np.asarray(h["occupancy"]))
                             for h in per_shard)
        if "run_rows" in per_shard[0]:
            self.history_run_rows_live = sum(
                int(np.asarray(h["run_rows"])) for h in per_shard)
        if self.decay < 1.0 and self._w:
            for w in self._w.values():
                w *= self.decay
        samples = 0
        for si, heat in enumerate(per_shard):
            bounds = np.asarray(heat["bounds"])
            hist = np.asarray(heat["hist"], dtype=np.int64)
            self.gc_reclaimed_total += int(
                np.asarray(heat["counts"], dtype=np.int64)[C_RECLAIMED])
            if "runs" in heat:
                self._note_history_runs(si, int(np.asarray(heat["runs"])))
            keys = _unpack_keys(bounds, self.key_words)
            for b, key in enumerate(keys):
                row = hist[b]
                if not row.any():
                    continue
                w = self._w.get(key)
                if w is None:
                    w = np.zeros((3,), np.float64)
                    self._w[key] = w
                w += row
            # first-witness attribution samples (a handful per batch; a
            # multi-shard txn may witness on the shard that owns the row)
            wb = np.asarray(heat["wit_bucket"])
            if wb.size and samples < 4:
                aborted = np.flatnonzero(wb >= 0)
                wv = np.asarray(heat["wit_ver"])
                for t in aborted[: 4 - samples]:
                    samples += 1
                    self.attribution.append({
                        "txn_index": int(t),
                        "version": version,
                        "witness_version": int(wv[t]) + base,
                        "range_begin": _fmt_key(keys[int(wb[t])]),
                    })
                    self._pending_witnesses.append({
                        "version": version,
                        "witness_version": int(wv[t]) + base,
                        "range_begin": keys[int(wb[t])],
                    })
        self._prune()

    def _note_history_runs(self, shard: int, nruns: int) -> None:
        """Fold one shard's post-apply run-stack depth into the derived
        append/merge counters (see __init__). `nruns == 0` with a prior
        nonzero depth is a zero-initialized plane (a loop slot that never
        ran a batch), not a merge — real merges always fire under a batch
        that then appends, leaving depth >= 1."""
        old = self._hist_nruns.get(shard, 0)
        if nruns > old:
            self.history_appends_total += nruns - old
        elif 0 < nruns < old:
            # the stack can only SHRINK through a lazy merge: the slots
            # were full at apply time, the merge retired them into the
            # base table, and the depth left behind is the batch's own
            # appends (1 on the device path). Equal depth is a
            # write-free batch — no append, no merge.
            self.history_merges_total += 1
            self.history_appends_total += nruns
        else:
            return  # equal depth (no writes) or a zero-initialized plane
        self._hist_nruns[shard] = nruns
        self.history_runs_live = sum(self._hist_nruns.values())

    def history_snapshot(self) -> Dict[str, int]:
        """The tiered-history counter fragment (host_engine
        history_stats_snapshot merges it under the structure identity)."""
        return {
            "appends": self.history_appends_total,
            "merges": self.history_merges_total,
            "runs_live": self.history_runs_live,
            "run_rows_live": self.history_run_rows_live,
        }

    def observe_batch(self, transactions, verdicts,
                      version: Optional[int] = None) -> None:
        """Host-fed merge path: fold ONE resolved batch's conflict ranges
        directly into the decayed map, keyed by each range's begin key.

        The device path (`merge`/`merge_shards`) rides the resolve step's
        packed aggregate and its table-sampled bucket grid; this path
        serves engines without the device layer (the CPU oracle, an
        elastic group of supervised engines — server/reshard.py) from the
        transactions the host already holds. Same read model either way:
        hot_ranges / concentration / split_points answer identically, the
        grid is just the observed range-begin keys instead of sampled
        table boundaries. Reads land in the reads lane; committed writes
        in the writes lane; a conflicted transaction's read begins in the
        conflicts lane (where the contention actually bit)."""
        from .types import TransactionCommitResult

        self.batches += 1
        committed = int(TransactionCommitResult.COMMITTED)
        too_old = int(TransactionCommitResult.TOO_OLD)
        if self.decay < 1.0 and self._w:
            for w in self._w.values():
                w *= self.decay

        def lane(key: bytes, ln: int, amount: float = 1.0) -> None:
            w = self._w.get(key)
            if w is None:
                w = self._w[key] = np.zeros((3,), np.float64)
            w[ln] += amount

        samples = 0
        for t, txn in enumerate(transactions):
            v = int(verdicts[t])
            if v == committed:
                self.verdict_totals["committed"] += 1
            elif v == too_old:
                self.verdict_totals["too_old"] += 1
            else:
                self.verdict_totals["conflicts"] += 1
            for r in txn.read_conflict_ranges:
                lane(r.begin, LANE_READS)
                if v != committed and v != too_old:
                    lane(r.begin, LANE_CONFLICTS)
            if v == committed:
                for r in txn.write_conflict_ranges:
                    lane(r.begin, LANE_WRITES)
            elif (v != too_old and version is not None and samples < 4
                  and txn.read_conflict_ranges):
                # sampled abort attribution, the host-fed analog of the
                # device path's first-witness ring: the host doesn't know
                # WHICH prior write convicted, but the aborted range and
                # batch version still place the contention
                samples += 1
                self.attribution.append({
                    "txn_index": t,
                    "version": int(version),
                    "witness_version": None,
                    "range_begin": _fmt_key(
                        txn.read_conflict_ranges[0].begin),
                })
                self._pending_witnesses.append({
                    "version": int(version),
                    "witness_version": None,
                    "range_begin": txn.read_conflict_ranges[0].begin,
                })
        self._prune()

    def drain_witnesses(self) -> List[dict]:
        """Consume the pending first-witness samples atomically and return
        them. `attribution` is a peek-only display ring shared by `cli
        heat`, the black-box batch records and `attribution_for`; any
        consumer that also peeked it would double-count samples it saw on
        a previous read. Consumers (the conflict scheduler) call this
        instead: each sample is returned exactly once, with the RAW begin
        key bytes (`range_begin`) so the consumer can key its own maps.
        Single swap-then-read, so a merge interleaved from the pipeline's
        pack/force never splits a sample between two drains."""
        pending, self._pending_witnesses = (
            self._pending_witnesses,
            deque(maxlen=self._pending_witnesses.maxlen))
        return list(pending)

    def attribution_for(self, version: int) -> List[dict]:
        """The retained first-witness attribution samples of ONE batch
        version — what the black-box journal attaches to that batch's
        record (core/blackbox.py) and `cli explain` leads its verdict
        line with."""
        return [dict(a) for a in self.attribution
                if a.get("version") == version]

    def reset_weights(self) -> None:
        """Drop the accumulated range weights and attribution samples
        (verdict/occupancy totals stay). Useful after a warm-up phase:
        while the table is still filling, the bucket grid shifts batch to
        batch and spreads one key's load across neighboring begin keys —
        resetting once the keyspace is populated measures the steady
        state on a stationary grid."""
        self._w.clear()
        self.attribution.clear()
        self._pending_witnesses.clear()
        self._last_splits = None

    def _prune(self) -> None:
        if len(self._w) <= self.MAX_RANGES:
            return
        ranked = sorted(self._w.items(), key=lambda kv: -float(kv[1].sum()))
        self._w = dict(ranked[: self.MAX_RANGES])

    # -- read model ----------------------------------------------------------
    def _sorted_items(self) -> List[Tuple[bytes, np.ndarray]]:
        return sorted(self._w.items(), key=lambda kv: kv[0])

    def total_load(self) -> float:
        """The split-planning load measure: write rows + conflict rows
        (conflicts weigh where contention actually bites, not just where
        bytes land)."""
        if not self._w:
            return 0.0
        return float(sum(w[LANE_WRITES] + w[LANE_CONFLICTS]
                         for w in self._w.values()))

    def hot_ranges(self, top_n: int = 8) -> List[dict]:
        """Top-N key ranges by write+conflict load, with each range's end
        key (the next boundary in key order; None = +inf)."""
        items = self._sorted_items()
        total = self.total_load() or 1.0
        scored = []
        for i, (key, w) in enumerate(items):
            end = items[i + 1][0] if i + 1 < len(items) else None
            load = float(w[LANE_WRITES] + w[LANE_CONFLICTS])
            scored.append({
                "begin": _fmt_key(key),
                "end": _fmt_key(end) if end is not None else None,
                "reads": round(float(w[LANE_READS]), 1),
                "writes": round(float(w[LANE_WRITES]), 1),
                "conflicts": round(float(w[LANE_CONFLICTS]), 1),
                "share": round(load / total, 4),
            })
        scored.sort(key=lambda r: -r["share"])
        return scored[:top_n]

    def concentration(self) -> float:
        """Normalized Herfindahl index of the write+conflict load split
        across ranges: 0 = perfectly even, 1 = all load in one range.
        Monotone in workload skew — the `conflict_heat` bench asserts it
        tracks the fleet's Zipf s."""
        loads = np.array([w[LANE_WRITES] + w[LANE_CONFLICTS]
                          for w in self._w.values()], np.float64)
        n = loads.size
        total = float(loads.sum())
        if n <= 1 or total <= 0:
            return 0.0
        f = loads / total
        hhi = float(np.sum(f * f))
        return max(0.0, (hhi - 1.0 / n) / (1.0 - 1.0 / n))

    def split_points(self, shards: Optional[int] = None) -> List[bytes]:
        """`shards - 1` suggested key-range split keys that equalize the
        measured write+conflict load — the direct input to multi-chip
        key-range sharding (ROADMAP item 1). Split i is the first range
        boundary whose cumulative load reaches i/shards of the total, so
        per-shard imbalance is bounded by the heaviest single bucket's
        share (finer device bucket grids tighten it)."""
        if shards is None:
            shards = self.default_split_shards()
        items = self._sorted_items()
        if not items or shards < 2:
            return []
        loads = np.array([w[LANE_WRITES] + w[LANE_CONFLICTS]
                          for _k, w in items], np.float64)
        total = float(loads.sum())
        if total <= 0:
            return []
        cum = np.cumsum(loads)
        out: List[bytes] = []
        for i in range(1, shards):
            j = int(np.searchsorted(cum, total * i / shards))
            j = min(j + 1, len(items) - 1)   # split at the NEXT begin key
            key = items[j][0]
            if not out or key > out[-1]:
                out.append(key)
        # Split-point hysteresis (the `resolver_heat_split_hysteresis`
        # knob): the equal-load derivation above re-runs on the DECAYED
        # weights every call, so two adjacent scrapes of a stationary
        # stream can disagree by one bucket — enough to flap an online
        # resharding controller between two near-equal plans. Keep the
        # last adopted splits unless the fresh candidate improves the
        # measured per-shard imbalance by at least the knob.
        last = self._last_splits
        if (last is not None and last != out
                and len(last) == len(out)):
            imb_last = self._imbalance(self.split_balance(shards, last))
            imb_new = self._imbalance(self.split_balance(shards, out))
            if imb_last - imb_new < self._split_hysteresis():
                return list(last)
        self._last_splits = list(out)
        return out

    def split_key_within(self, begin: bytes,
                         end: Optional[bytes]) -> Optional[bytes]:
        """The measured equal-load midpoint key STRICTLY inside span
        [begin, end) — where an online split of that span should cut
        (server/reshard.py). None when the span's load sits in a single
        retained bucket (nothing to split on)."""
        items = [(k, w) for k, w in self._sorted_items()
                 if k >= begin and (end is None or k < end)]
        if len(items) < 2:
            return None
        loads = [float(w[LANE_WRITES] + w[LANE_CONFLICTS]) for _k, w in items]
        total = sum(loads)
        if total <= 0:
            return None
        acc = 0.0
        for i, (k, _w) in enumerate(items):
            acc += loads[i]
            if acc >= total / 2 and i + 1 < len(items):
                key = items[i + 1][0]
                if key > begin and (end is None or key < end):
                    return key
                return None
        return None

    @staticmethod
    def _imbalance(fracs: Sequence[float]) -> float:
        """Worst per-shard deviation from the equal-load ideal."""
        if not fracs:
            return 0.0
        ideal = 1.0 / len(fracs)
        return max(abs(f - ideal) for f in fracs)

    @staticmethod
    def _split_hysteresis() -> float:
        from .knobs import SERVER_KNOBS

        return float(getattr(SERVER_KNOBS,
                             "resolver_heat_split_hysteresis", 0.05))

    def split_balance(self, shards: Optional[int] = None,
                      splits: Optional[Sequence[bytes]] = None) -> List[float]:
        """Measured load fraction per shard under `splits` (default: the
        suggested split_points) — what the heat-smoke/bench assert stays
        within tolerance of 1/shards."""
        if shards is None:
            shards = self.default_split_shards()
        if splits is None:
            splits = self.split_points(shards)
        items = self._sorted_items()
        total = self.total_load()
        if not items or total <= 0:
            return []
        frac = [0.0] * (len(splits) + 1)
        for key, w in items:
            s = 0
            for sp in splits:
                if key >= sp:
                    s += 1
                else:
                    break
            frac[s] += float(w[LANE_WRITES] + w[LANE_CONFLICTS]) / total
        return frac

    @staticmethod
    def default_split_shards() -> int:
        from .knobs import SERVER_KNOBS

        return int(getattr(SERVER_KNOBS, "resolver_heat_split_shards", 8))

    # -- snapshots -----------------------------------------------------------
    def occupancy_frac(self) -> float:
        return self.occupancy / self.capacity if self.capacity else 0.0

    def brief(self) -> dict:
        """Tiny span/flight-record attachment: enough to say whether a
        slow or quarantined batch ran under hot-key pressure. Runs on the
        supervisor's per-batch path, so it is one argmax pass over the
        raw weights — no sorting, and only the winning key is formatted
        (hot_ranges would format every retained range)."""
        best_key, best_load, total = None, 0.0, 0.0
        for key, w in self._w.items():
            load = float(w[LANE_WRITES] + w[LANE_CONFLICTS])
            total += load
            if load > best_load:
                best_load, best_key = load, key
        return {
            "conflicts": self.verdict_totals["conflicts"],
            "occupancy_frac": round(self.occupancy_frac(), 4),
            "concentration": round(self.concentration(), 4),
            "top_range": _fmt_key(best_key) if best_key is not None else None,
            "top_share": round(best_load / total, 4) if total > 0 else 0.0,
        }

    def snapshot(self, top_n: int = 8, brief: bool = False) -> dict:
        """The status-document / CLI fragment: hot ranges, occupancy
        headroom, verdict totals, and the suggested split points."""
        if brief:
            return self.brief()
        shards = self.default_split_shards()
        splits = self.split_points(shards)
        return {
            "batches": self.batches,
            "buckets": self.buckets,
            "capacity": self.capacity,
            "occupancy": self.occupancy,
            "occupancy_frac": round(self.occupancy_frac(), 4),
            "gc_reclaimed": self.gc_reclaimed_total,
            "verdicts": dict(self.verdict_totals),
            "concentration": round(self.concentration(), 4),
            "hot_ranges": self.hot_ranges(top_n=top_n),
            "split_shards": shards,
            "split_points": [_fmt_key(k) for k in splits],
            "split_balance": [round(f, 4)
                              for f in self.split_balance(shards, splits)],
            "recent_attribution": list(self.attribution)[-top_n:],
            "history": self.history_snapshot(),
        }


def heat_buckets_from_knobs() -> int:
    """The `resolver_heat_buckets` knob: device-side histogram buckets per
    resolve step; 0 disables the whole layer (no heat outputs in any
    program, no aggregator, nothing allocated)."""
    from .knobs import SERVER_KNOBS

    return int(getattr(SERVER_KNOBS, "resolver_heat_buckets", 0) or 0)


def aggregator_for(cfg, n_shards: int = 1) -> Optional[KeyRangeHeatAggregator]:
    """Aggregator for an engine's KernelConfig, or None when heat is off.
    `n_shards` scales the capacity gauge: each key-range shard owns a
    capacity-H table, and merge_shards sums their occupancies."""
    if getattr(cfg, "heat_buckets", 0) <= 0:
        return None
    from .knobs import SERVER_KNOBS

    return KeyRangeHeatAggregator(
        key_words=cfg.key_words,
        capacity=cfg.capacity * max(1, n_shards),
        buckets=cfg.heat_buckets,
        decay=float(getattr(SERVER_KNOBS, "resolver_heat_decay", 0.98)),
    )
