"""TDMetric: time-series metrics with on-cluster persistence.

Re-design of flow/TDMetric.actor.h (1373 LoC) reduced to its load-bearing
shape: named metrics record (time, value) CHANGES (not samples — a
time-series of a level metric is its edit history, which reconstructs the
exact value at any time), buffered in bounded in-memory blocks that the
MetricLogger (client/metric_logger.py) periodically drains into the
database's `\\xff/metrics/` keyspace, where they are queryable by
(metric, time range) — the reference's metric-database design
(fdbclient/MetricLogger.actor.cpp).

  * Int64Metric   — a level: set()/increment(); records on change
  * BoolMetric    — a level of 0/1
  * ContinuousMetric — an event stream: log(value) records every event
  * TDMetricCollection — the per-process registry the logger drains
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: per-metric in-memory buffer bound: oldest entries drop first (the
#: reference bounds block memory the same way; persistence is best-effort
#: telemetry, never backpressure)
MAX_BUFFERED = 4096


class _BaseMetric:
    def __init__(self, collection: "TDMetricCollection", name: str):
        self.name = name
        self.collection = collection
        #: undrained (time, value) entries
        self.buffer: List[Tuple[float, int]] = []

    def _record(self, value: int) -> None:
        self.buffer.append((self.collection.now(), value))
        if len(self.buffer) > MAX_BUFFERED:
            del self.buffer[: len(self.buffer) - MAX_BUFFERED]

    def drain(self) -> List[Tuple[float, int]]:
        out, self.buffer = self.buffer, []
        return out


class Int64Metric(_BaseMetric):
    """A level metric: the series is its change history."""

    def __init__(self, collection, name):
        super().__init__(collection, name)
        self.value = 0

    def set(self, v: int) -> None:
        if v != self.value:
            self.value = v
            self._record(v)

    def increment(self, by: int = 1) -> None:
        self.value += by
        self._record(self.value)


class BoolMetric(Int64Metric):
    def set(self, v) -> None:  # type: ignore[override]
        super().set(1 if v else 0)


class ContinuousMetric(_BaseMetric):
    """An event metric: every log() is an entry."""

    def log(self, value: int = 1) -> None:
        self._record(value)


class TDMetricCollection:
    """Per-process metric registry (TDMetricCollection's role). `now` is
    injected (the sim's virtual clock or the wall clock)."""

    def __init__(self, now=None):
        import time as _time

        self.now = now or _time.monotonic  # fdbtpu-lint: allow[determinism] wall-mode default only; the sim passes its virtual clock as `now`
        self.metrics: Dict[str, _BaseMetric] = {}

    def int64(self, name: str) -> Int64Metric:
        m = self.metrics.get(name)
        if m is None:
            m = self.metrics[name] = Int64Metric(self, name)
        assert isinstance(m, Int64Metric)
        return m

    def bool(self, name: str) -> BoolMetric:
        m = self.metrics.get(name)
        if m is None:
            m = self.metrics[name] = BoolMetric(self, name)
        assert isinstance(m, BoolMetric)
        return m

    def continuous(self, name: str) -> ContinuousMetric:
        m = self.metrics.get(name)
        if m is None:
            m = self.metrics[name] = ContinuousMetric(self, name)
        assert isinstance(m, ContinuousMetric)
        return m

    def drain_all(self) -> Dict[str, List[Tuple[float, int]]]:
        """Undrained entries of every metric (cleared)."""
        out = {}
        for name, m in self.metrics.items():
            entries = m.drain()
            if entries:
                out[name] = entries
        return out

    def value_at(self, name: str, t: float,
                 persisted: List[Tuple[float, int]]) -> Optional[int]:
        """Reconstruct a level metric's value at time t from its persisted
        change history (the TDMetric read model)."""
        best = None
        for et, v in persisted:
            if et <= t:
                best = v
        return best
