"""Cluster watchdog: a deterministic rule engine over the telemetry hub.

Everything before this module MEASURES (spans, heat, the compile/memory
ledger, sampled device timing); nothing EVALUATES. SLOs were asserted
post-hoc at campaign end, so a degrading cluster only learned about it
from the autopsy. The watchdog closes that gap: declarative `AlertRule`s
are evaluated on every `TelemetryHub.sync()` over the hub's existing
series — no new collection path, no device interaction, zero extra host
syncs — and firing alerts group into `Incident`s machine-correlated
against injected fault windows, ResilientEngine health transitions and
the tail-sampled trace root cause, so a breach reads
"slo_p99_burn firing · overlaps partition window · dominant=server_resolve
· resolver resilient.2 state=probation" instead of a bare gauge.

Rule classes (docs/observability.md "Watchdog, burn rates & incidents"):

  * `ThresholdRule`   — level/counter compare (blocking_syncs > 0,
    steady-state compiles > 0, state_memory_pressure, resolver health
    state >= suspect);
  * `StalenessRule`   — a counter that must advance under traffic stops
    changing (commit flow stalled);
  * `AnomalyRule`     — EWMA mean/variance z-score bands (heat
    concentration shifts that announce a moving hot spot);
  * `BurnRateRule`    — multi-window SLO burn rates (Google-SRE style
    fast+slow window pair over an error budget: p99-vs-budget, abort
    fraction, tenant throttle rate). Both windows must burn above the
    threshold, so a blip can't fire and a slow leak can't hide.

Lifecycle: ok -> pending (condition active) -> firing (active for
`watchdog_hold_s`) -> resolved (clear for `watchdog_clear_s`) -> ok.
Every transition lands in a bounded ring (`watchdog_alert_ring`) and the
firing set is exported as `alerts.*` hub series — the ALERTS-style
`fdbtpu_alerts` Prometheus family.

Determinism contract (fdbtpu-lint applies): the clock is `span_now()`
(the sim's virtual clock when one is installed), evaluation draws no
rng, iterates only insertion-ordered dicts, and reads only host-side
python values — abort sets are bit-identical with the watchdog on and
`blocking_syncs` stays 0 (tests/test_watchdog.py pins both). The
disabled path is one attribute check in `sync()`: `watchdog_enabled`
off allocates nothing and adds <5 µs/call (the NULL_SPAN-style
allocation-counter guard).
"""
from __future__ import annotations

import re
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import blackbox
from .trace import span_now

#: allocation counter for the disabled-path regression guard
#: (tests/test_watchdog.py, the core/trace.py span_allocations pattern):
#: bumped whenever the watchdog allocates evaluation state — with the
#: watchdog off, `hub().sync()` must leave it untouched
watchdog_allocations = [0]

#: alert lifecycle states (exposition index: `alerts.<...>.state`)
OK, PENDING, FIRING = 0, 1, 2
STATE_NAMES = {OK: "ok", PENDING: "pending", FIRING: "firing"}

#: incidents retained (closed ones age out oldest-first)
MAX_INCIDENTS = 64
#: resolver health-state transitions retained for incident correlation
MAX_HEALTH_TRANSITIONS = 128
#: minimum good+bad events inside a burn window before the rule may fire
#: (a single bad request out of two must never page)
BURN_MIN_EVENTS = 8


def _pattern_re(pattern: str) -> "re.Pattern":
    """A dotted series pattern with `*` wildcards -> regex with one
    capture group per `*` (the captures key multi-series rules)."""
    parts = [re.escape(p) for p in pattern.split("*")]
    return re.compile("^" + "(.+)".join(parts) + "$")


class _SeriesView:
    """One evaluation tick's read model over the hub's TDMetric series:
    current values plus a per-rule match cache invalidated when the
    series population grows (it only grows — metrics are never deleted).
    `hub` (when the evaluator passes it) lets a rule read a registered
    source's richer detail — e.g. the stalled-reshard rule naming the
    frozen range — without growing the series surface."""

    def __init__(self, metrics: Dict[str, Any], hub: Any = None):
        self.metrics = metrics
        self.hub = hub

    def value(self, name: str) -> Optional[float]:
        m = self.metrics.get(name)
        if m is None:
            return None
        return float(getattr(m, "value", 0))


class AlertRule:
    """Base declarative rule: subclasses implement `conditions(t, view)`
    yielding (series_key, active, value, detail) per tracked series
    group. hold/clear default to the watchdog_* knobs at evaluation time
    so `--knob` overrides steer a running campaign."""

    kind = "rule"

    def __init__(self, name: str, hold_s: Optional[float] = None,
                 clear_s: Optional[float] = None):
        self.name = name
        self.hold_s = hold_s
        self.clear_s = clear_s
        #: series-population size the match cache was built at
        self._cache_n = -1
        self._cache: Dict[str, List] = {}

    def _matches(self, view: _SeriesView, pattern_key: str,
                 rx: "re.Pattern") -> List[Tuple[str, Tuple[str, ...]]]:
        """(series_name, wildcard captures) for every matching series,
        cached until the hub grows a new series."""
        if self._cache_n != len(view.metrics):
            self._cache.clear()
            self._cache_n = len(view.metrics)
        hit = self._cache.get(pattern_key)
        if hit is None:
            hit = [(name, m.groups()) for name in view.metrics
                   for m in (rx.match(name),) if m is not None]
            self._cache[pattern_key] = hit
        return hit

    def resolved_hold_s(self) -> float:
        if self.hold_s is not None:
            return float(self.hold_s)
        from .knobs import SERVER_KNOBS

        return float(SERVER_KNOBS.watchdog_hold_s)

    def resolved_clear_s(self) -> float:
        if self.clear_s is not None:
            return float(self.clear_s)
        from .knobs import SERVER_KNOBS

        return float(SERVER_KNOBS.watchdog_clear_s)

    def conditions(self, t: float, view: _SeriesView):
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind}


class ThresholdRule(AlertRule):
    """value OP threshold over every series matching `pattern`."""

    kind = "threshold"
    _OPS = {">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
            "<": lambda a, b: a < b, "<=": lambda a, b: a <= b}

    def __init__(self, name: str, pattern: str, threshold: float,
                 op: str = ">", **kw):
        super().__init__(name, **kw)
        self.pattern = pattern
        self.threshold = float(threshold)
        self.op = op
        self._rx = _pattern_re(pattern)
        self._cmp = self._OPS[op]

    def conditions(self, t, view):
        for series, _caps in self._matches(view, self.pattern, self._rx):
            v = view.value(series)
            if v is None:
                continue
            yield (series, self._cmp(v, self.threshold), v,
                   f"{self.op} {self.threshold:g}")

    def describe(self):
        return {**super().describe(), "pattern": self.pattern,
                "op": self.op, "threshold": self.threshold}


class StalenessRule(AlertRule):
    """A series that must keep advancing under live traffic (a commit
    SLI total, a batch counter) has not changed for `max_age_s`. Arms at
    first sighting; absence before first sighting is not staleness (a
    cluster that never served is cold, not stalled)."""

    kind = "staleness"

    def __init__(self, name: str, pattern: str, max_age_s: float, **kw):
        super().__init__(name, **kw)
        self.pattern = pattern
        self.max_age_s = float(max_age_s)
        self._rx = _pattern_re(pattern)
        #: series -> [last value, t of last change]
        self._last: Dict[str, List[float]] = {}

    def conditions(self, t, view):
        for series, _caps in self._matches(view, self.pattern, self._rx):
            v = view.value(series)
            if v is None:
                continue
            st = self._last.get(series)
            if st is None:
                watchdog_allocations[0] += 1
                self._last[series] = [v, t]
                yield (series, False, 0.0, "armed")
                continue
            if v != st[0]:
                st[0], st[1] = v, t
            age = t - st[1]
            yield (series, age > self.max_age_s, age,
                   f"no change in {age:.2f}s (max {self.max_age_s:g}s)")

    def describe(self):
        return {**super().describe(), "pattern": self.pattern,
                "max_age_s": self.max_age_s}


class AnomalyRule(AlertRule):
    """EWMA z-score bands: the series' running mean/variance define the
    expected band; a sample more than `z_threshold` deviations out is
    anomalous. The band keeps adapting (the anomalous value folds into
    the EWMA), so a persistent level shift re-centres and the alert
    resolves — this rule flags CHANGE, the threshold rules flag state."""

    kind = "anomaly"
    #: EWMA smoothing for mean and variance
    ALPHA = 0.2
    #: observations before the band is trusted
    WARMUP = 8
    #: std floor: constant series jittering by one quantum must not page
    STD_FLOOR = 1.0

    def __init__(self, name: str, pattern: str,
                 z_threshold: Optional[float] = None, **kw):
        super().__init__(name, **kw)
        self.pattern = pattern
        self.z_threshold = z_threshold
        self._rx = _pattern_re(pattern)
        #: series -> [mean, var, n_seen]
        self._bands: Dict[str, List[float]] = {}

    def _z(self) -> float:
        if self.z_threshold is not None:
            return float(self.z_threshold)
        from .knobs import SERVER_KNOBS

        return float(SERVER_KNOBS.watchdog_z_threshold)

    def conditions(self, t, view):
        z_thr = self._z()
        for series, _caps in self._matches(view, self.pattern, self._rx):
            v = view.value(series)
            if v is None:
                continue
            band = self._bands.get(series)
            if band is None:
                watchdog_allocations[0] += 1
                self._bands[series] = [v, 0.0, 1]
                yield (series, False, 0.0, "warming")
                continue
            mean, var, n = band
            std = max(var ** 0.5, self.STD_FLOOR,
                      0.02 * abs(mean))
            z = (v - mean) / std
            active = n >= self.WARMUP and abs(z) > z_thr
            d = v - mean
            if active:
                # clamp the update for anomalous samples: the band WALKS
                # toward a level shift instead of swallowing it in one
                # EWMA step (which would collapse the z-score before the
                # hold window could fire) — the alert stays active while
                # the shift is still outside the widening band, then
                # resolves as the band converges on the new level
                d = (z_thr if d > 0 else -z_thr) * std
            band[0] = mean + self.ALPHA * d
            band[1] = (1 - self.ALPHA) * (var + self.ALPHA * d * d)
            band[2] = n + 1
            yield (series, active, round(z, 3),
                   f"z={z:.2f} band={mean:.1f}±{z_thr:g}·{std:.1f}")

    def describe(self):
        return {**super().describe(), "pattern": self.pattern,
                "z_threshold": self.z_threshold}


class BurnRateRule(AlertRule):
    """Multi-window SLO burn rate over a good/bad counter pair.

    burn = (bad / (good + bad)) / budget_frac over a trailing window;
    1.0 means the error budget is being spent exactly at the sustainable
    rate. The rule fires only when BOTH the fast and the slow window
    burn above `watchdog_burn_threshold` — the fast window gives
    detection latency, the slow window stops a blip from paging and
    makes the alert self-clearing once the bad rate stops (the standard
    multiwindow multi-burn-rate construction). Series pairs are joined
    by their `*` captures (one alert per engine/admission/SLI label);
    a missing bad-side series reads 0 (no errors yet)."""

    kind = "burn"

    def __init__(self, name: str, good_pattern: str, bad_pattern: str,
                 budget_frac: float, fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 threshold: Optional[float] = None,
                 min_events: int = BURN_MIN_EVENTS, **kw):
        super().__init__(name, **kw)
        self.good_pattern = good_pattern
        self.bad_pattern = bad_pattern
        self.budget_frac = float(budget_frac)
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.threshold = threshold
        self.min_events = int(min_events)
        self._good_rx = _pattern_re(good_pattern)
        self._bad_rx = _pattern_re(bad_pattern)
        #: capture key -> deque[(t, good, bad)]
        self._hist: Dict[Tuple[str, ...], deque] = {}

    def _knobs(self) -> Tuple[float, float, float]:
        from .knobs import SERVER_KNOBS

        k = SERVER_KNOBS
        return (float(self.fast_s if self.fast_s is not None
                      else k.watchdog_burn_fast_s),
                float(self.slow_s if self.slow_s is not None
                      else k.watchdog_burn_slow_s),
                float(self.threshold if self.threshold is not None
                      else k.watchdog_burn_threshold))

    @staticmethod
    def _at(hist: deque, target_t: float) -> Tuple[float, float]:
        """(good, bad) as of target_t: the newest sample at or before it,
        else the oldest sample (pre-history = the earliest observation,
        so a window wider than the history reads the full span)."""
        best = hist[0]
        for s in hist:
            if s[0] <= target_t:
                best = s
            else:
                break
        return best[1], best[2]

    def window_burn(self, key: Tuple[str, ...], window_s: float,
                    now_t: float) -> Tuple[float, float]:
        """(burn rate, events) over the trailing window — exposed so the
        smoke's hand computation checks the exact arithmetic the alert
        uses."""
        hist = self._hist.get(key)
        if not hist or len(hist) < 2:
            return 0.0, 0.0
        g1, b1 = hist[-1][1], hist[-1][2]
        g0, b0 = self._at(hist, now_t - window_s)
        dg, db = max(0.0, g1 - g0), max(0.0, b1 - b0)
        events = dg + db
        if events <= 0:
            return 0.0, 0.0
        return (db / events) / self.budget_frac, events

    def conditions(self, t, view):
        fast_s, slow_s, thr = self._knobs()
        bads = {caps: series for series, caps
                in self._matches(view, self.bad_pattern, self._bad_rx)}
        for series, caps in self._matches(view, self.good_pattern,
                                          self._good_rx):
            good = view.value(series) or 0.0
            bad_series = bads.get(caps)
            bad = (view.value(bad_series) or 0.0) \
                if bad_series is not None else 0.0
            hist = self._hist.get(caps)
            if hist is None:
                watchdog_allocations[0] += 1
                hist = self._hist[caps] = deque()
            hist.append((t, good, bad))
            while hist and hist[0][0] < t - 2 * slow_s:
                hist.popleft()
            burn_fast, ev_fast = self.window_burn(caps, fast_s, t)
            burn_slow, ev_slow = self.window_burn(caps, slow_s, t)
            active = (burn_fast > thr and burn_slow > thr
                      and ev_slow >= self.min_events)
            key = ".".join(caps) or series
            yield (key, active, round(min(burn_fast, burn_slow), 3),
                   f"burn fast={burn_fast:.2f}/slow={burn_slow:.2f} "
                   f"(thr {thr:g}, budget {self.budget_frac:g})")

    def describe(self):
        return {**super().describe(), "good": self.good_pattern,
                "bad": self.bad_pattern, "budget_frac": self.budget_frac}


class ReshardStalledRule(AlertRule):
    """An online reshard has been in flight longer than the
    `reshard_stall_s` knob (server/reshard.py publishes
    `reshard.<label>.in_flight_age_us`; a completed or abandoned op
    resets it to 0, clearing the alert). The detail reads like a page:
    "reshard of [k1,k2) frozen · donor r1 state=probation" — composed
    from the live controller through the hub registry, so the incident
    names the range and the donor engine's health, not a bare gauge.
    Fires immediately (hold 0): a stalled handoff is a fact, not a
    rate."""

    kind = "reshard"

    def __init__(self, name: str = "reshard_stalled",
                 pattern: str = "reshard.*.in_flight_age_us", **kw):
        kw.setdefault("hold_s", 0.0)
        super().__init__(name, **kw)
        self.pattern = pattern
        self._rx = _pattern_re(pattern)

    def conditions(self, t, view):
        from .knobs import SERVER_KNOBS

        stall_us = float(SERVER_KNOBS.reshard_stall_s) * 1e6
        for series, caps in self._matches(view, self.pattern, self._rx):
            v = view.value(series)
            if v is None:
                continue
            active = v > stall_us
            detail = (f"in flight {v / 1e6:.2f}s "
                      f"(stall after {stall_us / 1e6:g}s)")
            if active and view.hub is not None and caps:
                rc = view.hub.reshard_source(caps[0])
                if rc is not None:
                    live = rc.in_flight_detail()
                    if live:
                        detail = f"{live} · {detail}"
            yield (series, active, round(v / 1e6, 3), detail)

    def describe(self):
        return {**super().describe(), "pattern": self.pattern}


class RecoveryStalledRule(AlertRule):
    """A crash-stop recovery has been in flight longer than the
    `resolver_recovery_stall_s` knob (fault/recovery.py's
    RecoveryTracker publishes `recovery.<label>.in_flight_age_us`; a
    completed — even failed — recovery resets it to 0, clearing the
    alert). A stalled recovery is the worst blackout shape: the process
    is up but serving nothing, which no liveness probe distinguishes
    from warm. Fires immediately (hold 0): a wedged restart is a fact,
    not a rate."""

    kind = "recovery"

    def __init__(self, name: str = "recovery_stalled",
                 pattern: str = "recovery.*.in_flight_age_us", **kw):
        kw.setdefault("hold_s", 0.0)
        super().__init__(name, **kw)
        self.pattern = pattern
        self._rx = _pattern_re(pattern)

    def conditions(self, t, view):
        from .knobs import SERVER_KNOBS

        stall_us = float(SERVER_KNOBS.resolver_recovery_stall_s) * 1e6
        for series, caps in self._matches(view, self.pattern, self._rx):
            v = view.value(series)
            if v is None:
                continue
            active = v > stall_us
            detail = (f"in flight {v / 1e6:.2f}s "
                      f"(stall after {stall_us / 1e6:g}s)")
            if active and view.hub is not None and caps:
                rt = view.hub.recovery_source(caps[0])
                if rt is not None:
                    live = rt.in_flight_detail()
                    if live:
                        detail = f"{live} · {detail}"
            yield (series, active, round(v / 1e6, 3), detail)

    def describe(self):
        return {**super().describe(), "pattern": self.pattern}


class _AlertState:
    """Lifecycle state of one (rule, series) pair."""

    __slots__ = ("state", "since", "clear_since", "value", "detail",
                 "t_firing", "fired_count")

    def __init__(self) -> None:
        watchdog_allocations[0] += 1
        self.state = OK
        self.since = 0.0
        self.clear_since: Optional[float] = None
        self.value: float = 0.0
        self.detail = ""
        self.t_firing: Optional[float] = None
        self.fired_count = 0


class Incident:
    """A group of alerts firing in one contiguous interval, correlated
    after the fact against injected fault windows, health transitions
    and the trace root cause (real/nemesis.py hands those in)."""

    def __init__(self, ident: int, t0: float):
        watchdog_allocations[0] += 1
        self.id = ident
        self.t0 = t0
        self.t1: Optional[float] = None
        #: alert key -> {name, series, value, detail} at firing time
        self.alerts: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.windows: List[Dict[str, Any]] = []
        self.health: List[Dict[str, Any]] = []
        self.root_cause: Optional[Dict[str, Any]] = None
        self.explained = False
        self.explanation: Optional[str] = None
        #: already sunk to the black-box journal (correlate() may run
        #: more than once; the append-only journal must not duplicate)
        self.journaled = False

    def summary(self) -> str:
        parts = [" ".join(f"{a['name']} firing"
                          for a in list(self.alerts.values())[:1])]
        extra = len(self.alerts) - 1
        if extra > 0:
            parts[0] += f" (+{extra} more)"
        if self.windows:
            kinds = sorted({w.get("kind", "?") for w in self.windows})
            parts.append("overlaps " + "+".join(kinds) + " window")
        if self.root_cause:
            parts.append(f"dominant={self.root_cause.get('dominant_segment')}")
        if self.health:
            # the WORST state the incident spanned explains it better
            # than whichever transition happened to come last (an arc
            # usually ends back at healthy)
            sev = {"healthy": 0, "suspect": 1, "failed": 2,
                   "probation": 3, "quarantined": 4}
            h = max(self.health, key=lambda h: sev.get(h["state"], -1))
            parts.append(f"resolver {h['label']} state={h['state']}")
        return " · ".join(parts)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "t0": round(self.t0, 4),
            "t1": round(self.t1, 4) if self.t1 is not None else None,
            "alerts": list(self.alerts.values()),
            "windows": [{"kind": w.get("kind"),
                         "t0": round(float(w.get("t0", 0)), 4),
                         "t1": round(float(w.get("t1", 0)), 4)}
                        for w in self.windows],
            "health": list(self.health),
            "root_cause": self.root_cause,
            "explained": self.explained,
            "explanation": self.explanation,
            "summary": self.summary(),
        }


def default_rules() -> List[AlertRule]:
    """The knob-driven default ruleset (docs/observability.md rule
    catalog). Budgets come from the watchdog_* knobs so one `--knob`
    override retunes a live campaign; hold/clear default per-rule to the
    global knobs. Health-state and discipline rules fire immediately
    (hold 0): a blocking sync or a failed engine is a fact, not a rate."""
    from .knobs import SERVER_KNOBS

    k = SERVER_KNOBS
    return [
        # -- burn-rate pairs (SLO spend) ---------------------------------
        BurnRateRule("slo_p99_burn", "sli.*.good", "sli.*.bad",
                     budget_frac=float(k.watchdog_slo_bad_frac)),
        BurnRateRule("abort_frac_burn",
                     "engine.*.verdicts.committed",
                     "engine.*.verdicts.conflicts",
                     budget_frac=float(k.watchdog_abort_budget_frac)),
        BurnRateRule("tenant_throttle_burn",
                     "admission.*.admitted", "admission.*.rejected",
                     budget_frac=float(k.watchdog_throttle_budget_frac)),
        # conflict-scheduler predictor health (pipeline/scheduler.py):
        # probes are predicted-doomed transactions dispatched anyway —
        # one that COMMITS is a mispredict. A mispredict share above the
        # budget means the predictor has gone stale and pre-abort is
        # refusing transactions that would have won.
        BurnRateRule("sched_mispredict",
                     "sched.*.probe_ok", "sched.*.mispredicts",
                     budget_frac=float(k.resolver_sched_mispredict_frac)),
        # -- discipline thresholds (must-be-zero invariants, live) -------
        ThresholdRule("blocking_syncs", "loop.*.blocking_syncs", 0, ">",
                      hold_s=0.0),
        ThresholdRule("steady_state_compiles", "perf.*.compiles_steady",
                      0, ">", hold_s=0.0),
        ThresholdRule("state_memory_pressure",
                      "resolver.*.state_memory_pressure", 0, ">"),
        # state index >= 1 == suspect or worse (telemetry.HEALTH_STATE_INDEX)
        ThresholdRule("engine_unhealthy", "resolver.*.state", 1, ">=",
                      hold_s=0.0),
        # -- anomaly bands ------------------------------------------------
        AnomalyRule("heat_concentration_shift",
                    "heat.*.concentration_x1000"),
        # -- online resharding (server/reshard.py) ------------------------
        ReshardStalledRule("reshard_stalled"),
        # blackout burn: an executed reshard whose freeze -> cutover
        # interval exceeded reshard_blackout_budget_ms — a fact the
        # moment the counter moves, like the discipline rules
        ThresholdRule("reshard_blackout",
                      "reshard.*.blackout_over_budget", 0, ">",
                      hold_s=0.0),
        # -- crash-stop recovery (fault/recovery.py) ----------------------
        RecoveryStalledRule("recovery_stalled"),
        # -- staleness/absence -------------------------------------------
        StalenessRule("commit_flow_stalled", "sli.*.total",
                      max_age_s=float(k.watchdog_staleness_s)),
    ]


class Watchdog:
    """The rule engine. One per process, attached to the telemetry hub
    (`hub().attach_watchdog(...)` — or automatically at hub construction
    when `watchdog_enabled` is on); `evaluate()` runs on every
    `TelemetryHub.sync()`."""

    def __init__(self, rules: Optional[Sequence[AlertRule]] = None,
                 now_fn=None):
        from .knobs import SERVER_KNOBS

        self.rules: List[AlertRule] = list(
            rules if rules is not None else default_rules())
        self.now_fn = now_fn or span_now
        self._states: Dict[Tuple[str, str], _AlertState] = {}
        self._rule_by_name = {r.name: r for r in self.rules}
        #: bounded transition ring: every pending/firing/resolved edge
        self.ring: deque = deque(
            maxlen=int(SERVER_KNOBS.watchdog_alert_ring))
        self.incidents: List[Incident] = []
        self._open: Optional[Incident] = None
        self._next_incident = 1
        self.evaluations = 0
        #: resolver health transitions observed through the hub series
        #: (resolver.<label>.state change history, correlation input)
        self.health_transitions: deque = deque(maxlen=MAX_HEALTH_TRANSITIONS)
        self._health_rx = _pattern_re("resolver.*.state")
        self._health_last: Dict[str, int] = {}

    def _edge(self, entry: Dict[str, Any]) -> None:
        """One alert lifecycle edge: into the bounded ring AND, when a
        black-box journal is installed, onto disk — post-hoc forensics
        (`cli explain`) joins these against the batch/fault timeline."""
        self.ring.append(entry)
        if blackbox.enabled():
            blackbox.record_alert(entry["alert"], entry["series"],
                                  entry["state"], entry["value"],
                                  entry["detail"])

    # -- evaluation ----------------------------------------------------------
    def _track_health(self, t: float, view: _SeriesView) -> None:
        from .telemetry import HEALTH_STATE_INDEX

        names = {v: n for n, v in HEALTH_STATE_INDEX.items()}
        for series in view.metrics:
            m = self._health_rx.match(series)
            if m is None:
                continue
            v = int(view.value(series) or 0)
            if self._health_last.get(series) == v:
                continue
            self._health_last[series] = v
            self.health_transitions.append({
                "t": round(t, 4), "label": m.group(1),
                "state": names.get(v, str(v))})

    def _step(self, t: float, rule: AlertRule, series: str, active: bool,
              value: float, detail: str) -> None:
        key = (rule.name, series)
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _AlertState()
            st.since = t
        st.value, st.detail = value, detail
        if st.state == OK:
            if active:
                st.state, st.since = PENDING, t
                self._edge({"t": round(t, 4), "alert": rule.name,
                                  "series": series, "state": "pending",
                                  "value": value, "detail": detail})
                # hold 0 = fire on the same tick the condition appears
                if t - st.since >= rule.resolved_hold_s():
                    self._fire(t, rule, series, st)
        elif st.state == PENDING:
            if not active:
                st.state = OK
                self._edge({"t": round(t, 4), "alert": rule.name,
                                  "series": series, "state": "cleared",
                                  "value": value, "detail": detail})
            elif t - st.since >= rule.resolved_hold_s():
                self._fire(t, rule, series, st)
        elif st.state == FIRING:
            if active:
                st.clear_since = None
                if self._open is not None:
                    a = self._open.alerts.get((rule.name, series))
                    if a is not None:
                        a["value"] = value
            else:
                if st.clear_since is None:
                    st.clear_since = t
                if t - st.clear_since >= rule.resolved_clear_s():
                    st.state, st.clear_since = OK, None
                    self._edge({"t": round(t, 4), "alert": rule.name,
                                      "series": series, "state": "resolved",
                                      "value": value, "detail": detail})

    def _fire(self, t: float, rule: AlertRule, series: str,
              st: _AlertState) -> None:
        st.state, st.t_firing, st.clear_since = FIRING, t, None
        st.fired_count += 1
        self._edge({"t": round(t, 4), "alert": rule.name,
                          "series": series, "state": "firing",
                          "value": st.value, "detail": st.detail})
        if self._open is None:
            self._open = Incident(self._next_incident, t)
            self._next_incident += 1
            self.incidents.append(self._open)
            del self.incidents[:-MAX_INCIDENTS]
        self._open.alerts[(rule.name, series)] = {
            "name": rule.name, "kind": rule.kind, "series": series,
            "value": st.value, "detail": st.detail, "t": round(t, 4)}

    def evaluate(self, hub) -> None:
        """One tick: read every rule's series off the hub, step the
        lifecycles, export the alert set as `alerts.*` series, and
        open/close the incident envelope. Called from sync()."""
        t = self.now_fn()
        self.evaluations += 1
        view = _SeriesView(hub.tdmetrics.metrics, hub)
        self._track_health(t, view)
        for rule in self.rules:
            for series, active, value, detail in rule.conditions(t, view):
                self._step(t, rule, series, active, value, detail)
        # incident envelope: closes when the firing set drains
        if self._open is not None and not any(
                st.state == FIRING for st in self._states.values()):
            self._open.t1 = t
            self._open = None
        # ALERTS-style exposition (`fdbtpu_alerts` family): one state
        # gauge per tracked alert + the global firing count
        td = hub.tdmetrics
        n_firing = 0
        for (rule_name, series), st in self._states.items():
            if st.state == FIRING:
                n_firing += 1
            td.int64(f"alerts.{rule_name}.{series}.state").set(st.state)
        td.int64("alerts.firing").set(n_firing)

    # -- read model ----------------------------------------------------------
    def firing(self) -> List[Dict[str, Any]]:
        return [{"name": rule_name, "series": series, "value": st.value,
                 "detail": st.detail, "since": round(st.t_firing or 0, 4),
                 "kind": getattr(self._rule_by_name.get(rule_name), "kind",
                                 "rule")}
                for (rule_name, series), st in self._states.items()
                if st.state == FIRING]

    def burn_firing(self) -> bool:
        """Any burn-rate alert currently firing — the signal the
        ratekeeper consumes as a rate clamp alongside resolver_degraded
        (server/ratekeeper.py), and the hook an online resharding
        controller will drive from (ROADMAP item 4)."""
        return any(a["kind"] == "burn" for a in self.firing())

    def alerts_snapshot(self) -> List[Dict[str, Any]]:
        """Every tracked (rule, series) pair's current lifecycle state."""
        return [{"name": rule_name, "series": series,
                 "state": STATE_NAMES.get(st.state, str(st.state)),
                 "value": st.value, "detail": st.detail,
                 "fired_count": st.fired_count}
                for (rule_name, series), st in self._states.items()]

    def timeline(self) -> List[Tuple]:
        """The deterministic replay identity: every ring transition plus
        per-incident (alert names, window kinds, root cause) — two runs
        of the same seed must produce equal timelines."""
        out: List[Tuple] = [
            (round(e["t"], 3), e["alert"], e["series"], e["state"])
            for e in self.ring]
        for inc in self.incidents:
            out.append((
                "incident", inc.id,
                tuple(sorted(a["name"] for a in inc.alerts.values())),
                tuple(sorted({w.get("kind") for w in inc.windows})),
                (inc.root_cause or {}).get("dominant_segment"),
                inc.explained))
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The engine_health / status-doc fragment (server/resolver.py
        attaches it; `tools/cli.py alerts|incidents` renders it)."""
        firing = self.firing()
        return {
            "evaluations": self.evaluations,
            "rules": [r.describe() for r in self.rules],
            "firing": firing,
            "burn_firing": any(a["kind"] == "burn" for a in firing),
            "alerts": self.alerts_snapshot(),
            "ring": list(self.ring)[-32:],
            "incidents": [i.as_dict() for i in self.incidents],
            "health_transitions": list(self.health_transitions)[-16:],
        }

    # -- correlation ---------------------------------------------------------
    def correlate(self, windows: Sequence[Dict[str, Any]],
                  root_cause: Optional[Dict[str, Any]] = None,
                  breached_slo: Optional[str] = None,
                  margin_s: float = 0.25) -> List[Incident]:
        """Machine-correlate every incident against injected fault
        windows ({kind, t0, t1} dicts — the nemesis' own records), the
        observed health transitions, and the campaign's trace root cause.
        An incident is EXPLAINED when it overlaps an injected window, or
        when `breached_slo` names a breach one of its burn alerts
        measures (the incident then IS the breach's alert, not noise).
        Anything else is an unexplained incident — `assert_slos` fails
        the campaign on it, alert name first."""
        from .knobs import SERVER_KNOBS

        end_default = self.now_fn()
        burn_look_back = float(SERVER_KNOBS.watchdog_burn_slow_s)
        for inc in self.incidents:
            lo, hi = inc.t0 - margin_s, (inc.t1 or end_default) + margin_s
            if any(a.get("kind") == "burn" for a in inc.alerts.values()):
                # a burn alert's firing evidence is its trailing slow
                # window: bad events inside [t0 - slow_s, t0] lit it, so
                # a fault window anywhere in that span explains the
                # incident even when the alert fired after the window
                # closed (burn trails the cause by construction)
                lo -= burn_look_back
            inc.windows = [w for w in windows
                           if float(w.get("t0", 0)) <= hi
                           and float(w.get("t1", 0)) >= lo]
            inc.health = [h for h in self.health_transitions
                          if lo <= h["t"] <= hi]
            inc.root_cause = root_cause
            if inc.windows:
                inc.explained = True
                kinds = sorted({w.get("kind", "?") for w in inc.windows})
                inc.explanation = "overlaps injected " + "+".join(kinds)
            elif breached_slo is not None and any(
                    a["kind"] == "burn" for a in inc.alerts.values()):
                inc.explained = True
                inc.explanation = f"names the {breached_slo} breach"
        if blackbox.enabled():
            # correlated incidents onto the black-box journal, ONCE per
            # incident even when correlate() runs again: the post-hoc
            # explain joins them against batch/fault timelines
            for inc in self.incidents:
                if not inc.journaled:
                    inc.journaled = True
                    blackbox.record_incident(inc.as_dict())
        return self.incidents


# -- SLI recording ------------------------------------------------------------

def record_commit_sli(hub, latency_ms: float, budget_ms: float,
                      label: str = "commit") -> None:
    """One served commit ack into the p99-vs-budget SLI counters the
    `slo_p99_burn` rule consumes: good = acked within the budget, bad =
    acked late. Transport failures and throttles are NOT SLI events —
    they burn the throttle/abort budgets, not the latency one. Callers
    gate on `hub.watchdog is not None` so the disabled path records
    nothing."""
    td = hub.tdmetrics
    td.int64(f"sli.{label}.total").increment()
    if latency_ms <= budget_ms:
        td.int64(f"sli.{label}.good").increment()
    else:
        td.int64(f"sli.{label}.bad").increment()


def watchdog_from_knobs() -> Optional[Watchdog]:
    """A default-ruleset watchdog when `watchdog_enabled` is on, else
    None (the disabled path constructs nothing)."""
    from .knobs import SERVER_KNOBS

    if not bool(getattr(SERVER_KNOBS, "watchdog_enabled", False)):
        return None
    return Watchdog(default_rules())
