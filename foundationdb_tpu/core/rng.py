"""Deterministic random source.

Analog of the reference's seeded generator (flow/DeterministicRandom.h:1-119):
one seeded stream drives every randomized decision in simulation so a failing
run replays exactly from its seed. A separate nondeterministic stream exists
for things that must not perturb simulation (IDs in trace logs, etc.)
(reference: g_random vs g_nondeterministic_random, flow/flow.cpp).
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence, TypeVar

T = TypeVar("T")


class DeterministicRandom:
    def __init__(self, seed: int):
        self._seed = seed
        self._r = random.Random(seed)

    @property
    def seed(self) -> int:
        return self._seed

    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) — half-open like the reference randomInt."""
        assert hi > lo
        return self._r.randrange(lo, hi)

    def random_int64(self, lo: int, hi: int) -> int:
        return self._r.randrange(lo, hi)

    def coinflip(self) -> bool:
        return self._r.random() < 0.5

    def random_unique_id(self) -> int:
        return self._r.getrandbits(64)

    def random_alpha_numeric(self, length: int) -> str:
        alphabet = "abcdefghijklmnopqrstuvwxyz0123456789"
        return "".join(self._r.choice(alphabet) for _ in range(length))

    def random_bytes(self, length: int) -> bytes:
        return self._r.getrandbits(8 * length).to_bytes(length, "big") if length else b""

    def random_choice(self, seq: Sequence[T]) -> T:
        return seq[self.random_int(0, len(seq))]

    def shuffle(self, lst: List[T]) -> None:
        self._r.shuffle(lst)

    def fork(self) -> "DeterministicRandom":
        """Derive an independent deterministic substream."""
        return DeterministicRandom(self._r.getrandbits(63))


# Global streams, installed by the simulator or real-world bootstrap
# (reference: g_random / g_nondeterministic_random).
g_random: DeterministicRandom = DeterministicRandom(0)
g_nondeterministic_random: DeterministicRandom = DeterministicRandom(
    random.SystemRandom().getrandbits(63)
)


def set_global_random(rng: DeterministicRandom) -> None:
    global g_random
    g_random = rng
