"""KeyRangeMap: a coalescing range-keyed map.

Re-design of fdbclient/KeyRangeMap.h (+ flow/IndexedSet.h's role as its
container): the WHOLE keyspace is covered by contiguous half-open ranges,
each carrying a value; `insert` overwrites a span (splitting boundary
ranges), point and range lookups are bisects, and adjacent ranges with
equal values COALESCE — the property the reference leans on for the
keyServers/serverKeys maps, the client's location cache, and conflict-
range bookkeeping.

Representation: ascending boundary keys with `vals[i]` covering
[bounds[i], bounds[i+1]) and vals[-1] covering [bounds[-1], +inf)."""
from __future__ import annotations

import bisect
from typing import Any, Iterator, List, Optional, Tuple


class KeyRangeMap:
    def __init__(self, default: Any = None):
        self._bounds: List[bytes] = [b""]
        self._vals: List[Any] = [default]

    # -- lookups --------------------------------------------------------------
    def _idx(self, key: bytes) -> int:
        return bisect.bisect_right(self._bounds, key) - 1

    def __getitem__(self, key: bytes) -> Any:
        return self._vals[self._idx(key)]

    def range_containing(self, key: bytes) -> Tuple[bytes, Optional[bytes], Any]:
        """(begin, end, value) of the range holding `key`; end is None for
        the final (unbounded) range."""
        i = self._idx(key)
        end = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
        return self._bounds[i], end, self._vals[i]

    def intersecting(self, begin: bytes, end: bytes
                     ) -> Iterator[Tuple[bytes, Optional[bytes], Any]]:
        """Every (clipped_begin, clipped_end, value) covering [begin, end)."""
        if begin >= end:
            return
        i = self._idx(begin)
        while i < len(self._bounds):
            b = self._bounds[i]
            if b >= end:
                return
            e = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
            cb = max(b, begin)
            ce = end if e is None else min(e, end)
            yield cb, ce, self._vals[i]
            i += 1

    def ranges(self) -> List[Tuple[bytes, Optional[bytes], Any]]:
        out = []
        for i, b in enumerate(self._bounds):
            e = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
            out.append((b, e, self._vals[i]))
        return out

    # -- mutation -------------------------------------------------------------
    def insert(self, begin: bytes, end: Optional[bytes], value: Any) -> None:
        """Set [begin, end) (end None = to +inf) to `value`, splitting the
        boundary ranges and coalescing equal neighbors."""
        if end is not None and begin >= end:
            return
        i = self._idx(begin)
        # value that resumes after `end`
        after_val = self._vals[self._idx(end)] if end is not None else None
        # drop boundaries strictly inside (begin, end)
        if end is None:
            hi = len(self._bounds)
        else:
            hi = bisect.bisect_left(self._bounds, end)
        lo = i + 1
        del self._bounds[lo:hi]
        del self._vals[lo:hi]
        # split at begin
        if self._bounds[i] == begin:
            self._vals[i] = value
        else:
            self._bounds.insert(i + 1, begin)
            self._vals.insert(i + 1, value)
            i += 1
        # split at end (restore the suffix value)
        if end is not None:
            nxt = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
            if nxt != end:
                self._bounds.insert(i + 1, end)
                self._vals.insert(i + 1, after_val)
        self._coalesce_around(i)

    def _coalesce_around(self, i: int) -> None:
        """Merge range i with equal-valued neighbors (KeyRangeMap's
        coalesce): the map stays minimal."""
        # right neighbor first (indices shift left on delete)
        if i + 1 < len(self._bounds) and self._vals[i + 1] == self._vals[i]:
            del self._bounds[i + 1]
            del self._vals[i + 1]
        if i > 0 and self._vals[i - 1] == self._vals[i]:
            del self._bounds[i]
            del self._vals[i]

    def clear(self, default: Any = None) -> None:
        self._bounds = [b""]
        self._vals = [default]

    def __len__(self) -> int:
        return len(self._bounds)
