"""On-disk serialization for durable state.

Stands in for the reference's byte-stable serializer (flow/serialize.h).
The sim's durability contract only needs self-consistent bytes with
checksums above them (disk_queue.py frames), so the stdlib pickle at a
pinned protocol is sufficient and deterministic for identical inputs; a
flat binary format becomes necessary only when real processes exchange
files across versions.
"""
from __future__ import annotations

import pickle
import struct

PROTOCOL = 4


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=PROTOCOL)


def loads(raw: bytes):
    return pickle.loads(raw)


# ---------------------------------------------------------------------------
# Columnar conflict-range wire blocks (the resolver's hot input format).
#
# The reference resolver receives transactions as serialized
# ResolveTransactionBatchRequest bytes (fdbserver/ResolverInterface.h) and
# walks them in C++. The TPU-native analog keeps conflict ranges in a compact
# little-endian block per transaction so the resolver host path can turn a
# whole batch into device arrays with one native pass (native/fastpack.c)
# instead of per-range Python objects:
#
#   [u32 n_read][u32 n_write]
#   then n_read read ranges followed by n_write write ranges, each:
#     [u32 hdr]  hdr = begin_len | kind << 30
#     [begin_len bytes]                         kind 0: POINT [k, k+'\x00')
#     [u32 end_len][end_len bytes]  (kind 1 only)     1: real range [b, e)
#                                                     2: empty read [k, k)
# ---------------------------------------------------------------------------

_KIND_POINT = 0
_KIND_RANGE = 1
_KIND_EMPTY = 2
_LEN_MASK = (1 << 30) - 1


def conflict_wire_ex(read_ranges, write_ranges):
    """Encode a transaction's conflict ranges as one wire block. Encoding is
    client-side work (the client serializes its commit request once); the
    resolver's native parser consumes the concatenated blocks. Returns
    (block, all_point, max_key_len) — the classification falls out of the
    encode for free and lets the resolver skip whole-batch encodes that the
    fast path would reject anyway."""
    from .types import is_point_range

    parts = [struct.pack("<II", len(read_ranges), len(write_ranges))]
    all_point = True
    max_len = 0
    for rng in (*read_ranges, *write_ranges):
        b, e = rng.begin, rng.end
        if len(b) > max_len:
            max_len = len(b)
        if is_point_range(b, e):
            parts.append(struct.pack("<I", len(b) | (_KIND_POINT << 30)))
            parts.append(b)
        elif e <= b:
            parts.append(struct.pack("<I", len(b) | (_KIND_EMPTY << 30)))
            parts.append(b)
            all_point = False
        else:
            parts.append(struct.pack("<I", len(b) | (_KIND_RANGE << 30)))
            parts.append(b)
            parts.append(struct.pack("<I", len(e)))
            parts.append(e)
            all_point = False
            if len(e) > max_len:
                max_len = len(e)
    return b"".join(parts), all_point, max_len


def conflict_wire(read_ranges, write_ranges) -> bytes:
    return conflict_wire_ex(read_ranges, write_ranges)[0]


def conflict_unwire(block: bytes):
    """Decode a conflict wire block -> (read_ranges, write_ranges) as
    (begin, end) byte pairs. The inverse of conflict_wire, for tests and the
    pure-Python fallback."""
    nr, nw = struct.unpack_from("<II", block, 0)
    off = 8
    out = []
    for _ in range(nr + nw):
        (hdr,) = struct.unpack_from("<I", block, off)
        off += 4
        blen, kind = hdr & _LEN_MASK, hdr >> 30
        b = block[off : off + blen]
        off += blen
        if kind == _KIND_POINT:
            out.append((b, b + b"\x00"))
        elif kind == _KIND_EMPTY:
            out.append((b, b))  # [k, k)
        else:
            (elen,) = struct.unpack_from("<I", block, off)
            off += 4
            out.append((b, block[off : off + elen]))
            off += elen
    return out[:nr], out[nr:]
