"""On-disk serialization for durable state: a tagged, versioned flat
binary format (the analog of flow/serialize.h's byte-stable versioned
serializers).

Everything that touches a disk — DiskQueue payloads, tlog/storage side
state and metadata, coordination registers — goes through dumps()/loads()
here. Unlike pickle, the bytes do not depend on Python class layout:

  * scalars/containers use fixed type tags + varints;
  * dataclasses are encoded as NAMED records listing (field name, value)
    pairs against a registry (register_record) — a vN payload read by a
    vN+1 binary simply ignores fields it dropped and defaults fields it
    added, which is what makes restart-across-upgrade safe;
  * enums encode as (registered name, integer value).

The header carries a magic byte + format version so a future
incompatible format can bump it and keep a reader for the old one.
"""
from __future__ import annotations

import struct
from enum import Enum
from typing import Any, Callable, Dict, Tuple, Type

MAGIC = 0xF7
FORMAT_VERSION = 1

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3
_T_BYTES = 4
_T_STR = 5
_T_LIST = 6
_T_TUPLE = 7
_T_DICT = 8
_T_SET = 9
_T_RECORD = 10
_T_ENUM = 11
_T_FLOAT = 12
_T_FROZENSET = 13

_RECORDS: Dict[str, Type] = {}
_RECORD_NAMES: Dict[Type, str] = {}
_ENUMS: Dict[str, Type] = {}
_ENUM_NAMES: Dict[Type, str] = {}
#: non-dataclass types with explicit (to_state, from_state) codecs, encoded
#: as single-field records — e.g. KeyShardMap, which is fully described by
#: its split keys but derives its fields in __init__
_ADAPTERS: Dict[Type, Tuple[str, Callable]] = {}
_ADAPTER_DECODERS: Dict[str, Callable] = {}


def register_adapter(cls: Type, name: str, to_state: Callable, from_state: Callable) -> Type:
    """Register a custom codec: `to_state(obj)` must return a wire-encodable
    value; `from_state(state)` reconstructs the object."""
    _ADAPTERS[cls] = (name, to_state)
    _ADAPTER_DECODERS[name] = from_state
    return cls

#: modules whose import registers every record reachable from disk state;
#: imported lazily on the first unknown record (a restore may run before
#: the defining module was imported)
_LAZY_REGISTRARS = (
    "foundationdb_tpu.core.types",
    # TraceContext — the propagated distributed-tracing context that rides
    # RPC frames under the "tc" key (core/trace.py; real/transport.py)
    "foundationdb_tpu.core.trace",
    "foundationdb_tpu.server.coordination",
    "foundationdb_tpu.server.coordinated_state",
    "foundationdb_tpu.server.log_system",
)


def register_record(cls: Type, name: str = "") -> Type:
    """Register a dataclass for named-record encoding (call at module
    import from the defining module). Field names are the schema."""
    n = name or cls.__name__
    _RECORDS[n] = cls
    _RECORD_NAMES[cls] = n
    return cls


def register_enum(cls: Type, name: str = "") -> Type:
    n = name or cls.__name__
    _ENUMS[n] = cls
    _ENUM_NAMES[cls] = n
    return cls


def _write_varint(out: bytearray, v: int) -> None:
    # zigzag + LEB128; arbitrary precision (a fixed-width shift would
    # corrupt ints below -2^63)
    u = ((-v) << 1) - 1 if v < 0 else v << 1
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(raw: bytes, off: int) -> Tuple[int, int]:
    u = 0
    shift = 0
    while True:
        b = raw[off]
        off += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (u >> 1) ^ -(u & 1), off


def _encode(out: bytearray, obj: Any) -> None:
    if obj is None:
        out.append(_T_NONE)
    elif obj is True:
        out.append(_T_TRUE)
    elif obj is False:
        out.append(_T_FALSE)
    elif isinstance(obj, Enum):
        cls = type(obj)
        name = _ENUM_NAMES.get(cls)
        if name is None:
            raise TypeError(f"unregistered enum {cls.__name__}")
        out.append(_T_ENUM)
        _encode_str(out, name)
        _write_varint(out, int(obj.value))
    elif isinstance(obj, int):
        out.append(_T_INT)
        _write_varint(out, obj)
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out += struct.pack("<d", obj)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(_T_BYTES)
        _write_varint(out, len(obj))
        out += obj
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(_T_STR)
        _write_varint(out, len(raw))
        out += raw
    elif isinstance(obj, list):
        out.append(_T_LIST)
        _write_varint(out, len(obj))
        for x in obj:
            _encode(out, x)
    elif isinstance(obj, tuple):
        out.append(_T_TUPLE)
        _write_varint(out, len(obj))
        for x in obj:
            _encode(out, x)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        _write_varint(out, len(obj))
        for k, v in obj.items():
            _encode(out, k)
            _encode(out, v)
    elif isinstance(obj, frozenset):
        out.append(_T_FROZENSET)
        _write_varint(out, len(obj))
        for x in sorted(obj, key=repr):
            _encode(out, x)
    elif isinstance(obj, set):
        out.append(_T_SET)
        _write_varint(out, len(obj))
        for x in sorted(obj, key=repr):
            _encode(out, x)
    else:
        adapter = _ADAPTERS.get(type(obj))
        if adapter is not None:
            name, to_state = adapter
            out.append(_T_RECORD)
            _encode_str(out, name)
            _write_varint(out, 1)
            _encode_str(out, "state")
            _encode(out, to_state(obj))
            return
        name = _RECORD_NAMES.get(type(obj))
        if name is None:
            raise TypeError(f"wire cannot encode {type(obj).__name__}: "
                            "register_record it or use plain containers")
        import dataclasses

        fields = dataclasses.fields(obj)
        out.append(_T_RECORD)
        _encode_str(out, name)
        _write_varint(out, len(fields))
        for f in fields:
            _encode_str(out, f.name)
            _encode(out, getattr(obj, f.name))


def _encode_str(out: bytearray, s: str) -> None:
    raw = s.encode("utf-8")
    _write_varint(out, len(raw))
    out += raw


def _decode_str(raw: bytes, off: int) -> Tuple[str, int]:
    n, off = _read_varint(raw, off)
    return raw[off:off + n].decode("utf-8"), off + n


def _resolve_record(name: str) -> Type:
    cls = _RECORDS.get(name)
    if cls is None:
        import importlib

        for mod in _LAZY_REGISTRARS:
            importlib.import_module(mod)
        cls = _RECORDS.get(name)
    if cls is None:
        raise ValueError(f"unknown wire record type {name!r}")
    return cls


def _decode(raw: bytes, off: int) -> Tuple[Any, int]:
    tag = raw[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_INT:
        return _read_varint(raw, off)
    if tag == _T_FLOAT:
        return struct.unpack_from("<d", raw, off)[0], off + 8
    if tag == _T_BYTES:
        n, off = _read_varint(raw, off)
        return bytes(raw[off:off + n]), off + n
    if tag == _T_STR:
        n, off = _read_varint(raw, off)
        return raw[off:off + n].decode("utf-8"), off + n
    if tag in (_T_LIST, _T_TUPLE, _T_SET, _T_FROZENSET):
        n, off = _read_varint(raw, off)
        items = []
        for _ in range(n):
            x, off = _decode(raw, off)
            items.append(x)
        if tag == _T_LIST:
            return items, off
        if tag == _T_TUPLE:
            return tuple(items), off
        if tag == _T_SET:
            return set(items), off
        return frozenset(items), off
    if tag == _T_DICT:
        n, off = _read_varint(raw, off)
        d = {}
        for _ in range(n):
            k, off = _decode(raw, off)
            v, off = _decode(raw, off)
            d[k] = v
        return d, off
    if tag == _T_ENUM:
        name, off = _decode_str(raw, off)
        v, off = _read_varint(raw, off)
        cls = _ENUMS.get(name)
        if cls is None:
            import importlib

            for mod in _LAZY_REGISTRARS:
                importlib.import_module(mod)
            cls = _ENUMS.get(name)
        if cls is None:
            raise ValueError(f"unknown wire enum {name!r}")
        return cls(v), off
    if tag == _T_RECORD:
        name, off = _decode_str(raw, off)
        nf, off = _read_varint(raw, off)
        got: Dict[str, Any] = {}
        for _ in range(nf):
            fname, off = _decode_str(raw, off)
            val, off = _decode(raw, off)
            got[fname] = val
        dec = _ADAPTER_DECODERS.get(name)
        if dec is None and name not in _RECORDS:
            import importlib

            for mod in _LAZY_REGISTRARS:
                importlib.import_module(mod)
            dec = _ADAPTER_DECODERS.get(name)
        if dec is not None:
            return dec(got["state"]), off
        cls = _resolve_record(name)
        import dataclasses

        known = {f.name for f in dataclasses.fields(cls)}
        # tolerant schema evolution: drop fields the reader no longer has;
        # fields the reader added (with defaults) stay at their defaults
        return cls(**{k: v for k, v in got.items() if k in known}), off
    raise ValueError(f"bad wire tag {tag} at {off - 1}")


def dumps(obj) -> bytes:
    out = bytearray([MAGIC, FORMAT_VERSION])
    _encode(out, obj)
    return bytes(out)


def loads(raw: bytes):
    if len(raw) < 2 or raw[0] != MAGIC:
        raise ValueError("not a wire payload (bad magic)")
    if raw[1] != FORMAT_VERSION:
        raise ValueError(f"unsupported wire format version {raw[1]}")
    obj, _off = _decode(raw, 2)
    return obj


# ---------------------------------------------------------------------------
# Columnar conflict-range wire blocks (the resolver's hot input format).
#
# The reference resolver receives transactions as serialized
# ResolveTransactionBatchRequest bytes (fdbserver/ResolverInterface.h) and
# walks them in C++. The TPU-native analog keeps conflict ranges in a compact
# little-endian block per transaction so the resolver host path can turn a
# whole batch into device arrays with one native pass (native/fastpack.c)
# instead of per-range Python objects:
#
#   [u32 n_read][u32 n_write]
#   then n_read read ranges followed by n_write write ranges, each:
#     [u32 hdr]  hdr = begin_len | kind << 30
#     [begin_len bytes]                         kind 0: POINT [k, k+'\x00')
#     [u32 end_len][end_len bytes]  (kind 1 only)     1: real range [b, e)
#                                                     2: empty read [k, k)
# ---------------------------------------------------------------------------

_KIND_POINT = 0
_KIND_RANGE = 1
_KIND_EMPTY = 2
_LEN_MASK = (1 << 30) - 1


def conflict_wire_ex(read_ranges, write_ranges):
    """Encode a transaction's conflict ranges as one wire block. Encoding is
    client-side work (the client serializes its commit request once); the
    resolver's native parser consumes the concatenated blocks. Returns
    (block, all_point, max_key_len) — the classification falls out of the
    encode for free and lets the resolver skip whole-batch encodes that the
    fast path would reject anyway."""
    from .types import is_point_range

    parts = [struct.pack("<II", len(read_ranges), len(write_ranges))]
    all_point = True
    max_len = 0
    for rng in (*read_ranges, *write_ranges):
        b, e = rng.begin, rng.end
        if len(b) > max_len:
            max_len = len(b)
        if is_point_range(b, e):
            parts.append(struct.pack("<I", len(b) | (_KIND_POINT << 30)))
            parts.append(b)
        elif e <= b:
            parts.append(struct.pack("<I", len(b) | (_KIND_EMPTY << 30)))
            parts.append(b)
            all_point = False
        else:
            parts.append(struct.pack("<I", len(b) | (_KIND_RANGE << 30)))
            parts.append(b)
            parts.append(struct.pack("<I", len(e)))
            parts.append(e)
            all_point = False
            if len(e) > max_len:
                max_len = len(e)
    return b"".join(parts), all_point, max_len


def conflict_wire(read_ranges, write_ranges) -> bytes:
    return conflict_wire_ex(read_ranges, write_ranges)[0]


def conflict_unwire(block: bytes):
    """Decode a conflict wire block -> (read_ranges, write_ranges) as
    (begin, end) byte pairs. The inverse of conflict_wire, for tests and the
    pure-Python fallback."""
    nr, nw = struct.unpack_from("<II", block, 0)
    off = 8
    out = []
    for _ in range(nr + nw):
        (hdr,) = struct.unpack_from("<I", block, off)
        off += 4
        blen, kind = hdr & _LEN_MASK, hdr >> 30
        b = block[off : off + blen]
        off += blen
        if kind == _KIND_POINT:
            out.append((b, b + b"\x00"))
        elif kind == _KIND_EMPTY:
            out.append((b, b))  # [k, k)
        else:
            (elen,) = struct.unpack_from("<I", block, off)
            off += 4
            out.append((b, block[off : off + elen]))
            off += elen
    return out[:nr], out[nr:]
