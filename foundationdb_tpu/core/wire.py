"""On-disk serialization for durable state.

Stands in for the reference's byte-stable serializer (flow/serialize.h).
The sim's durability contract only needs self-consistent bytes with
checksums above them (disk_queue.py frames), so the stdlib pickle at a
pinned protocol is sufficient and deterministic for identical inputs; a
flat binary format becomes necessary only when real processes exchange
files across versions.
"""
from __future__ import annotations

import pickle

PROTOCOL = 4


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=PROTOCOL)


def loads(raw: bytes):
    return pickle.loads(raw)
