"""Static key-range shard map (jax-free).

The analog of the proxy's `keyResolvers` range map
(MasterProxyServer.actor.cpp:263-316, ProxyCommitData:169), shared by the
proxy's resolver routing, the storage shard map, and the device engines'
host routing. Lives outside ops/ so server roles can import it without
pulling in the JAX compute stack.
"""
from __future__ import annotations

import bisect
from typing import List, Optional, Sequence, Tuple

from . import wire
from .types import Key


class KeyShardMap:
    """Static partition of the keyspace into S contiguous spans.

    Span s = [begins[s], begins[s+1]) with begins[0] = b'' and a virtual
    +inf end for the last span (the analog of the keyResolvers range map,
    ProxyCommitData:169)."""

    def __init__(self, split_keys: Sequence[Key]):
        assert list(split_keys) == sorted(split_keys), "split keys must be sorted"
        assert all(k for k in split_keys), "split keys must be non-empty"
        self.begins: List[Key] = [b""] + list(split_keys)
        self.n_shards = len(self.begins)

    @staticmethod
    def uniform(n_shards: int) -> "KeyShardMap":
        """Evenly split on the first key byte."""
        if n_shards == 1:
            return KeyShardMap([])
        assert n_shards <= 256, "one-byte granularity cannot split past 256 shards"
        splits = [bytes([(256 * i) // n_shards]) for i in range(1, n_shards)]
        return KeyShardMap(splits)

    def span_end(self, s: int) -> Optional[Key]:
        return self.begins[s + 1] if s + 1 < self.n_shards else None

    def shard_of_key(self, key: Key) -> int:
        """Shard owning `key` (span containing it)."""
        return max(bisect.bisect_right(self.begins, key) - 1, 0)

    def shard_of_point_below(self, key: Key) -> int:
        """Shard owning the interval strictly below `key` (for empty reads:
        mirrors VersionIntervalMap.version_strictly_below's max(i,0))."""
        return max(bisect.bisect_left(self.begins, key) - 1, 0)

    def shards_of_range(self, begin: Key, end: Key) -> List[Tuple[int, Key, Key]]:
        """(shard, clipped_begin, clipped_end) for every span intersecting
        the non-empty range [begin, end)."""
        out = []
        lo = max(bisect.bisect_right(self.begins, begin) - 1, 0)
        for s in range(lo, self.n_shards):
            sb = self.begins[s]
            if sb >= end:
                break
            se = self.span_end(s)
            cb = max(begin, sb)
            ce = end if se is None else min(end, se)
            if cb < ce:
                out.append((s, cb, ce))
        return out


# wire codec: a shard map is fully described by its split keys (real-mode
# role interfaces carry it inside ProxyConfig / Initialize* requests)
wire.register_adapter(
    KeyShardMap, "KeyShardMap",
    to_state=lambda m: list(m.begins[1:]),
    from_state=lambda splits: KeyShardMap(splits),
)
