"""Static key-range shard map (jax-free).

The analog of the proxy's `keyResolvers` range map
(MasterProxyServer.actor.cpp:263-316, ProxyCommitData:169), shared by the
proxy's resolver routing, the storage shard map, and the device engines'
host routing. Lives outside ops/ so server roles can import it without
pulling in the JAX compute stack.
"""
from __future__ import annotations

import bisect
from typing import List, Optional, Sequence, Tuple

from . import wire
from .types import Key


def _fmt_key(key: bytes) -> str:
    """Render a boundary key for humans/JSON: printable ASCII as text,
    anything else as 0x-hex (the `tools/cli.py` convention)."""
    try:
        s = key.decode()
        if s.isascii() and s.isprintable():
            return s
    except UnicodeDecodeError:
        pass
    return "0x" + key.hex()


class KeyShardMap:
    """Static partition of the keyspace into S contiguous spans.

    Span s = [begins[s], begins[s+1]) with begins[0] = b'' and a virtual
    +inf end for the last span (the analog of the keyResolvers range map,
    ProxyCommitData:169)."""

    def __init__(self, split_keys: Sequence[Key]):
        assert list(split_keys) == sorted(split_keys), "split keys must be sorted"
        assert all(k for k in split_keys), "split keys must be non-empty"
        self.begins: List[Key] = [b""] + list(split_keys)
        self.n_shards = len(self.begins)

    @staticmethod
    def uniform(n_shards: int) -> "KeyShardMap":
        """Evenly split on the first key byte."""
        if n_shards == 1:
            return KeyShardMap([])
        assert n_shards <= 256, "one-byte granularity cannot split past 256 shards"
        splits = [bytes([(256 * i) // n_shards]) for i in range(1, n_shards)]
        return KeyShardMap(splits)

    @staticmethod
    def from_split_points(splits: Sequence[Key],
                          n_shards: int) -> "KeyShardMap":
        """An n_shards-way map from MEASURED split keys — what the mesh
        engine adopts from KeyRangeHeatAggregator.split_points(). The
        aggregator's proposals are best-effort (an empty or one-hot heat
        histogram can emit duplicate, empty or too-few keys), so this
        sanitizes: sorted, deduplicated, non-empty keys only; anything
        short of the n_shards - 1 boundaries a full map needs falls back
        to the byte-uniform split — a cold engine starts uniform and
        adopts measured splits on the next (re)build, it never crashes on
        a degenerate histogram."""
        clean = sorted({bytes(k) for k in splits if k})
        if len(clean) != max(int(n_shards), 1) - 1:
            return KeyShardMap.uniform(n_shards)
        return KeyShardMap(clean)

    def span_end(self, s: int) -> Optional[Key]:
        return self.begins[s + 1] if s + 1 < self.n_shards else None

    def shard_of_key(self, key: Key) -> int:
        """Shard owning `key` (span containing it)."""
        return max(bisect.bisect_right(self.begins, key) - 1, 0)

    def shard_of_point_below(self, key: Key) -> int:
        """Shard owning the interval strictly below `key` (for empty reads:
        mirrors VersionIntervalMap.version_strictly_below's max(i,0))."""
        return max(bisect.bisect_left(self.begins, key) - 1, 0)

    def shards_of_range(self, begin: Key, end: Key) -> List[Tuple[int, Key, Key]]:
        """(shard, clipped_begin, clipped_end) for every span intersecting
        the non-empty range [begin, end)."""
        out = []
        lo = max(bisect.bisect_right(self.begins, begin) - 1, 0)
        for s in range(lo, self.n_shards):
            sb = self.begins[s]
            if sb >= end:
                break
            se = self.span_end(s)
            cb = max(begin, sb)
            ce = end if se is None else min(end, se)
            if cb < ce:
                out.append((s, cb, ce))
        return out


class EpochedKeyShardMap:
    """Versioned shard map: a monotone sequence of (epoch, flip_version,
    KeyShardMap) entries, atomically flipped at a chosen commit version.

    The online-resharding analog of the proxy's `_routing_flips` chain
    (server/proxy.py): every consumer routes a batch by the newest epoch
    whose flip_version is <= the batch's commit version, so proxies and
    resolvers that agree on commit versions agree on routing — a
    transaction resolves under exactly ONE epoch (the one its batch
    version selects), never both sides of a flip. Epochs fully below the
    GC horizon are pruned (`gc`); the newest epoch at or below the
    horizon is always kept (it still routes the horizon itself).

    Jax-free and wire-serializable like KeyShardMap: the whole epoch
    chain rides status documents and role-handoff RPCs."""

    def __init__(self, initial: KeyShardMap, flip_version: int = 0,
                 epoch: int = 0):
        #: ascending (epoch, flip_version, map)
        self.epochs: List[Tuple[int, int, KeyShardMap]] = \
            [(int(epoch), int(flip_version), initial)]

    @property
    def epoch(self) -> int:
        return self.epochs[-1][0]

    @property
    def flip_version(self) -> int:
        return self.epochs[-1][1]

    def current(self) -> KeyShardMap:
        return self.epochs[-1][2]

    def map_for_version(self, version: int) -> KeyShardMap:
        """The map that resolves `version`: newest epoch at or below it
        (versions below the first retained flip route by that first
        epoch — its predecessors were GC'd because nothing below the
        horizon may resolve any more)."""
        return self.entry_for_version(version)[2]

    def entry_for_version(self, version: int) -> Tuple[int, int, KeyShardMap]:
        for e in reversed(self.epochs):
            if version >= e[1]:
                return e
        return self.epochs[0]

    def flip(self, new_map: KeyShardMap, flip_version: int) -> int:
        """Install `new_map` for every version >= flip_version; returns
        the new epoch id. Flips are strictly ordered — a flip at or below
        the newest one would make routing ambiguous for the overlap."""
        assert flip_version > self.flip_version, \
            f"flip at v{flip_version} not above newest v{self.flip_version}"
        e = self.epoch + 1
        self.epochs.append((e, int(flip_version), new_map))
        return e

    def gc(self, oldest_version: int) -> None:
        """Drop epochs no version >= oldest_version can route by."""
        while len(self.epochs) > 1 and self.epochs[1][1] <= oldest_version:
            self.epochs.pop(0)

    def as_dict(self) -> dict:
        # keys render through _fmt_key: this dict rides campaign-report
        # JSON (`cli shards REPORT.json`), where raw bytes would land as
        # repr strings via json default=str
        return {
            "epoch": self.epoch,
            "flip_version": self.flip_version,
            "n_shards": self.current().n_shards,
            "splits": [_fmt_key(k) for k in self.current().begins[1:]],
            "history": [
                {"epoch": e, "flip_version": fv,
                 "splits": [_fmt_key(k) for k in m.begins[1:]]}
                for e, fv, m in self.epochs
            ],
        }


# wire codec: a shard map is fully described by its split keys (real-mode
# role interfaces carry it inside ProxyConfig / Initialize* requests)
wire.register_adapter(
    KeyShardMap, "KeyShardMap",
    to_state=lambda m: list(m.begins[1:]),
    from_state=lambda splits: KeyShardMap(splits),
)

# the epoch chain serializes as its (epoch, flip_version, splits) rows
wire.register_adapter(
    EpochedKeyShardMap, "EpochedKeyShardMap",
    to_state=lambda em: [(e, fv, list(m.begins[1:]))
                         for e, fv, m in em.epochs],
    from_state=lambda rows: _epoched_from_state(rows),
)


def _epoched_from_state(rows) -> EpochedKeyShardMap:
    e0, fv0, splits0 = rows[0]
    em = EpochedKeyShardMap(KeyShardMap(list(splits0)), fv0, e0)
    em.epochs = [(int(e), int(fv), KeyShardMap(list(s)))
                 for e, fv, s in rows]
    return em
