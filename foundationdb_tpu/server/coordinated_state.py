"""Coordinated state: majority read/write of DBCoreState over coordinators.

Re-design of fdbserver/CoordinatedState.actor.cpp + DBCoreState.h. The
coordinated state is the cluster's root of trust: which tlog generation is
current, where recovery left off, and which configuration the transaction
system runs. A recovering master must (1) read it from a majority, (2)
write the new generation exclusively — losing the race to a competing
master surfaces as coordinated_state_conflict, killing the loser.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, List, Optional

from ..core import buggify, error
from ..sim.actors import all_of
from ..sim.loop import Future, TaskPriority
from ..sim.network import Endpoint
from .coordination import (
    GENERATION_READ_TOKEN,
    GENERATION_WRITE_TOKEN,
    Generation,
    GenerationReadRequest,
    GenerationWriteRequest,
    ZERO_GEN,
)

CSTATE_KEY = "dbcore"
COORD_REQUEST_TIMEOUT = 2.0


@dataclass(frozen=True)
class LogGenerationInfo:
    """One tlog generation (reference: CoreTLogSet, DBCoreState.h): its
    LogSystemConfig (membership + identity + version floor) and, once the
    epoch has ended, the recovery version it was cut at. end_version ==
    None means the generation is current (still growing)."""

    config: Any                 # LogSystemConfig (kept untyped: no cycle)
    end_version: Optional[int] = None


@dataclass(frozen=True)
class DBCoreState:
    """reference: DBCoreState (fdbserver/DBCoreState.h) — everything a new
    master needs to end the previous epoch: the recovery count and the tlog
    generations that may hold unrecovered data. Storage assignments ride
    along (the reference reads them from the txnStateStore tag; carrying
    them here keeps the seed-configuration path explicit until the system
    keyspace lands)."""

    recovery_count: int = 0
    generations: tuple = ()           # of LogGenerationInfo, oldest..newest
    storage_tags: tuple = ()          # of (tag, shard_begin, shard_end, address)
    #: resolver key-shard split keys chosen by resolutionBalancing; empty =
    #: uniform splits (masterserver.actor.cpp:919-977)
    resolver_splits: tuple = ()
    #: worker addresses excluded from hosting storage (ManagementAPI's
    #: \xff/conf/excluded analog — persisted so recoveries keep them)
    excluded: tuple = ()
    #: mirror of the committed \xff/conf/ map as sorted (key, value) byte
    #: pairs: recovery reads role counts from HERE (before any storage is
    #: reachable), the way the reference reads DatabaseConfiguration out
    #: of the recovered txnStateStore
    conf: tuple = ()


class CoordinatedState:
    """One master's handle on the replicated cstate (ReusableCoordinatedState).

    Protocol (CoordinatedState.actor.cpp): reads broadcast a fresh
    generation and take the value with the highest write generation from a
    majority; the subsequent exclusive write reuses a generation higher
    than everything seen — a competing master's interleaved read/write
    forces this writer's generation stale and its write fails.
    """

    def __init__(self, net, src_addr: str, coordinator_addrs: List[str], salt: int):
        from ..sim.actors import AsyncMutex

        self.net = net
        self.src = src_addr
        self.coords = list(coordinator_addrs)
        self.salt = salt
        self._max_seen = ZERO_GEN
        self._read_gen: Optional[Generation] = None
        #: generation the NEXT set_exclusive writes at. Starts at the read
        #: generation (the exclusivity check needs write1 == read gen);
        #: subsequent writes run a fresh read-verify-write cycle so they
        #: are ordered after our earlier writes AND any interleaved writer
        #: is detected — sequential writes from one handle MUST be ordered,
        #: or a network-delayed earlier write applying late on one
        #: coordinator silently reinstates a stale value at an equal
        #: generation and a later quorum read can return it (found by
        #: BUGGIFY reordering; the register max() can't break same-gen ties)
        self._write_gen: Optional[Generation] = None
        self._last_written: Optional[DBCoreState] = None
        self._write_mutex = AsyncMutex()

    @property
    def _majority(self) -> int:
        return len(self.coords) // 2 + 1

    async def _broadcast(self, token: str, req_for) -> List[Any]:
        """Send to every coordinator; return the successful majority of
        replies (error if a majority is unreachable)."""
        if buggify.buggify():
            # skewed quorum broadcast: a straggling master's ops interleave
            # with a competitor's — the generation math must stay exclusive
            from ..sim.loop import delay
            await delay(0.1, TaskPriority.COORDINATION)
        futures = [
            self.net.request(
                self.src, Endpoint(addr, token), req_for(addr),
                TaskPriority.COORDINATION, timeout=COORD_REQUEST_TIMEOUT,
            )
            for addr in self.coords
        ]
        out = Future()
        replies: List[Any] = []
        state = {"err": 0}
        n = len(futures)

        def one(f) -> None:
            if out.is_ready:
                return
            if f.is_error:
                state["err"] += 1
                if n - state["err"] < self._majority:
                    out._set_error(error.coordinators_changed("majority unreachable"))
                return
            replies.append(f.get())
            if len(replies) >= self._majority:
                out._set(None)

        for f in futures:
            f.on_ready(one)
        await out
        return replies

    async def read(self) -> Optional[DBCoreState]:
        """Loop until our read generation exceeds every generation any
        majority coordinator has seen (reference: CoordinatedState::read
        retries on conflictGen). Without the loop, a fresh reader's
        first-guess generation competes on the random salt against the
        accumulated history and its write can lose forever — live-locking
        recovery behind master churn."""
        while True:
            gen = Generation(self._max_seen.txn + 1, self.salt)
            replies = await self._broadcast(
                GENERATION_READ_TOKEN, lambda _: GenerationReadRequest(CSTATE_KEY, gen)
            )
            value, value_gen = None, ZERO_GEN
            stale = False
            for r in replies:
                if r.value_gen >= value_gen:
                    value, value_gen = r.value, r.value_gen
                if r.read_gen > self._max_seen:
                    self._max_seen = r.read_gen
                if r.read_gen > gen:
                    stale = True   # someone is ahead: our write would lose
            if stale:
                continue
            self._read_gen = gen
            self._write_gen = gen
            return value

    async def set_exclusive(self, state: DBCoreState) -> None:
        """Write `state`; any interleaved reader/writer with a higher
        generation wins and we die (coordinated_state_conflict semantics
        via master_recovery_failed).

        The first write uses the read generation exactly (the register's
        `gen >= read_gen` check is the exclusivity gate). Every LATER write
        runs a fresh read-verify-write cycle (the reference's
        ReusableCoordinatedState shape): the fresh read yields a strictly
        higher generation — ordering this write after our own earlier ones
        even when a delayed duplicate frame lands late on one register —
        and verifies the value is still our last write, so an interleaved
        writer is detected rather than silently overwritten (a bare
        txn+1 bump would pass the register check on a salt tie and let two
        masters both believe they hold exclusivity)."""
        async with self._write_mutex:
            assert self._write_gen is not None, "read() before set_exclusive()"
            if self._last_written is not None:
                cur = await self.read()
                if cur != self._last_written:
                    raise error.master_recovery_failed(
                        "cstate changed under this master between writes"
                    )
            gen = self._write_gen
            replies = await self._broadcast(
                GENERATION_WRITE_TOKEN,
                lambda _: GenerationWriteRequest(CSTATE_KEY, gen, state),
            )
            for r in replies:
                if not r.ok:
                    raise error.master_recovery_failed(
                        f"cstate write lost to generation {r.max_gen}"
                    )
            if gen > self._max_seen:
                self._max_seen = gen
            self._last_written = state


from ..core import wire as _wire

_wire.register_record(LogGenerationInfo)
_wire.register_record(DBCoreState)
