"""ManagementAPI: transactional database configuration.

Re-design of fdbclient/ManagementAPI.actor.cpp (changeConfig) +
DatabaseConfiguration.cpp: the configuration lives in the `\\xff/conf/`
keyspace, written transactionally (ordered with user traffic, replicated,
recovered like any data). The serving master watches the range; a change
updates the coordinated state's conf mirror and bounces the epoch, and
the NEXT recovery recruits with the new counts — exactly the reference's
"most configuration changes take effect at the next recovery" model.
Storage replication changes additionally drive the DD replication fixer,
which grows/shrinks every shard's team to the configured factor.

Conf keys (values are ascii integers):
    \\xff/conf/proxies          commit proxies per generation
    \\xff/conf/resolvers        resolvers (key-shard count)
    \\xff/conf/logs             tlog replicas per generation
    \\xff/conf/log_replication  per-tag tlog replication factor (0 = all)
    \\xff/conf/replication      storage replicas per shard (1/2/3 =
                                single/double/triple)
"""
from __future__ import annotations

from typing import Dict, Optional

CONF_PREFIX = b"\xff/conf/"
CONF_END = CONF_PREFIX + b"\xff"

#: `configure single|double|triple` redundancy modes -> storage replication
REDUNDANCY_MODES = {"single": 1, "double": 2, "triple": 3}
#: every legal conf key suffix
CONF_KEYS = (b"proxies", b"resolvers", b"logs", b"log_replication",
             b"replication")


def conf_key(name: bytes) -> bytes:
    return CONF_PREFIX + name


def conf_int(conf: Dict[bytes, bytes], name: bytes, default: int) -> int:
    """A conf entry as an int, else `default` (missing or unparsable —
    tolerant: a bad write must never wedge recovery)."""
    raw = conf.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


async def read_configuration(tr) -> Dict[bytes, bytes]:
    """The current \\xff/conf/ map through a transaction."""
    rows = await tr.get_range(CONF_PREFIX, CONF_END, limit=1000, snapshot=True)
    return {k[len(CONF_PREFIX):]: v for k, v in rows}


async def change_configuration(db, mode: Optional[str] = None, **counts) -> None:
    """reference: changeConfig. `mode` is a redundancy keyword
    (single/double/triple); `counts` are proxies=/resolvers=/logs=/
    log_replication= integers. Writes are one transaction: the serving
    master's conf watcher picks the commit up and applies it at the next
    recovery."""
    updates: Dict[bytes, bytes] = {}
    if mode is not None:
        if mode not in REDUNDANCY_MODES:
            from ..core import error

            raise error.client_invalid_operation(f"unknown redundancy mode {mode!r}")
        updates[b"replication"] = str(REDUNDANCY_MODES[mode]).encode()
    for name, value in counts.items():
        key = name.encode()
        if key not in CONF_KEYS:
            from ..core import error

            raise error.client_invalid_operation(f"unknown configuration key {name!r}")
        updates[key] = str(int(value)).encode()

    async def go(tr):
        tr.set_access_system_keys()
        for k, v in updates.items():
            tr.set(conf_key(k), v)
    await db.run(go)
