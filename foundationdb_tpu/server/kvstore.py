"""Durable ordered key-value engine: log-structured merge over the sim disk.

The "ssd-class" IKeyValueStore the round-3 verdict called for (the role of
the reference's patched-sqlite btree engine, fdbserver/KeyValueStoreSQLite
.actor.cpp + IKeyValueStore.h:30-99) — own design: an LSM tree rather than
a B-tree, because the sim disk's fault model (torn un-synced writes,
AsyncFileNonDurable semantics) rewards append-only structures with
checksummed framing, and the write path of a storage server is
sequential-batch shaped anyway.

Structure on disk (all under a name prefix):
  <name>.dq            WAL via DiskQueue (checksummed frames, torn-tail
                       recovery, alternating pop headers)
  <name>-<seq>.sst     immutable sorted runs: 4KB-target blocks of wire-
                       encoded entries, a block index, range tombstones,
                       and a checksummed footer; always fully synced
                       BEFORE the manifest references them
  <name>.manifest      wire dict {runs: [...], seq}: written to a temp
                       file, synced, renamed (atomic install)

Write path: set/clear buffer into the memtable; commit() appends one WAL
frame and fsyncs — that is the durability point. When the memtable exceeds
flush_bytes, commit() also flushes it to a new run and truncates the WAL.
When runs pile past max_runs, a full merge compacts them to one (newest
precedence, tombstones dropped).

Read path: memtable -> runs newest-to-oldest, block reads on demand through
the per-run index with a small LRU block cache — the dataset does NOT live
in process memory; RAM holds the memtable, indexes, and the cache only.

Mutation precedence inside the memtable is tracked with sequence numbers;
a flush materializes point entries post-tombstone, so within a run a point
entry always wins and the run's range tombstones mask only older levels.
"""
from __future__ import annotations

import struct
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..core import buggify, wire
from ..sim.actors import AsyncMutex
from ..sim.disk import SimDisk
from .disk_queue import DiskQueue

Key = bytes
Value = bytes

_FOOT = struct.Struct("<II")      # footer length, crc32


def _lookup(mem: Dict[Key, Tuple[int, Optional[Value]]],
            tombs: List[Tuple[int, Key, Key]],
            key: Key) -> Tuple[bool, Optional[Value]]:
    """Memtable precedence rule, shared by point gets (live state) and
    range reads (their snapshot): a point entry wins iff its seq is newer
    than every covering range tombstone."""
    e = mem.get(key)
    tomb_seq = max((s for s, b, x in tombs if b <= key < x), default=-1)
    if e is not None and e[0] > tomb_seq:
        return True, e[1]
    if tomb_seq >= 0:
        return True, None
    return False, None


class _Run:
    """One immutable sorted run: lazy block reads through the index."""

    def __init__(self, disk: SimDisk, name: str, index, tombs, cache, cache_cap):
        self.disk = disk
        self.name = name
        #: [(first_key, offset, length)] per block, ascending
        self.index = index
        #: [(begin, end)] range tombstones masking OLDER levels
        self.tombs = tombs
        self._cache = cache
        self._cache_cap = cache_cap

    @classmethod
    async def open(cls, disk: SimDisk, name: str, cache, cache_cap) -> "_Run":
        f = disk.open(name, create=False)
        size = f.size()
        raw = await f.read(size - _FOOT.size, _FOOT.size)
        flen, crc = _FOOT.unpack(raw)
        foot = await f.read(size - _FOOT.size - flen, flen)
        if zlib.crc32(foot) != crc:
            raise IOError(f"corrupt run footer: {name}")
        meta = wire.loads(foot)
        return cls(disk, name, meta["index"], meta["tombs"], cache, cache_cap)

    def covers_tomb(self, key: Key) -> bool:
        return any(b <= key < e for b, e in self.tombs)

    def _block_of(self, key: Key) -> int:
        """Index of the last block whose first_key <= key (-1: before all)."""
        lo, hi = -1, len(self.index) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.index[mid][0] <= key:
                lo = mid
            else:
                hi = mid - 1
        return lo

    async def _block(self, i: int) -> List[Tuple[Key, Optional[Value]]]:
        ck = (self.name, i)
        hit = self._cache.get(ck)
        if hit is not None:
            self._cache.move_to_end(ck)
            return hit
        _, off, length = self.index[i]
        f = self.disk.open(self.name, create=False)
        raw = await f.read(off, length)
        entries = wire.loads(raw)
        self._cache[ck] = entries
        while len(self._cache) > self._cache_cap:
            self._cache.popitem(last=False)
        return entries

    async def get(self, key: Key) -> Tuple[bool, Optional[Value]]:
        """(found, value|None-tombstone). found=False: key absent from this
        run's points (range tombstones are the caller's concern)."""
        i = self._block_of(key)
        if i < 0:
            return False, None
        entries = await self._block(i)
        lo, hi = 0, len(entries)
        while lo < hi:
            mid = (lo + hi) // 2
            if entries[mid][0] < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(entries) and entries[lo][0] == key:
            return True, entries[lo][1]
        return False, None

    async def iter_from(self, key: Key, reverse: bool = False):
        """Async generator of (k, v|None) from `key` (inclusive forward,
        <= key backward for reverse)."""
        nb = len(self.index)
        if not reverse:
            i = max(self._block_of(key), 0)
            while i < nb:
                for k, v in await self._block(i):
                    if k >= key:
                        yield k, v
                i += 1
        else:
            i = self._block_of(key)
            if i < 0:
                return
            while i >= 0:
                for k, v in reversed(await self._block(i)):
                    if k <= key:
                        yield k, v
                i -= 1


class SSTableStore:
    FLUSH_BYTES = 1 << 16
    MAX_RUNS = 6
    BLOCK_BYTES = 1 << 12
    CACHE_BLOCKS = 64

    def __init__(self, disk: SimDisk, name: str):
        self.disk = disk
        self.name = name
        self.wal = DiskQueue(disk, name)
        #: key -> (seq, value|None); range tombstones [(seq, begin, end)]
        self._mem: Dict[Key, Tuple[int, Optional[Value]]] = {}
        self._mem_tombs: List[Tuple[int, Key, Key]] = []
        self._mem_bytes = 0
        self._seq = 0
        self._run_seq = 0
        self._runs: List[_Run] = []          # newest first
        self._pending: List[Tuple] = []      # ops since last commit
        self._cache: OrderedDict = OrderedDict()
        #: readers mid-await: compaction must not delete run files under
        #: them (epoch-style reclamation — files die when the last reader
        #: that could still hold their _Run finishes)
        self._active_reads = 0
        self._defer_delete: List[str] = []
        #: serializes commit(): two concurrent committers (storage
        #: durability cycle vs extend_shard page commits, tlog spill vs
        #: pop clears) would otherwise interleave at the WAL-push await —
        #: one clearing _pending ops the other never logged — or race a
        #: _flush into the middle of a _compact's run-list rebuild
        self._commit_mutex = AsyncMutex()
        #: the background compaction, if one is running (commit() spawns)
        self._compact_task = None
        #: single-flight for the merge itself: a direct maintenance
        #: _compact() call must never overlap the background one (both
        #: snapshot the run list and reclaim files)
        self._compact_mutex = AsyncMutex()
        #: high-water mark of items the streaming merge buffered at once
        #: (heads + the current block) — the bounded-memory contract; tests
        #: assert it never approaches the dataset size
        self.compact_peak_items = 0

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    async def open(cls, disk: SimDisk, name: str) -> "SSTableStore":
        st = cls(disk, name)
        man = f"{name}.manifest"
        run_names: List[str] = []
        if disk.exists(man):
            f = disk.open(man)
            try:
                meta = wire.loads(await f.read(0, f.size()))
                run_names = meta["runs"]
                st._run_seq = meta["seq"]
            except Exception:
                run_names = []      # torn manifest: fresh store (pre-install)
        for rn in run_names:
            st._runs.append(await _Run.open(disk, rn, st._cache, cls.CACHE_BLOCKS))
        # Orphaned runs (crash between run sync and manifest install): GC.
        keep = set(run_names)
        for fname in disk.list(f"{name}-"):
            if fname.endswith(".sst") and fname not in keep:
                disk.delete(fname)
        for _, payload in await st.wal.recover():
            try:
                ops = wire.loads(payload)
            except Exception:
                break
            st._apply_ops(ops)
        st._pending = []
        return st

    def _apply_ops(self, ops) -> None:
        for op in ops:
            if op[0] == 0:
                self._mem_set(op[1], op[2])
            else:
                self._mem_clear(op[1], op[2])

    # -- write path ----------------------------------------------------------
    def _mem_set(self, key: Key, value: Optional[Value]) -> None:
        self._seq += 1
        self._mem[key] = (self._seq, value)
        self._mem_bytes += len(key) + (len(value) if value else 0) + 16

    def _mem_clear(self, begin: Key, end: Key) -> None:
        self._seq += 1
        self._mem_tombs.append((self._seq, begin, end))
        for k in [k for k in self._mem if begin <= k < end]:
            self._mem[k] = (self._seq, None)
        self._mem_bytes += len(begin) + len(end) + 16

    def set(self, key: Key, value: Value) -> None:
        self._pending.append((0, key, value))
        self._mem_set(key, value)

    def clear_range(self, begin: Key, end: Key) -> None:
        self._pending.append((1, begin, end))
        self._mem_clear(begin, end)

    async def commit(self) -> None:
        """Durability point: WAL frame + fsync; flush as needed
        (IKeyValueStore::commit). Serialized: ops staged after this
        committer's WAL snapshot ride the NEXT commit (and its fsync ack),
        never a half-logged state. Compaction runs in the BACKGROUND — the
        commit path never waits for a merge (the reference's btree spreads
        its page writes the same way)."""
        async with self._commit_mutex:
            if self._pending:
                ops, self._pending = self._pending, []
                if buggify.buggify():
                    # slow WAL append: widens the un-fsynced window a crash
                    # tears through
                    from ..sim.loop import TaskPriority, delay
                    await delay(0.01, TaskPriority.DEFAULT_DELAY)
                await self.wal.push(wire.dumps(ops))
            await self.wal.commit()
            flush_at = 256 if buggify.buggify() else self.FLUSH_BYTES
            if self._mem_bytes >= flush_at:
                await self._flush()
        max_runs = 1 if buggify.buggify() else self.MAX_RUNS
        if len(self._runs) > max_runs and self._compact_task is None:
            from ..sim.loop import TaskPriority, spawn

            t = spawn(self._compact_bg(), TaskPriority.LOW,
                      name=f"compact:{self.name}")
            self._compact_task = t

            def done(_f) -> None:
                self._compact_task = None

            t.on_ready(done)

    async def _write_run(self, entries, tombs) -> str:
        """entries: sorted [(k, v|None)]; returns the synced file name.
        One encoder for the run format: delegates to the streaming writer
        (which compaction also uses)."""
        async def gen():
            for e in entries:
                yield e
        return await self._write_run_stream(gen(), tombs)

    async def _install_manifest(self, run_names: List[str]) -> None:
        tmp = f"{self.name}.manifest.tmp"
        f = self.disk.open(tmp)
        await f.truncate(0)
        await f.write(0, wire.dumps({"runs": run_names, "seq": self._run_seq}))
        await f.sync()
        self.disk.rename(tmp, f"{self.name}.manifest")

    async def _flush(self) -> None:
        if not self._mem and not self._mem_tombs:
            return
        entries = sorted((k, v) for k, (_s, v) in self._mem.items())
        tombs = [(b, e) for _s, b, e in self._mem_tombs]
        rn = await self._write_run(entries, tombs)
        run = await _Run.open(self.disk, rn, self._cache, self.CACHE_BLOCKS)
        self._runs.insert(0, run)
        await self._install_manifest([r.name for r in self._runs])
        self._mem.clear()
        self._mem_tombs.clear()
        self._mem_bytes = 0
        if buggify.buggify():
            # crash window: run installed in the manifest but the WAL not
            # yet truncated — recovery must tolerate re-applying covered ops
            from ..sim.loop import TaskPriority, delay
            await delay(0.02, TaskPriority.DEFAULT_DELAY)
        # WAL content is fully covered by the installed run.
        await self.wal.pop_to(self.wal.end_offset)

    async def _write_run_stream(self, entries, tombs) -> str:
        """Write one sorted run from an ASYNC ITERATOR of (k, v) entries,
        block by block — the one and only encoder of the on-disk run
        format (a second inline copy would silently diverge from
        _Run.open's expectations). Returns the synced file name."""
        self._run_seq += 1
        rn = f"{self.name}-{self._run_seq}.sst"
        f = self.disk.open(rn)
        await f.truncate(0)
        index = []
        off = 0
        blk: List[Tuple[Key, Value]] = []
        bbytes = 0
        n_entries = 0

        async def flush_blk():
            nonlocal off, blk, bbytes
            raw = wire.dumps(blk)
            await f.write(off, raw)
            index.append((blk[0][0], off, len(raw)))
            off += len(raw)
            blk, bbytes = [], 0

        async for k, v in entries:
            blk.append((k, v))
            n_entries += 1
            bbytes += len(k) + len(v or b"") + 8
            self.compact_peak_items = max(self.compact_peak_items, len(blk))
            if bbytes >= self.BLOCK_BYTES:
                await flush_blk()
                if buggify.buggify():
                    # mid-write crash window: the half-written run is an
                    # orphan reopen GCs; the manifest still names the OLD
                    # runs
                    from ..sim.loop import TaskPriority, delay
                    await delay(0.02, TaskPriority.DEFAULT_DELAY)
        if blk:
            await flush_blk()
        foot = wire.dumps({"index": index, "tombs": tombs, "n": n_entries})
        await f.write(off, foot + _FOOT.pack(len(foot), zlib.crc32(foot)))
        await f.sync()
        return rn

    async def _merged_entries(self, snapshot):
        """Streaming k-way merge over `snapshot` runs (newest first):
        newest precedence, tombstones of newer runs mask older entries,
        resolved deletions drop out. Peak memory: one head per run."""
        iters = [r.iter_from(b"") for r in snapshot]
        heads: List[Optional[Tuple[Key, Optional[Value]]]] = []
        for it in iters:
            try:
                heads.append(await anext(it))
            except StopAsyncIteration:
                heads.append(None)
        while True:
            pick: Optional[Key] = None
            for h in heads:
                if h is not None and (pick is None or h[0] < pick):
                    pick = h[0]
            if pick is None:
                return
            val: Optional[Value] = None
            taken = None
            for i, h in enumerate(heads):
                if h is not None and h[0] == pick:
                    if taken is None:
                        taken = i
                        val = h[1]
                    try:
                        heads[i] = await anext(iters[i])
                    except StopAsyncIteration:
                        heads[i] = None
            if taken is not None and any(
                snapshot[up].covers_tomb(pick) for up in range(taken)
            ):
                val = None
            if val is not None:
                yield pick, val

    async def _compact_bg(self) -> None:
        """Background full compaction of a SNAPSHOT of the current runs:
        streaming k-way merge (newest precedence, tombstones resolved and
        dropped), blocks written incrementally — peak memory is one block
        plus one head per run, NEVER the dataset ("the dataset does not
        live in process memory" holds through its own maintenance).
        Commits keep flushing new runs meanwhile; the install swaps only
        the snapshotted suffix of the run list. Single-flight under the
        compact mutex (a direct _compact() call serializes behind us)."""
        async with self._compact_mutex:
            await self._compact_locked()

    async def _compact_locked(self) -> None:
        snapshot = list(self._runs)
        if len(snapshot) < 2:
            return
        rn = await self._write_run_stream(self._merged_entries(snapshot), [])
        run = await _Run.open(self.disk, rn, self._cache, self.CACHE_BLOCKS)
        if buggify.buggify():
            # crash window: merged run durable but manifest not installed —
            # reopen must GC the orphan and serve the OLD manifest's runs
            from ..sim.loop import TaskPriority, delay
            await delay(0.02, TaskPriority.DEFAULT_DELAY)
        # swap ONLY the snapshotted suffix: runs flushed during the merge
        # stay in front (they are newer than the merged result). The
        # install shares the commit mutex so a concurrent flush's manifest
        # write cannot interleave with ours on the tmp file.
        async with self._commit_mutex:
            keep = self._runs[: len(self._runs) - len(snapshot)]
            assert self._runs[len(self._runs) - len(snapshot):] == snapshot
            old = [r.name for r in snapshot]
            self._runs = keep + [run]
            await self._install_manifest([r.name for r in self._runs])
        for name in old:
            for ck in [c for c in self._cache if c[0] == name]:
                del self._cache[ck]
        self._reclaim(old)

    async def _compact(self) -> None:
        """Synchronous full merge (tests and maintenance entry): the same
        streaming path, serialized behind any background merge."""
        await self._compact_bg()

    def _reclaim(self, names: List[str]) -> None:
        """Delete run files now, or park them until in-flight reads drain
        (a reader's _Run would otherwise hit file_not_found mid-block)."""
        if self._active_reads > 0:
            self._defer_delete.extend(names)
        else:
            for name in names:
                self.disk.delete(name)

    def _read_done(self) -> None:
        self._active_reads -= 1
        if self._active_reads == 0 and self._defer_delete:
            names, self._defer_delete = self._defer_delete, []
            for name in names:
                self.disk.delete(name)

    # -- read path -----------------------------------------------------------
    def _mem_lookup(self, key: Key) -> Tuple[bool, Optional[Value]]:
        return _lookup(self._mem, self._mem_tombs, key)

    async def get(self, key: Key) -> Optional[Value]:
        found, v = self._mem_lookup(key)
        if found:
            return v
        if buggify.buggify():
            # slow cold read: stretches the window a concurrent
            # flush/compaction can interleave into
            from ..sim.loop import TaskPriority, delay
            await delay(0.01, TaskPriority.DEFAULT_DELAY)
        runs = list(self._runs)     # snapshot: a flush/compact mid-read
        self._active_reads += 1     # must not shift or delete our levels
        try:
            for run in runs:
                found, v = await run.get(key)
                if found:
                    return v
                if run.covers_tomb(key):
                    return None
            return None
        finally:
            self._read_done()

    async def get_range(self, begin: Key, end: Key, limit: int,
                        reverse: bool = False) -> Tuple[List[Tuple[Key, Value]], bool]:
        """Merged live entries in [begin, end); (items, more). The memtable
        and run list are SNAPSHOTTED up front: a commit/flush/compact
        interleaving with this read's block awaits must not clear _mem
        under the lazy cursor or renumber the precedence levels."""
        out: List[Tuple[Key, Value]] = []
        # Per-level cursors: (next entry, level, iterator). Memtable is
        # level -1 (highest precedence).
        mem_snap = {k: e for k, e in self._mem.items() if begin <= k < end}
        mem_tombs = list(self._mem_tombs)
        runs = list(self._runs)
        mem_keys = sorted(mem_snap)
        if reverse:
            mem_keys.reverse()

        async def mem_iter():
            for k in mem_keys:
                yield k, mem_snap[k][1]

        def mem_lookup(key: Key) -> Tuple[bool, Optional[Value]]:
            return _lookup(mem_snap, mem_tombs, key)

        def masked(key: Key, level: int) -> bool:
            # masked by a range tombstone strictly newer than `level`
            # (level -1 = memtable; runs are levels 0..). Memtable point
            # entries override mem tombs via seq; for runs the memtable
            # tomb always wins (it is newer than every run).
            if level >= 0 and any(b <= key < e for _s, b, e in mem_tombs):
                return True
            for up in range(max(level, 0)):
                if runs[up].covers_tomb(key):
                    return True
            return False

        iters = [(-1, mem_iter())]
        for lvl, run in enumerate(runs):
            if reverse:
                it = run.iter_from(end, reverse=True)
            else:
                it = run.iter_from(begin)
            iters.append((lvl, it))

        self._active_reads += 1
        try:
            heads: List[Optional[Tuple[Key, Optional[Value]]]] = []
            live: List = []
            for lvl, it in iters:
                try:
                    nxt = await anext(it)
                    if reverse and lvl >= 0 and nxt[0] >= end:
                        while nxt[0] >= end:
                            nxt = await anext(it)
                except StopAsyncIteration:
                    nxt = None
                heads.append(nxt)
                live.append(it)

            def better(a: Key, b: Key) -> bool:
                return a > b if reverse else a < b

            while len(out) < limit:
                # pick frontier key across levels
                pick: Optional[Key] = None
                for h in heads:
                    if h is not None and (not reverse and h[0] >= end):
                        continue
                    if h is not None and (pick is None or better(h[0], pick)):
                        pick = h[0]
                if pick is None or (not reverse and pick >= end) or (reverse and pick < begin):
                    return out, False
                # resolve precedence: lowest level index with this key wins
                val: Optional[Value] = None
                taken_level = None
                for idx, h in enumerate(heads):
                    if h is not None and h[0] == pick:
                        if taken_level is None:
                            taken_level = idx - 1   # level: -1 memtable
                            val = h[1]
                        try:
                            heads[idx] = await anext(live[idx])
                        except StopAsyncIteration:
                            heads[idx] = None
                if taken_level is not None and taken_level >= 0 and masked(pick, taken_level):
                    val = None
                elif taken_level == -1:
                    # memtable entry: seq already resolved vs mem tombs
                    found, val = mem_lookup(pick)
                if val is not None and (begin <= pick < end):
                    out.append((pick, val))
            return out, True
        finally:
            self._read_done()

    # -- maintenance ---------------------------------------------------------
    def destroy(self) -> None:
        """Delete every on-disk artifact (IKeyValueStore::dispose)."""
        if self._compact_task is not None:
            self._compact_task.cancel()
            self._compact_task = None
        for rn in [r.name for r in self._runs] + self._defer_delete:
            self.disk.delete(rn)
        self._defer_delete = []
        self.disk.delete(f"{self.name}.manifest")
        self.disk.delete(f"{self.name}.manifest.tmp")
        self.disk.delete(f"{self.name}.dq")
        self.disk.delete(f"{self.name}.dq.tmp")
