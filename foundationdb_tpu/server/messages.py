"""Typed request/reply payloads between roles.

Analogs of the reference's *Interface.h structs (MasterProxyInterface.h,
ResolverInterface.h:83-98, TLogInterface.h, StorageServerInterface.h). The
sim network passes them by reference; roles must treat them as immutable.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.types import CommitTransaction, Key, KeyRange, Mutation, Version

# -- master ------------------------------------------------------------------


@dataclass
class GetCommitVersionRequest:
    """reference: GetCommitVersionRequest (MasterInterface.h); requestNum
    dedups retried proxy requests."""

    request_num: int
    proxy_id: str


@dataclass
class GetCommitVersionReply:
    version: Version
    prev_version: Version
    #: live resolutionBalancing (masterserver.actor.cpp:919-977 redesigned
    #: bounce-free): when set, every batch with version >= routing_version
    #: must split conflict ranges by routing_splits (the new resolver map);
    #: the master piggybacks the CURRENT flip on every reply, proxies apply
    #: it before building their batch (phase 1 orders it exactly)
    routing_version: Version = 0
    routing_old_splits: tuple = ()
    routing_splits: tuple = ()


# -- resolver ----------------------------------------------------------------


@dataclass
class ResolveTransactionBatchRequest:
    """reference: ResolverInterface.h:83-98."""

    prev_version: Version
    version: Version
    last_received_version: Version
    transactions: List[CommitTransaction] = field(default_factory=list)
    #: live split handoff (ResolutionSplitRequest's role): batches at or
    #: above routing_version were split by the NEW resolver map; on first
    #: sight (the version chain orders it), the resolver seeds a synthetic
    #: whole-span write over the ranges it GAINED, so reads with pre-flip
    #: snapshots conflict conservatively instead of silently missing the
    #: donor's history (exact again once snapshots pass the flip)
    routing_version: Version = 0
    routing_old_splits: tuple = ()
    routing_splits: tuple = ()


@dataclass
class ResolveTransactionBatchReply:
    committed: List[int] = field(default_factory=list)  # TransactionCommitResult values


# -- tlog --------------------------------------------------------------------


@dataclass
class TLogCommitRequest:
    """reference: TLogCommitRequest (TLogInterface.h); messages are
    (tag -> mutations) for one commit version. gen_id scopes the push to
    one log generation; known_committed is the proxy's newest all-replica-
    acked version (the KCV the peek horizon rides on)."""

    prev_version: Version
    version: Version
    messages: Dict[int, List[Mutation]] = field(default_factory=dict)
    gen_id: Tuple[int, int] = (0, 0)
    known_committed: Version = 0


@dataclass
class TLogKnownCommittedRequest:
    """All replicas acked `version`; advance the peek horizon."""

    version: Version


@dataclass
class TLogLockRequest:
    """End this generation (reference: TLogLockResult via tLogLock:496)."""

    pass


@dataclass
class TLogLockReply:
    gen_id: Tuple[int, int]
    known_committed: Version
    end_version: Version


@dataclass
class TLogRecoveryDataRequest:
    """Fetch all un-popped data <= end_version for seeding the successor
    generation."""

    end_version: Version


@dataclass
class TLogRecoveryDataReply:
    tag_data: Dict[int, List[Tuple[Version, List[Mutation]]]] = field(default_factory=dict)
    popped: Dict[int, Version] = field(default_factory=dict)


@dataclass
class TLogPeekRequest:
    """Pull messages for one tag from begin_version on; blocks until the
    tlog's version advances past begin_version (reference: tLogPeekMessages,
    TLogServer.actor.cpp:950)."""

    tag: int
    begin_version: Version


@dataclass
class TLogPeekReply:
    messages: List[Tuple[Version, List[Mutation]]] = field(default_factory=list)
    end_version: Version = 0   # peeker may advance its version to this


@dataclass
class TLogPopRequest:
    """Storage persisted through `version`; tlog may discard (tLogPop:898)."""

    tag: int
    version: Version


# -- proxy -------------------------------------------------------------------


@dataclass
class GetReadVersionRequest:
    """reference: GetReadVersionRequest (MasterProxyInterface.h)."""

    priority: int = 0


@dataclass
class GetReadVersionReply:
    version: Version


@dataclass
class CommitTransactionRequest:
    transaction: CommitTransaction
    #: multi-tenant QoS identity (docs/real_cluster.md): None rides the
    #: legacy single-tenant path untouched; set, the proxy's per-tenant
    #: admission control (server/ratekeeper.py TenantAdmission) may shed
    #: this commit with the typed transaction_throttled error instead of
    #: letting one hot tenant queue every other tenant past the SLO
    tenant: Optional[str] = None


@dataclass
class CommitReply:
    """version set on success; error raised otherwise (not_committed /
    transaction_too_old propagate as FDBError through the sim network).
    txn_batch_index orders transactions that share a commit version
    (reference: CommitID's batchIndex, used by versionstamps)."""

    version: Version
    txn_batch_index: int = 0


@dataclass
class GetKeyServerLocationsRequest:
    begin: Key
    end: Key


@dataclass
class GetKeyServerLocationsReply:
    """(range, [storage addresses]) pairs covering [begin, end)."""

    results: List[Tuple[KeyRange, List[str]]] = field(default_factory=list)


# -- storage -----------------------------------------------------------------


@dataclass
class GetValueRequest:
    key: Key
    version: Version


@dataclass
class GetValueReply:
    value: Optional[bytes]


@dataclass
class WatchValueRequest:
    """Fires when key's value differs from `value` (watchValue:773)."""

    key: Key
    value: Optional[bytes]
    version: Version


@dataclass
class GetKeyValuesRequest:
    """Range read [begin, end) at version, up to `limit` pairs
    (reference: GetKeyValuesRequest, StorageServerInterface.h)."""

    begin: Key
    end: Key
    version: Version
    limit: int = 10_000
    reverse: bool = False


@dataclass
class GetKeyValuesReply:
    data: List[Tuple[Key, bytes]] = field(default_factory=list)
    more: bool = False
