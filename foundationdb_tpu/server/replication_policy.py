"""Replication policy: team selection across failure domains.

Re-design of the reference's policy DSL (fdbrpc/ReplicationPolicy.h:280
PolicyAcross / PolicyAnd over LocalityData) reduced to the composition the
framework actually deploys: choose/validate replica teams spread across
distinct locality values (machine, then datacenter as the outer domain).
Localities flow from each worker's registration (SimProcess machine_id /
dc_id — the sim's LocalityData) through the cluster controller to the
master's data distribution.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

Locality = Tuple[str, str]          # (machine_id, dc_id)


class PolicyAcross:
    """`count` replicas across distinct values of `field` ("machine_id" or
    "dc_id"); falls back to best-effort spread when the pool has fewer
    distinct domains than replicas (the reference's team builder likewise
    degrades rather than stalling on small clusters)."""

    def __init__(self, count: int, field: str = "machine_id"):
        assert field in ("machine_id", "dc_id")
        self.count = count
        self.field = field

    def _value(self, loc: Optional[Locality]) -> str:
        if loc is None:
            return ""
        return loc[0] if self.field == "machine_id" else loc[1]

    def select(
        self,
        candidates: Sequence[str],
        localities: Dict[str, Locality],
    ) -> Optional[List[str]]:
        """Pick `count` addresses, preferring distinct domains; determinate
        given candidate order. None if the pool is too small."""
        if len(candidates) < self.count:
            return None
        chosen: List[str] = []
        used_domains: set = set()
        # pass 1: one per distinct domain
        for a in candidates:
            if len(chosen) == self.count:
                return chosen
            d = self._value(localities.get(a))
            if d not in used_domains:
                chosen.append(a)
                used_domains.add(d)
        # pass 2 (degraded): fill from the remainder
        for a in candidates:
            if len(chosen) == self.count:
                break
            if a not in chosen:
                chosen.append(a)
        return chosen if len(chosen) == self.count else None

    def validate(self, team: Sequence[str], localities: Dict[str, Locality]) -> bool:
        """True iff the team spans min(count, distinct-available) domains."""
        domains = {self._value(localities.get(a)) for a in team}
        all_domains = {self._value(l) for l in localities.values()} or {""}
        need = min(self.count, len(all_domains))
        return len(team) >= self.count and len(domains) >= need
