"""Leader election: candidacy against the coordinators' leader registers.

Re-design of fdbserver/LeaderElection.actor.cpp (tryBecomeLeaderInternal:78)
+ fdbclient/MonitorLeader.actor.cpp. A candidate registers with every
coordinator; each coordinator's register independently nominates the best
live candidate; whoever a majority nominates is the leader and keeps the
lease alive with heartbeats. Losing the heartbeat majority means stepping
down (the register will nominate a successor once the lease expires).
"""
from __future__ import annotations

from typing import List, Optional

from ..core import buggify, error
from ..sim.actors import AsyncVar, all_of, any_of
from ..sim.loop import Future, TaskPriority, delay, spawn
from ..sim.network import Endpoint
from .coordination import (
    CANDIDACY_TOKEN,
    CANDIDACY_TTL,
    GET_LEADER_TOKEN,
    LEADER_HEARTBEAT_TOKEN,
    LEADER_TIMEOUT,
    CandidacyRequest,
    GetLeaderRequest,
    LeaderHeartbeatRequest,
    LeaderInfo,
)

HEARTBEAT_INTERVAL = LEADER_TIMEOUT / 4


def _majority(n: int) -> int:
    return n // 2 + 1


async def try_become_leader(
    net, src_addr: str, coordinator_addrs: List[str], info: LeaderInfo
) -> None:
    """Returns when `info` has been elected by a majority of coordinators
    (reference: tryBecomeLeaderInternal). The caller must then run
    `hold_leadership` and abdicate when it returns."""
    nominees: List[Optional[LeaderInfo]] = [None] * len(coordinator_addrs)
    changed = AsyncVar(0)

    async def poll(i: int, addr: str) -> None:
        prev_id: Optional[int] = None
        while True:
            if buggify.buggify():
                # laggard candidate: this coordinator sees the candidacy
                # late — elections must survive stragglers and re-votes
                await delay(CANDIDACY_TTL, TaskPriority.COORDINATION)
            try:
                nominee = await net.request(
                    src_addr,
                    Endpoint(addr, CANDIDACY_TOKEN),
                    CandidacyRequest(info, prev_id),
                    TaskPriority.COORDINATION,
                    timeout=2 * CANDIDACY_TTL,
                )
            except error.FDBError:
                nominees[i] = None
                changed.set(changed.get() + 1)
                await delay(CANDIDACY_TTL / 2, TaskPriority.COORDINATION)
                prev_id = None
                continue
            nominees[i] = nominee
            prev_id = nominee.id if nominee is not None else None
            changed.set(changed.get() + 1)

    pollers = [
        spawn(poll(i, addr), TaskPriority.COORDINATION, name=f"candidacy:{addr}")
        for i, addr in enumerate(coordinator_addrs)
    ]
    try:
        while True:
            votes = sum(
                1 for n in nominees if n is not None and n.id == info.id
            )
            if votes >= _majority(len(coordinator_addrs)):
                return
            await changed.on_change()
    finally:
        for p in pollers:
            p.cancel()


async def hold_leadership(
    net, src_addr: str, coordinator_addrs: List[str], info: LeaderInfo
) -> None:
    """Heartbeat every coordinator; returns when a majority no longer
    acknowledges this leader (lease lost — abdicate NOW)."""
    while True:
        futures = [
            net.request(
                src_addr,
                Endpoint(addr, LEADER_HEARTBEAT_TOKEN),
                LeaderHeartbeatRequest(info),
                TaskPriority.COORDINATION,
                timeout=LEADER_TIMEOUT / 2,
            )
            for addr in coordinator_addrs
        ]
        acks = 0
        for f in futures:
            try:
                if await _settle(f):
                    acks += 1
            except error.FDBError:
                pass
        if acks < _majority(len(coordinator_addrs)):
            return
        interval = HEARTBEAT_INTERVAL
        if buggify.buggify():
            # near-miss heartbeat cadence: the lease renews just before
            # expiry, so coordinator-side TTL math gets exercised at the edge
            interval = LEADER_TIMEOUT * 0.9
        await delay(interval, TaskPriority.COORDINATION)


async def _settle(f: Future):
    return await f


async def tally_leader_once(net, src_addr: str, coordinator_addrs: List[str]
                            ) -> Optional[LeaderInfo]:
    """One majority nominee tally: the leader if a majority of coordinators
    currently agree on one, else None. Shared by monitor_leader and the
    client's cluster-file resolution."""
    tally: dict = {}
    for addr in coordinator_addrs:
        try:
            nominee = await net.request(
                src_addr, Endpoint(addr, GET_LEADER_TOKEN),
                GetLeaderRequest(None), TaskPriority.COORDINATION,
                timeout=LEADER_TIMEOUT,
            )
        except error.FDBError:
            continue
        if nominee is not None:
            count, _ = tally.get(nominee.id, (0, nominee))
            tally[nominee.id] = (count + 1, nominee)
    for count, nominee in tally.values():
        if count >= _majority(len(coordinator_addrs)):
            return nominee
    return None


async def monitor_leader(
    net, src_addr: str, coordinator_addrs: List[str], out: AsyncVar
) -> None:
    """Keep `out` set to the currently elected leader (or None), as seen by
    a majority of coordinators (reference: monitorLeaderInternal). Runs
    forever; spawn it on the observing process."""
    while True:
        best = await tally_leader_once(net, src_addr, coordinator_addrs)
        if (out.get().id if out.get() is not None else None) != (
            best.id if best is not None else None
        ):
            out.set(best)
        await delay(LEADER_TIMEOUT / 2, TaskPriority.COORDINATION)
