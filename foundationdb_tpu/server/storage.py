"""Storage server: MVCC reads over a versioned in-memory store.

Round-1 scope of fdbserver/storageserver.actor.cpp: a per-key version-chain
store standing in for VersionedMap (fdbclient/VersionedMap.h) over a durable
engine; an update loop pulling the server's tag from the tlog (update:2340),
applying mutations (incl. atomic ops, Atomic.h) in version order; reads wait
for the requested version (waitForVersion:644), answer from the MVCC window,
and reject out-of-window versions with transaction_too_old / future_version.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..core import buggify, error, wire
from ..core.stats import CounterCollection
from ..core.types import (
    MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
    Key,
    KeyRange,
    Mutation,
    MutationType,
    STORAGE_ATOMIC_MUTATIONS,
    Value,
    Version,
    apply_atomic_op,
)
from ..sim.actors import AsyncVar, NotifiedVersion
from ..sim.loop import TaskPriority, delay, spawn
from ..sim.network import Endpoint, SimProcess
from .disk_queue import DiskQueue
from .log_system import LogSystemClient, LogSystemConfig
from .messages import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
)

GET_VALUE_TOKEN = "storage.getValue"
GET_KEY_VALUES_TOKEN = "storage.getKeyValues"
WATCH_VALUE_TOKEN = "storage.watchValue"

#: how far ahead of the storage version a read may wait before future_version
#: (reference: storageserver waitForVersion MVCC window)
MAX_READ_AHEAD_VERSIONS = MAX_WRITE_TRANSACTION_LIFE_VERSIONS
#: parked watches expire server-side after this long; below the client's 30s
#: request timeout so a live client re-registers before its entry dies here
WATCH_EXPIRE_SECONDS = 25.0


class VersionedStore:
    """Sorted keys, each with an append-only (version, value|None) chain;
    None = cleared. The logical content at version V is the last entry <= V
    of every chain. Plays VersionedMap's role with plain bisect structures —
    adequate for simulation scale; the Pallas/native engines replace it in
    the storage-engine round."""

    def __init__(self) -> None:
        self._keys: List[Key] = []
        self._chains: Dict[Key, List[Tuple[Version, Optional[Value]]]] = {}
        self.oldest_version: Version = 0

    def _chain(self, key: Key) -> List[Tuple[Version, Optional[Value]]]:
        c = self._chains.get(key)
        if c is None:
            bisect.insort(self._keys, key)
            c = self._chains[key] = []
        return c

    def value_at(self, key: Key, version: Version) -> Optional[Value]:
        c = self._chains.get(key)
        if not c:
            return None
        i = bisect.bisect_right(c, version, key=lambda e: e[0]) - 1
        if i < 0:
            return None
        return c[i][1]

    def set(self, key: Key, value: Value, version: Version) -> None:
        self._chain(key).append((version, value))

    def clear_range(self, begin: Key, end: Key, version: Version) -> None:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            c = self._chains[k]
            if c and c[-1][1] is not None:
                c.append((version, None))

    def range_at(
        self, begin: Key, end: Key, version: Version, limit: int, reverse: bool = False
    ) -> Tuple[List[Tuple[Key, Value]], bool]:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = list(reversed(keys))
        out: List[Tuple[Key, Value]] = []
        for i, k in enumerate(keys):
            v = self.value_at(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    return out, i + 1 < len(keys)
        return out, False

    def snapshot_items(self, version: Version) -> List[Tuple[Key, Value]]:
        """Flattened live content at `version` (for durable snapshots)."""
        out: List[Tuple[Key, Value]] = []
        for k in self._keys:
            v = self.value_at(k, version)
            if v is not None:
                out.append((k, v))
        return out

    def load_snapshot(self, items: List[Tuple[Key, Value]], version: Version) -> None:
        self._keys = sorted(k for k, _ in items)
        self._chains = {k: [(version, v)] for k, v in items}
        self.oldest_version = version

    def forget_before(self, version: Version) -> None:
        """Drop history below `version`, keeping each chain's latest entry at
        or below it (the storage analog of removeBefore)."""
        self.oldest_version = max(self.oldest_version, version)
        dead: List[Key] = []
        for k, c in self._chains.items():
            i = bisect.bisect_right(c, version, key=lambda e: e[0]) - 1
            if i > 0:
                del c[: i]
            if len(c) == 1 and c[0][1] is None:
                dead.append(k)
        for k in dead:
            del self._chains[k]
            i = bisect.bisect_left(self._keys, k)
            del self._keys[i]


class StorageServer:
    #: rewrite the snapshot when the WAL exceeds this
    SNAPSHOT_BYTES = 1 << 18

    def __init__(
        self,
        proc: SimProcess,
        tag: int,
        shard: KeyRange,
        log_view: AsyncVar,
        net,
        start_version: Version = 0,
        disk=None,
        defer_update_loop: bool = False,
    ):
        """`log_view` is an AsyncVar[LogSystemConfig | None]: the current
        log generation to pull from. Recovery re-points it (the worker's
        ServerDBInfo watch), and the update loop follows — the analog of
        the reference storage server tracking the log system through
        ServerDBInfo broadcasts (storageserver.actor.cpp update:2340)."""
        self.proc = proc
        self.tag = tag
        self.shard = shard
        self.net = net
        self.log_view = log_view
        self.store = VersionedStore()
        #: reference: StorageServer::Counters (storageserver.actor.cpp)
        self.stats = CounterCollection("Storage", f"tag{tag}")
        self.version = NotifiedVersion(start_version)
        #: durable (synced) version: the tlog may only be popped to here
        self.durable_version: Version = start_version
        self.queue: Optional[DiskQueue] = DiskQueue(disk, f"storage-{tag}") if disk is not None else None
        self._disk = disk
        self._update_task = None
        self._tokens = [GET_VALUE_TOKEN, GET_KEY_VALUES_TOKEN, WATCH_VALUE_TOKEN,
                        "storage.stats"]
        proc.register(GET_VALUE_TOKEN, self.get_value)
        proc.register(GET_KEY_VALUES_TOKEN, self.get_key_values)
        #: parked watches: key -> [(expected value, Promise)]
        self._watches: Dict[Key, List] = {}
        proc.register(WATCH_VALUE_TOKEN, self.watch_value)
        from .ratekeeper import STORAGE_QUEUE_INFO_TOKEN, StorageQueueInfo

        async def queue_info(_req):
            return StorageQueueInfo(
                tag=self.tag, version=self.version.get(),
                durable_version=self.durable_version,
            )

        async def stats_req(_req):
            return self.stats.as_dict()

        proc.register("storage.stats", stats_req)

        proc.register(STORAGE_QUEUE_INFO_TOKEN, queue_info)
        self._tokens.append(STORAGE_QUEUE_INFO_TOKEN)
        if not defer_update_loop:
            self.start_update_loop()

    def start_update_loop(self) -> None:
        self._update_task = spawn(self.update_loop(), TaskPriority.STORAGE,
                                  name=f"ss-update:{self.tag}")
        self.proc.actors.add(self._update_task)

    def retire(self) -> None:
        """This replica's shard moved away (MoveKeys finish): stop serving,
        stop pulling the tag, drop the disk footprint."""
        for tok in self._tokens:
            self.proc.unregister(tok)
        if self._update_task is not None:
            self._update_task.cancel()
        for parked in self._watches.values():
            for _expected, p in parked:
                if not p.is_set:
                    p.send_error(error.watch_cancelled())
        self._watches.clear()
        if self._disk is not None:
            for suffix in (".meta", ".snap", ".snap.tmp", ".dq", ".dq.tmp"):
                self._disk.delete(self._meta_name() + suffix)

    async def fetch_keys(self, addrs: List[str], version: Version) -> None:
        """Populate this fresh replica with its shard's contents at
        `version`, read from the serving team (fetchKeys,
        storageserver.actor.cpp:1777). The AddingShard double buffer is the
        log system itself here: this tag's mutations > `version` are
        already accumulating at the tlogs and the update loop consumes them
        once this snapshot is loaded."""
        from ..core.types import key_after

        items: List[Tuple[Key, Value]] = []
        cb, ce = self.shard.begin, self.shard.end
        while cb < ce:
            reply = None
            last: Optional[error.FDBError] = None
            if buggify.buggify():
                # fetchKeys pauses mid-copy: the tag stream must buffer
                await delay(0.25, TaskPriority.FETCH_KEYS)
            for i in range(len(addrs) * 3):
                addr = addrs[i % len(addrs)]
                try:
                    reply = await self.net.request(
                        self.proc.address,
                        Endpoint(addr, GET_KEY_VALUES_TOKEN),
                        GetKeyValuesRequest(begin=cb, end=ce, version=version,
                                            limit=10_000),
                        TaskPriority.FETCH_KEYS, timeout=5.0,
                    )
                    break
                except error.FDBError as e:
                    last = e
                    await delay(0.2, TaskPriority.FETCH_KEYS)
            if reply is None:
                raise last if last is not None else error.connection_failed()
            items.extend(reply.data)
            if not reply.more or not reply.data:
                break
            cb = key_after(reply.data[-1][0])
        self.store.load_snapshot(items, version)
        self.version = NotifiedVersion(version)
        self.durable_version = version

    # -- durability ----------------------------------------------------------
    def _meta_name(self) -> str:
        return f"storage-{self.tag}"

    async def persist_initial(self) -> None:
        if self._disk is None:
            return
        meta = self._disk.open(self._meta_name() + ".meta")
        await meta.write(0, wire.dumps({
            "tag": self.tag, "begin": self.shard.begin, "end": self.shard.end,
        }))
        await meta.sync()

    async def _write_snapshot(self) -> None:
        """Flatten at the durable version into a fresh file + rename, then
        drop the covered WAL prefix (KeyValueStoreMemory's snapshot cycle)."""
        items = self.store.snapshot_items(self.durable_version)
        payload = wire.dumps({"version": self.durable_version, "items": items})
        tmp = self._disk.open(self._meta_name() + ".snap.tmp")
        await tmp.truncate(0)
        await tmp.write(0, payload)
        await tmp.sync()
        self._disk.rename(self._meta_name() + ".snap.tmp", self._meta_name() + ".snap")
        await self.queue.pop_to(self.queue.end_offset)

    @classmethod
    async def restore(cls, proc: SimProcess, disk, meta_name: str,
                      log_view: AsyncVar, net) -> Optional["StorageServer"]:
        meta_file = disk.open(meta_name)
        raw = await meta_file.read(0, meta_file.size())
        try:
            meta = wire.loads(raw)
        except Exception:
            return None
        snap_version, items = 0, []
        if disk.exists(f"storage-{meta['tag']}.snap"):
            f = disk.open(f"storage-{meta['tag']}.snap")
            raw = await f.read(0, f.size())
            try:
                snap = wire.loads(raw)
                snap_version, items = snap["version"], snap["items"]
            except Exception:
                pass  # torn snapshot: the WAL replays everything
        # The update loop must not run while the WAL/snapshot rebuild the
        # store, or freshly-peeked mutations interleave with the replay
        # (round-2 review): defer it until the state is consistent.
        ss = cls(proc, tag=meta["tag"], shard=KeyRange(meta["begin"], meta["end"]),
                 log_view=log_view, net=net, start_version=0, disk=disk,
                 defer_update_loop=True)
        ss.store.load_snapshot(items, snap_version)
        version = snap_version
        for _, payload in await ss.queue.recover():
            v, muts = wire.loads(payload)
            if v <= version:
                continue
            for m in muts:
                ss._apply(m, v)
            version = v
        ss.version = NotifiedVersion(version)
        ss.durable_version = version
        ss.start_update_loop()
        return ss

    # -- write path ----------------------------------------------------------
    def _fire_watches(self, key: Key, new_value: Optional[Value]) -> None:
        """Wake watchers whose expected value no longer matches
        (watchValue:773 triggers on change)."""
        parked = self._watches.get(key)
        if not parked:
            return
        still = []
        for expected, promise in parked:
            if expected != new_value:
                if not promise.is_set:
                    promise.send(new_value)
            else:
                still.append((expected, promise))
        if still:
            self._watches[key] = still
        else:
            del self._watches[key]

    def _apply(self, m: Mutation, version: Version) -> None:
        if m.type == MutationType.SET_VALUE:
            self.store.set(m.param1, m.param2, version)
            self._fire_watches(m.param1, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            self.store.clear_range(m.param1, m.param2, version)
            for k in [k for k in self._watches if m.param1 <= k < m.param2]:
                self._fire_watches(k, None)
        elif m.type in STORAGE_ATOMIC_MUTATIONS:
            existing = self.store.value_at(m.param1, version)
            new = apply_atomic_op(m.type, existing, m.param2)
            self.store.set(m.param1, new, version)
            self._fire_watches(m.param1, new)
        else:
            # Versionstamped mutations must have been rewritten to SET_VALUE
            # by the proxy (transform_versionstamp_mutation) before logging.
            raise error.client_invalid_operation(f"unsupported mutation {m.type}")

    async def update_loop(self) -> None:
        """Pull this server's tag from the tlog forever (update:2340 +
        updateStorage:2585 merged: in-memory apply == durable here). Peeks
        are idempotent, so transport loss (tlog death, partition, timeout)
        just retries; a blocked peek is re-armed every few virtual seconds so
        a partitioned-then-healed link recovers."""
        while True:
            cfg = self.log_view.get()
            if cfg is None:
                await self.log_view.on_change()
                continue
            client = LogSystemClient(self.net, self.proc.address, cfg)
            try:
                reply = await client.peek(self.tag, self.version.get() + 1)
            except error.FDBError:
                # tlog death / partition / generation turnover: re-read the
                # view and retry (peeks are idempotent).
                await delay(0.5, TaskPriority.TLOG_PEEK)
                continue
            applied_any = False
            for v, muts in reply.messages:
                if v <= self.version.get():
                    continue
                for m in muts:
                    self._apply(m, v)
                self.stats.add("mutations", len(muts))
                if self.queue is not None:
                    await self.queue.push(wire.dumps((v, muts)))
                applied_any = True
            if reply.end_version > self.version.get():
                self.version.set(reply.end_version)
                window = self.version.get() - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
                if window > 0:
                    self.store.forget_before(window)
                if self.queue is None:
                    self.durable_version = self.version.get()
                    client.pop(self.tag, self.durable_version)
                elif applied_any or self.version.get() - self.durable_version > 0:
                    # Make the applied window durable before popping the
                    # tlog (updateStorage:2585 -> tLogPop:898 ordering: the
                    # tlog must retain anything we could lose in a crash).
                    await self.queue.commit()
                    self.durable_version = self.version.get()
                    client.pop(self.tag, self.durable_version)
                    snap_limit = 1024 if buggify.buggify() else self.SNAPSHOT_BYTES
                    if self.queue.end_offset - self.queue._begin > snap_limit:
                        await self._write_snapshot()

    # -- read path -----------------------------------------------------------
    async def _wait_for_version(self, version: Version) -> None:
        """reference: waitForVersion, storageserver.actor.cpp:644."""
        if version < self.store.oldest_version:
            raise error.transaction_too_old()
        if version > self.version.get() + MAX_READ_AHEAD_VERSIONS:
            raise error.future_version()
        await self.version.when_at_least(version)

    def _check_shard(self, begin: Key, end: Key) -> None:
        if begin < self.shard.begin or end > self.shard.end:
            raise error.wrong_shard_server()

    async def get_value(self, req: GetValueRequest) -> GetValueReply:
        if not self.shard.contains(req.key):
            raise error.wrong_shard_server()
        await self._wait_for_version(req.version)
        self.stats.add("get_value")
        return GetValueReply(value=self.store.value_at(req.key, req.version))

    async def watch_value(self, req) -> Optional[Value]:
        """Park until key's value differs from req.value; returns the new
        value (reference: watchValue, storageserver.actor.cpp:773). If the
        value already differs at this server's version, fires immediately —
        the client races with writers, exactly like the reference."""
        from ..sim.loop import Promise

        if not self.shard.contains(req.key):
            raise error.wrong_shard_server()
        await self._wait_for_version(req.version)
        current = self.store.value_at(req.key, self.version.get())
        if current != req.value:
            return current
        p = Promise()
        entry = (req.value, p)
        self._watches.setdefault(req.key, []).append(entry)
        # Server-side expiry (reference: watchValue timeout / MAX_WATCHES):
        # a parked watch whose client timed out or died would otherwise sit
        # in _watches forever on a never-changing key.
        from ..sim.actors import any_of

        expiry = delay(WATCH_EXPIRE_SECONDS, TaskPriority.DEFAULT_ENDPOINT)
        idx, _ = await any_of([p.future, expiry])
        if idx == 0:
            # Fire the expiry future now so its callbacks drop; the stale
            # scheduler event retains only the (now ready) future itself.
            if not expiry.is_ready:
                expiry._set(None)
            return p.future.get()
        parked = self._watches.get(req.key)
        if parked is not None:
            try:
                parked.remove(entry)
            except ValueError:
                pass
            if not parked:
                del self._watches[req.key]
        raise error.watch_cancelled()

    async def get_key_values(self, req: GetKeyValuesRequest) -> GetKeyValuesReply:
        self._check_shard(req.begin, req.end)
        await self._wait_for_version(req.version)
        self.stats.add("get_range")
        data, more = self.store.range_at(req.begin, req.end, req.version, req.limit, req.reverse)
        self.stats.add("rows_read", len(data))
        return GetKeyValuesReply(data=data, more=more)
