"""Storage server: MVCC window over a durable ordered engine.

Re-design of fdbserver/storageserver.actor.cpp with the reference's actual
memory/durability split (round-4: the RAM-resident round-3 design is gone):

  * a per-key version-chain overlay (VersionedMap's role) holds ONLY the
    mutations in (durable_version, latest] — the MVCC read window;
  * a durable LSM engine (kvstore.SSTableStore, the KeyValueStoreSQLite
    role) holds the full dataset at exactly durable_version;
  * the update loop pulls the tag (update:2340) and applies to the overlay;
    a durability cycle (updateStorage:2585) writes resolved mutations up to
    latest - storage_durability_lag_versions into the engine, commits,
    advances oldest_version to the new durable_version, drops the covered
    overlay entries, and pops the tlog (tLogPop:898) — so reads at any
    version in [durable, latest] merge engine state with the overlay
    (readRange:936), RAM holds only the window, and crash recovery replays
    only the tag tail above durable, never the whole history.

Reads wait for the requested version (waitForVersion:644) and reject
out-of-window versions with transaction_too_old / future_version.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..core import buggify, error, wire
from ..core.stats import CounterCollection
from ..core.types import (
    MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
    Key,
    KeyRange,
    Mutation,
    MutationType,
    STORAGE_ATOMIC_MUTATIONS,
    Value,
    Version,
    apply_atomic_op,
)
from ..sim.actors import AsyncMutex, AsyncVar, NotifiedVersion
from ..sim.loop import TaskPriority, delay, spawn
from ..sim.network import Endpoint, SimProcess
from .disk_queue import DiskQueue
from .log_system import LogSystemClient, LogSystemConfig
from .messages import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
)

from dataclasses import dataclass


@dataclass
class ShrinkShardRequest:
    """Give up [new_end, shard.end) after a split moved it away."""
    tag: int
    new_end: Key


@dataclass
class ExtendShardRequest:
    """Absorb [shard.end, new_end) from the retiring upper team (merge)."""
    tag: int
    new_end: Key
    fetch_from: List[str]
    fetch_version: Version


GET_VALUE_TOKEN = "storage.getValue"
GET_KEY_VALUES_TOKEN = "storage.getKeyValues"
WATCH_VALUE_TOKEN = "storage.watchValue"
STORAGE_METRICS_TOKEN = "storage.metrics"
SHRINK_SHARD_TOKEN = "storage.shrinkShard"
EXTEND_SHARD_TOKEN = "storage.extendShard"

#: how far ahead of the storage version a read may wait before future_version
#: (reference: storageserver waitForVersion MVCC window)
MAX_READ_AHEAD_VERSIONS = MAX_WRITE_TRANSACTION_LIFE_VERSIONS
#: parked watches expire server-side after this long; below the client's 30s
#: request timeout so a live client re-registers before its entry dies here
WATCH_EXPIRE_SECONDS = 25.0


class VersionedStore:
    """Sorted keys, each with an append-only (version, value|None) chain;
    None = cleared. The logical content at version V is the last entry <= V
    of every chain. Plays VersionedMap's role with plain bisect structures —
    adequate for simulation scale; the Pallas/native engines replace it in
    the storage-engine round."""

    def __init__(self) -> None:
        self._keys: List[Key] = []
        self._chains: Dict[Key, List[Tuple[Version, Optional[Value]]]] = {}
        #: version-stamped range tombstones [(version, begin, end)]: as an
        #: OVERLAY over a durable engine, a clear must mask engine keys the
        #: overlay has no chain for (chains alone were only correct when
        #: they held the whole dataset)
        self._tombs: List[Tuple[Version, Key, Key]] = []
        self.oldest_version: Version = 0

    def _chain(self, key: Key) -> List[Tuple[Version, Optional[Value]]]:
        c = self._chains.get(key)
        if c is None:
            bisect.insort(self._keys, key)
            c = self._chains[key] = []
        return c

    def value_at(self, key: Key, version: Version) -> Optional[Value]:
        c = self._chains.get(key)
        if not c:
            return None
        i = bisect.bisect_right(c, version, key=lambda e: e[0]) - 1
        if i < 0:
            return None
        return c[i][1]

    def set(self, key: Key, value: Value, version: Version) -> None:
        self._chain(key).append((version, value))

    def clear_range(self, begin: Key, end: Key, version: Version) -> None:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            c = self._chains[k]
            if c and c[-1][1] is not None:
                c.append((version, None))
        self._tombs.append((version, begin, end))

    def range_at(
        self, begin: Key, end: Key, version: Version, limit: int, reverse: bool = False
    ) -> Tuple[List[Tuple[Key, Value]], bool]:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = list(reversed(keys))
        out: List[Tuple[Key, Value]] = []
        for i, k in enumerate(keys):
            v = self.value_at(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    return out, i + 1 < len(keys)
        return out, False

    def snapshot_items(self, version: Version) -> List[Tuple[Key, Value]]:
        """Flattened live content at `version` (for durable snapshots)."""
        out: List[Tuple[Key, Value]] = []
        for k in self._keys:
            v = self.value_at(k, version)
            if v is not None:
                out.append((k, v))
        return out

    def load_snapshot(self, items: List[Tuple[Key, Value]], version: Version) -> None:
        self._keys = sorted(k for k, _ in items)
        self._chains = {k: [(version, v)] for k, v in items}
        self._tombs = []
        self.oldest_version = version

    def forget_before(self, version: Version) -> None:
        """Drop history below `version`, keeping each chain's latest entry at
        or below it (the storage analog of removeBefore) — the memory-mode
        rule, where chains ARE the dataset."""
        self.oldest_version = max(self.oldest_version, version)
        self._tombs = [t for t in self._tombs if t[0] > version]
        dead: List[Key] = []
        for k, c in self._chains.items():
            i = bisect.bisect_right(c, version, key=lambda e: e[0]) - 1
            if i > 0:
                del c[: i]
            if len(c) == 1 and c[0][1] is None:
                dead.append(k)
        for k in dead:
            del self._chains[k]
            i = bisect.bisect_left(self._keys, k)
            del self._keys[i]

    def entry_at(self, key: Key, version: Version) -> Optional[Tuple[Version, Optional[Value]]]:
        """Latest overlay fact about `key` at or below `version` — a chain
        entry or a range tombstone, whichever is newer (chains win ties:
        within one version, mutations applied later appended later). None
        means the overlay has nothing to say and the engine answers."""
        ce = None
        c = self._chains.get(key)
        if c:
            i = bisect.bisect_right(c, version, key=lambda e: e[0]) - 1
            if i >= 0:
                ce = c[i]
        if not self._tombs:     # common case: clear-free window
            return ce
        tv = -1
        for v, b, e in self._tombs:
            if v <= version and b <= key < e and v > tv:
                tv = v
        if ce is not None and (tv < 0 or ce[0] >= tv):
            return ce
        if tv >= 0:
            return (tv, None)
        return ce

    def drop_through(self, version: Version) -> None:
        """Durable-mode trim: entries <= `version` are now in the engine, so
        they leave the overlay ENTIRELY (no anchors — the engine at
        durable_version is the base the overlay patches)."""
        self.oldest_version = max(self.oldest_version, version)
        self._tombs = [t for t in self._tombs if t[0] > version]
        dead: List[Key] = []
        for k, c in self._chains.items():
            i = bisect.bisect_right(c, version, key=lambda e: e[0])
            if i > 0:
                del c[:i]
            if not c:
                dead.append(k)
        for k in dead:
            del self._chains[k]
            i = bisect.bisect_left(self._keys, k)
            del self._keys[i]

    def drop_through_range(self, begin: Key, end: Key) -> None:
        """Forget every chain in [begin, end) — the range left this shard
        (split shrink); out-of-shard tombs are harmless and expire with
        the window."""
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._chains[k]
        del self._keys[lo:hi]

    def overlay_keys(self, begin: Key, end: Key) -> List[Key]:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        return self._keys[lo:hi]


#: the engine's private keyspace: strictly above every servable shard end
#: (cluster shards end at b"\xff\xff\xff"), so range reads never see it —
#: the analog of the reference's persistent-format keys in its own KVS
STORAGE_PRIVATE_PREFIX = b"\xff\xff\xff\xff/"
DURABLE_VERSION_KEY = STORAGE_PRIVATE_PREFIX + b"durableVersion"
READ_FLOOR_KEY = STORAGE_PRIVATE_PREFIX + b"readFloor"


class StorageServer:
    #: durability cycle fires when the overlay backlog exceeds this
    #: (memory pressure overrides the version-lag cadence)
    PENDING_BYTES = 1 << 20

    def __init__(
        self,
        proc: SimProcess,
        tag: int,
        shard: KeyRange,
        log_view: AsyncVar,
        net,
        start_version: Version = 0,
        disk=None,
        kvs=None,
        defer_update_loop: bool = False,
    ):
        """`log_view` is an AsyncVar[LogSystemConfig | None]: the current
        log generation to pull from. Recovery re-points it (the worker's
        ServerDBInfo watch), and the update loop follows — the analog of
        the reference storage server tracking the log system through
        ServerDBInfo broadcasts (storageserver.actor.cpp update:2340)."""
        self.proc = proc
        self.tag = tag
        self.shard = shard
        self.net = net
        self.log_view = log_view
        self.store = VersionedStore()
        #: reference: StorageServer::Counters (storageserver.actor.cpp)
        self.stats = CounterCollection("Storage", f"tag{tag}")
        self.version = NotifiedVersion(start_version)
        #: durable (engine-committed) version: the tlog may only be popped
        #: to here, and oldest_version tracks it in durable mode
        self.durable_version: Version = start_version
        #: durable engine (kvstore.SSTableStore) or None = memory mode
        self.kvs = kvs
        #: resolved ops per version awaiting the durability cycle:
        #: [(version, [(0,k,v)|(1,b,e)], bytes)]
        self._pending: List[Tuple[Version, list, int]] = []
        self._pending_bytes = 0
        #: a durability cycle is mid-flight toward this version: reads below
        #: it must not consult the half-mutated engine (see _read_floor)
        self._durabilizing_to: Version = 0
        #: serializes _make_durable: the update loop's durability cycle and
        #: extend_shard's replay flush both scan/trim _pending across
        #: engine-commit awaits
        self._durable_mutex = AsyncMutex()
        #: an extend_shard fetch is in flight for (begin, end, buffer):
        #: tag mutations for the incoming range are buffered here instead
        #: of being dropped by the shard-bounds guard (AddingShard's double
        #: buffer, storageserver.actor.cpp:77)
        self._adding: Optional[Tuple[Key, Key, list]] = None
        #: byte sample (storageserver.actor.cpp:2776 byteSampleApplySet):
        #: each written key is sampled with probability size/FACTOR and
        #: carries weight FACTOR — total bytes and split points come from
        #: the sample, never from scanning the dataset
        from ..core.indexedset import IndexedSet

        #: order-statistic byte sample (flow/IndexedSet.h backing
        #: StorageMetrics): metric sums give the total and the median
        #: split key in O(log n), not a per-poll sort
        self.byte_sample = IndexedSet()
        #: write-bandwidth sample (StorageMetrics' bytesPerKSecond role):
        #: bytes of applied mutations since the last DD poll; the tracker
        #: divides by the poll gap for a rate
        self._bw_bytes: int = 0
        self._bw_last_poll: float = 0.0
        self._disk = disk
        self._update_task = None
        self._tokens = [GET_VALUE_TOKEN, GET_KEY_VALUES_TOKEN, WATCH_VALUE_TOKEN,
                        "storage.stats"]
        proc.register(GET_VALUE_TOKEN, self.get_value)
        proc.register(GET_KEY_VALUES_TOKEN, self.get_key_values)
        #: parked watches: key -> [(expected value, Promise)]
        self._watches: Dict[Key, List] = {}
        proc.register(WATCH_VALUE_TOKEN, self.watch_value)
        from .ratekeeper import STORAGE_QUEUE_INFO_TOKEN, StorageQueueInfo

        async def queue_info(_req):
            return StorageQueueInfo(
                tag=self.tag, version=self.version.get(),
                durable_version=self.durable_version,
                queue_bytes=self._pending_bytes,
            )

        async def stats_req(_req):
            return self.stats.as_dict()

        proc.register("storage.stats", stats_req)
        proc.register(STORAGE_METRICS_TOKEN, self.storage_metrics)
        proc.register(SHRINK_SHARD_TOKEN, self.shrink_shard)
        proc.register(EXTEND_SHARD_TOKEN, self.extend_shard)
        self._tokens += [STORAGE_METRICS_TOKEN, SHRINK_SHARD_TOKEN,
                         EXTEND_SHARD_TOKEN]

        proc.register(STORAGE_QUEUE_INFO_TOKEN, queue_info)
        self._tokens.append(STORAGE_QUEUE_INFO_TOKEN)
        if not defer_update_loop:
            self.start_update_loop()

    def start_update_loop(self) -> None:
        self._update_task = spawn(self.update_loop(), TaskPriority.STORAGE,
                                  name=f"ss-update:{self.tag}")
        self.proc.actors.add(self._update_task)

    def retire(self) -> None:
        """This replica's shard moved away (MoveKeys finish): stop serving,
        stop pulling the tag, drop the disk footprint."""
        for tok in self._tokens:
            self.proc.unregister(tok)
        if self._update_task is not None:
            self._update_task.cancel()
        for parked in self._watches.values():
            for _expected, p in parked:
                if not p.is_set:
                    p.send_error(error.watch_cancelled())
        self._watches.clear()
        if self.kvs is not None:
            self.kvs.destroy()
        if self._disk is not None:
            for suffix in (".meta", ".snap", ".snap.tmp", ".dq", ".dq.tmp"):
                self._disk.delete(self._meta_name() + suffix)

    async def _fetch_range(self, addrs: List[str], begin: Key, end: Key,
                           version: Version,
                           items: Optional[List[Tuple[Key, Value]]] = None) -> None:
        """Paged copy of [begin, end) at `version` from a serving team into
        the engine (durable mode; committed per page) or `items` (memory
        mode), with replica rotation + retries and BUGGIFY mid-copy pauses.
        Shared by fetchKeys and the merge path's extend (one fetch loop, one
        set of semantics)."""
        from ..core.types import key_after

        cb, ce = begin, end
        while cb < ce:
            reply = None
            last: Optional[error.FDBError] = None
            if buggify.buggify():
                # fetchKeys pauses mid-copy: the tag stream must buffer
                await delay(0.25, TaskPriority.FETCH_KEYS)
            for i in range(len(addrs) * 3):
                addr = addrs[i % len(addrs)]
                try:
                    reply = await self.net.request(
                        self.proc.address,
                        Endpoint(addr, GET_KEY_VALUES_TOKEN),
                        GetKeyValuesRequest(begin=cb, end=ce, version=version,
                                            limit=10_000),
                        TaskPriority.FETCH_KEYS, timeout=5.0,
                    )
                    break
                except error.FDBError as e:
                    last = e
                    await delay(0.2, TaskPriority.FETCH_KEYS)
            if reply is None:
                raise last if last is not None else error.connection_failed()
            for k, v in reply.data:
                if self.kvs is not None:
                    self.kvs.set(k, v)
                else:
                    items.append((k, v))
                self._sample_set(k, v)
            if self.kvs is not None:
                await self.kvs.commit()
            if not reply.more or not reply.data:
                break
            cb = key_after(reply.data[-1][0])

    async def fetch_keys(self, addrs: List[str], version: Version) -> None:
        """Populate this fresh replica with its shard's contents at
        `version`, read from the serving team (fetchKeys,
        storageserver.actor.cpp:1777). The AddingShard double buffer is the
        log system itself here: this tag's mutations > `version` are
        already accumulating at the tlogs and the update loop consumes them
        once this snapshot is loaded. In durable mode the copy streams into
        the engine (a retried half-fetch starts from a cleared shard)."""
        if self.kvs is not None:
            self.kvs.clear_range(self.shard.begin, self.shard.end)
        items: List[Tuple[Key, Value]] = []
        await self._fetch_range(addrs, self.shard.begin, self.shard.end,
                                version, items)
        if self.kvs is not None:
            self.kvs.set(DURABLE_VERSION_KEY, wire.dumps(version))
            await self.kvs.commit()
            self.store = VersionedStore()
            self.store.oldest_version = version
        else:
            self.store.load_snapshot(items, version)
        self.version = NotifiedVersion(version)
        self.durable_version = version

    # -- durability ----------------------------------------------------------
    def _meta_name(self) -> str:
        return f"storage-{self.tag}"

    @classmethod
    async def create(cls, proc: SimProcess, tag: int, shard: KeyRange,
                     log_view: AsyncVar, net, disk,
                     start_version: Version = 0,
                     defer_update_loop: bool = False) -> "StorageServer":
        """Fresh durable-mode server: open (or re-open) the engine."""
        from .kvstore import SSTableStore

        kvs = await SSTableStore.open(disk, f"storage-{tag}")
        return cls(proc, tag=tag, shard=shard, log_view=log_view, net=net,
                   start_version=start_version, disk=disk, kvs=kvs,
                   defer_update_loop=defer_update_loop)

    async def persist_initial(self) -> None:
        if self._disk is None:
            return
        meta = self._disk.open(self._meta_name() + ".meta")
        await meta.write(0, wire.dumps({
            "tag": self.tag, "begin": self.shard.begin, "end": self.shard.end,
        }))
        await meta.sync()
        if self.kvs is not None and await self.kvs.get(DURABLE_VERSION_KEY) is None:
            self.kvs.set(DURABLE_VERSION_KEY, wire.dumps(self.durable_version))
            await self.kvs.commit()

    def _purge_pending_outside(self) -> None:
        """Clip every pending durability op to the current shard bounds."""
        b, e = self.shard.begin, self.shard.end
        new_pending = []
        self._pending_bytes = 0
        for v, ops, _nb in self._pending:
            kept = []
            nbytes = 0
            for op in ops:
                if op[0] == 0:
                    if op[2] is None or not (b <= op[1] < e):
                        continue
                    kept.append(op)
                else:
                    cb, ce = max(op[1], b), min(op[2], e)
                    if cb >= ce:
                        continue
                    kept.append((1, cb, ce))
                nbytes += len(kept[-1][1]) + len(kept[-1][2] or b"") + 24
            new_pending.append((v, kept, nbytes))
            self._pending_bytes += nbytes
        self._pending = new_pending

    async def _make_durable(self, target: Version) -> None:
        """updateStorage:2585: push resolved ops <= target into the engine,
        commit (the durability point), advance the MVCC floor, trim the
        overlay, and let the caller pop the tlog."""
        async with self._durable_mutex:
            await self._make_durable_locked(target)

    async def _make_durable_locked(self, target: Version) -> None:
        i = 0
        new_durable = self.durable_version
        for v, _ops, _nb in self._pending:
            if v > target:
                break
            # max, not assignment: extend_shard's buffered replay may have
            # queued versions BELOW the current durable floor (durability
            # advanced during its fetch) — writing them is correct (their
            # keys are in the just-absorbed range, untouched above), but
            # the floor itself must never regress
            new_durable = max(new_durable, v)
            i += 1
        if i == 0:
            return
        # Raise the read floor BEFORE touching the engine: the memtable
        # makes each set visible immediately, so a concurrent read below
        # new_durable falling through to the engine could otherwise observe
        # a higher version's write. Reads past the gate re-check the floor
        # after their engine await (get_value/get_key_values).
        self._durabilizing_to = max(self._durabilizing_to, new_durable)
        self.store.oldest_version = max(self.store.oldest_version, new_durable)
        for v, ops, nbytes in self._pending[:i]:
            for op in ops:
                if op[0] == 0:
                    if op[2] is not None:
                        self.kvs.set(op[1], op[2])
                else:
                    self.kvs.clear_range(op[1], op[2])
            self._pending_bytes -= nbytes
        del self._pending[:i]
        self.kvs.set(DURABLE_VERSION_KEY, wire.dumps(new_durable))
        if buggify.buggify():
            # stall between staging and the engine fsync: reads that
            # awaited across this window must re-check the floor, and a
            # crash here loses the whole staged batch (tlog not yet popped)
            await delay(0.05, TaskPriority.STORAGE)
        await self.kvs.commit()
        self.durable_version = new_durable
        self.store.drop_through(new_durable)

    @classmethod
    async def restore(cls, proc: SimProcess, disk, meta_name: str,
                      log_view: AsyncVar, net) -> Optional["StorageServer"]:
        """Reboot recovery: the engine IS the state at durable_version; the
        update loop replays only the tag tail above it from the tlogs —
        restart cost is the durability lag, never the dataset size."""
        meta_file = disk.open(meta_name)
        raw = await meta_file.read(0, meta_file.size())
        try:
            meta = wire.loads(raw)
        except Exception:
            return None
        from .kvstore import SSTableStore

        kvs = await SSTableStore.open(disk, f"storage-{meta['tag']}")
        raw = await kvs.get(DURABLE_VERSION_KEY)
        durable = wire.loads(raw) if raw is not None else 0
        ss = cls(proc, tag=meta["tag"], shard=KeyRange(meta["begin"], meta["end"]),
                 log_view=log_view, net=net, start_version=durable, disk=disk,
                 kvs=kvs)
        ss.durable_version = durable
        ss.store.oldest_version = durable
        floor = await kvs.get(READ_FLOOR_KEY)
        if floor is not None:
            ss._durabilizing_to = max(ss._durabilizing_to, wire.loads(floor))
        return ss

    # -- write path ----------------------------------------------------------
    def _fire_watches(self, key: Key, new_value: Optional[Value]) -> None:
        """Wake watchers whose expected value no longer matches
        (watchValue:773 triggers on change)."""
        parked = self._watches.get(key)
        if not parked:
            return
        still = []
        for expected, promise in parked:
            if expected != new_value:
                if not promise.is_set:
                    promise.send(new_value)
            else:
                still.append((expected, promise))
        if still:
            self._watches[key] = still
        else:
            del self._watches[key]

    # -- byte sample + DD metrics -------------------------------------------
    def _sample_set(self, key: Key, value: Optional[Value]) -> None:
        from ..core.knobs import SERVER_KNOBS
        from ..sim.loop import current_scheduler

        if value is None:
            self.byte_sample.erase(key)
            return
        size = len(key) + len(value)
        factor = max(1, SERVER_KNOBS.dd_byte_sample_factor)
        # deterministic per seed: the sim RNG drives sampling
        if size >= factor or current_scheduler().rng.random01() < size / factor:
            self.byte_sample.insert(key, max(size, factor))   # replaces
        else:
            self.byte_sample.erase(key)   # re-rolled OUT of the sample

    @property
    def sampled_bytes(self) -> int:
        return self.byte_sample.total()

    def _sample_clear(self, begin: Key, end: Key) -> None:
        self.byte_sample.erase_range(begin, end)

    async def storage_metrics(self, _req) -> dict:
        """Per-shard size estimate, a median split point from the byte
        sample, and the applied-write bandwidth since the last poll (the
        DD tracker's WaitMetrics/SplitMetrics + bytesPerKSecond, reduced
        to polling; reference: StorageMetrics.actor.h)."""
        from ..sim.loop import now as _now

        t = _now()
        gap = max(t - self._bw_last_poll, 1e-6)
        write_bw = self._bw_bytes / gap if self._bw_last_poll else 0.0
        self._bw_bytes = 0
        self._bw_last_poll = t
        split = self.byte_sample.split_key()
        if split is not None and split <= self.shard.begin:
            # a split at the very first key would produce an empty lower
            # half; shard begin is excluded
            split = None
        return {
            "tag": self.tag,
            "begin": self.shard.begin,
            "end": self.shard.end,
            "bytes": self.sampled_bytes,
            "write_bw": write_bw,
            "mutations": self.stats.as_dict().get("mutations", 0),
            "split_key": split,
        }

    # -- shard reshaping (DD split/merge) ------------------------------------
    async def shrink_shard(self, req) -> None:
        """Give up [new_end, end): the upper half moved to a new team
        (split). Data beyond the new bound is dropped everywhere."""
        old_end = self.shard.end
        new_end = req.new_end
        if not (self.shard.begin < new_end <= old_end):
            raise error.client_invalid_operation("shrink bound outside shard")
        self.shard = KeyRange(self.shard.begin, new_end)
        self._sample_clear(new_end, old_end)
        # overlay + engine drop the range; straggler tag mutations for it
        # are discarded by the _apply bounds guard from now on. Ops already
        # APPLIED but not yet durable must drop too — otherwise a later
        # durability cycle resurrects the range in the engine, where a
        # subsequent merge-extend would expose it (pre-shrink values that
        # never saw the clears clipped away by the bounds guard).
        self._purge_pending_outside()
        self.store.clear_range(new_end, old_end, self.version.get())
        self.store.drop_through_range(new_end, old_end)
        if self.kvs is not None:
            self.kvs.clear_range(new_end, old_end)
            await self.kvs.commit()
        if self._disk is not None:
            meta = self._disk.open(self._meta_name() + ".meta")
            await meta.write(0, wire.dumps({
                "tag": self.tag, "begin": self.shard.begin,
                "end": self.shard.end,
            }))
            await meta.sync()

    async def extend_shard(self, req) -> None:
        """Absorb [end, new_end) from `fetch_from` at `fetch_version` (the
        merge path). AddingShard semantics (storageserver.actor.cpp:77):
        this team's tags were added to the upper shard before the fetch, so
        mutations for the incoming range arrive DURING the paged fetch —
        they are buffered (the shard-bounds guard would otherwise drop them
        and the version watermark would advance past them forever) and
        replayed in version order on top of the fetched base, and only then
        does the range join the shard."""
        old_end = self.shard.end
        if not (old_end <= req.new_end):
            raise error.client_invalid_operation("extend bound inside shard")
        if self._adding is not None:
            raise error.client_invalid_operation("extend already in flight")
        buf: list = []
        self._adding = (old_end, req.new_end, buf)
        try:
            if self.kvs is not None:
                # a retried half-fetch must not leave stale rows from the
                # aborted attempt under the fresh snapshot
                self.kvs.clear_range(old_end, req.new_end)
            items: List[Tuple[Key, Value]] = []
            await self._fetch_range(req.fetch_from, old_end, req.new_end,
                                    req.fetch_version, items)
        except BaseException:
            self._adding = None   # master retries; a re-fetch starts clean
            raise
        try:
            if buggify.buggify():
                # widen the fetch-to-replay gap: more tag mutations land in
                # the AddingShard buffer, stressing the replay version merge
                await delay(0.5, TaskPriority.FETCH_KEYS)
            if self.kvs is None:
                # fetched base BEFORE the buffered replay: chains stay monotone
                for k, v in items:
                    self.store.set(k, v, req.fetch_version)
            # Replay buffered mutations above the snapshot version. The
            # buffer may still grow during an atomic op's engine read; the
            # index loop drains the tail too, and _adding stays active
            # throughout so the update loop keeps routing new-range
            # mutations here (an older buffered write can never land after
            # a newer live one).
            per_version: Dict[Version, list] = {}
            i = 0
            while i < len(buf):
                v, m = buf[i]
                i += 1
                if v <= req.fetch_version:
                    continue   # already contained in the fetched snapshot
                op = await self._apply(m, v, unbounded=True)
                if self.kvs is not None:
                    per_version.setdefault(v, []).append(op)
        except BaseException:
            # A dangling buffer would reject every retried extend and eat
            # the incoming range's mutations forever; the retry re-fetches
            # from a cleared engine range and a fresh buffer. (Replayed
            # overlay entries beyond the un-widened shard are invisible to
            # reads and age out with the window.)
            self._adding = None
            raise
        self._adding = None
        self.shard = KeyRange(self.shard.begin, req.new_end)
        # Replayed ops enter the durability pipeline at their versions
        # (merge-sorted into _pending; a same-version entry may already
        # exist from the commit's in-shard portion — the ranges are
        # disjoint, so appending preserves apply semantics).
        for v in sorted(per_version):
            ops = per_version[v]
            nbytes = sum(len(op[1]) + len(op[2] or b"") + 24 for op in ops)
            j = bisect.bisect_left(self._pending, v, key=lambda e: e[0])
            if j < len(self._pending) and self._pending[j][0] == v:
                ev, eops, enb = self._pending[j]
                self._pending[j] = (ev, eops + ops, enb + nbytes)
            else:
                self._pending.insert(j, (v, ops, nbytes))
            self._pending_bytes += nbytes
        if self.kvs is not None and per_version:
            # The replayed versions may already be POPPED from the tlog
            # (in-shard durability advanced during the fetch and popped
            # past them); until they hit the engine they exist only in
            # this process's RAM. Force them durable BEFORE acking the
            # extend — a crash after the ack must not lose them, and the
            # master retires the donor team on our ack.
            await self._make_durable(max(per_version))
        # The fetched rows reflect fetch_version; reads below it in the new
        # range would see the future. Raise the floor (persisted so a
        # restart keeps the gate) — retries get fresher read versions. The
        # floor must be engine-durable BEFORE the extended meta syncs: a
        # crash between the two would otherwise restore the wider shard
        # with the stale floor and serve the fetch snapshot to reads below
        # fetch_version (read-from-the-future).
        self._durabilizing_to = max(self._durabilizing_to, req.fetch_version)
        if self.kvs is not None:
            self.kvs.set(READ_FLOOR_KEY, wire.dumps(self._durabilizing_to))
            await self.kvs.commit()
        if self._disk is not None:
            meta = self._disk.open(self._meta_name() + ".meta")
            await meta.write(0, wire.dumps({
                "tag": self.tag, "begin": self.shard.begin,
                "end": self.shard.end,
            }))
            await meta.sync()

    async def _existing_value(self, key: Key, version: Version) -> Optional[Value]:
        """Current value for an atomic-op read-modify-write: overlay entry
        if one covers `version`, else the durable engine (doEagerReads'
        read-before-apply, storageserver.actor.cpp:1370)."""
        e = self.store.entry_at(key, version)
        if e is not None:
            return e[1]
        if self.kvs is not None:
            return await self.kvs.get(key)
        return None

    async def _apply(self, m: Mutation, version: Version,
                     unbounded: bool = False) -> Optional[tuple]:
        """Apply one mutation to the overlay; returns the RESOLVED op for
        the durability cycle ((0, k, v) set / (1, b, e) clear) — atomic ops
        are materialized here, so the engine only ever stores values.
        `unbounded` (extend_shard's buffered replay) skips the shard-bounds
        guard: the mutation's range joins the shard only when the replay
        finishes, but its keys are already clipped to the incoming range."""
        if not unbounded and self._adding is not None:
            ab, ae, buf = self._adding
            if m.type == MutationType.CLEAR_RANGE:
                cb, ce = max(m.param1, ab), min(m.param2, ae)
                if cb < ce:
                    buf.append((version, Mutation(
                        type=MutationType.CLEAR_RANGE, param1=cb, param2=ce)))
                # fall through: the in-shard portion still applies below
            elif ab <= m.param1 < ae:
                buf.append((version, m))
                return (0, b"", None)
        if m.type == MutationType.SET_VALUE:
            if not unbounded and not self.shard.contains(m.param1):
                return (0, b"", None)    # straggler for a shrunk-away range
            self.store.set(m.param1, m.param2, version)
            self._bw_bytes += len(m.param1) + len(m.param2)
            self._sample_set(m.param1, m.param2)
            self._fire_watches(m.param1, m.param2)
            return (0, m.param1, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            if unbounded:
                b, e = m.param1, m.param2
            else:
                b = max(m.param1, self.shard.begin)
                e = min(m.param2, self.shard.end)
            if b >= e:
                return (0, b"", None)
            self.store.clear_range(b, e, version)
            self._bw_bytes += len(b) + len(e)
            self._sample_clear(b, e)
            for k in [k for k in self._watches if b <= k < e]:
                self._fire_watches(k, None)
            return (1, b, e)
        elif m.type in STORAGE_ATOMIC_MUTATIONS:
            if not unbounded and not self.shard.contains(m.param1):
                return (0, b"", None)
            existing = await self._existing_value(m.param1, version)
            new = apply_atomic_op(m.type, existing, m.param2)
            self.store.set(m.param1, new, version)
            self._bw_bytes += len(m.param1) + len(new)
            self._sample_set(m.param1, new)
            self._fire_watches(m.param1, new)
            return (0, m.param1, new)
        else:
            # Versionstamped mutations must have been rewritten to SET_VALUE
            # by the proxy (transform_versionstamp_mutation) before logging.
            raise error.client_invalid_operation(f"unsupported mutation {m.type}")

    async def update_loop(self) -> None:
        """Pull this server's tag from the tlog forever (update:2340), then
        run the durability cycle (updateStorage:2585). Peeks are idempotent,
        so transport loss (tlog death, partition, timeout) just retries; a
        blocked peek is re-armed every few virtual seconds so a
        partitioned-then-healed link recovers."""
        while True:
            cfg = self.log_view.get()
            if cfg is None:
                await self.log_view.on_change()
                continue
            client = LogSystemClient(self.net, self.proc.address, cfg)
            try:
                reply = await client.peek(self.tag, self.version.get() + 1)
            except error.FDBError:
                # tlog death / partition / generation turnover: re-read the
                # view and retry (peeks are idempotent).
                await delay(0.5, TaskPriority.TLOG_PEEK)
                continue
            for v, muts in reply.messages:
                if v <= self.version.get():
                    continue
                if self.kvs is None:
                    for m in muts:
                        await self._apply(m, v)
                else:
                    ops = []
                    nbytes = 0
                    for m in muts:
                        op = await self._apply(m, v)
                        ops.append(op)
                        nbytes += len(op[1]) + len(op[2] or b"") + 24
                    self._pending.append((v, ops, nbytes))
                    self._pending_bytes += nbytes
                self.stats.add("mutations", len(muts))
            if reply.end_version > self.version.get():
                self.version.set(reply.end_version)
                if self.kvs is None:
                    window = self.version.get() - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
                    if window > 0:
                        self.store.forget_before(window)
                    self.durable_version = self.version.get()
                    client.pop(self.tag, self.durable_version)
                else:
                    from ..core.knobs import SERVER_KNOBS

                    lag = SERVER_KNOBS.storage_durability_lag_versions
                    if buggify.buggify():
                        lag = 100  # an eager flusher stresses the floor
                    target = self.version.get() - lag
                    limit = 1024 if buggify.buggify() else self.PENDING_BYTES
                    if self._pending_bytes > limit:
                        # memory pressure: drain everything applied so far
                        target = self.version.get()
                    if self._pending and target >= self._pending[0][0]:
                        await self._make_durable(target)
                    client.pop(self.tag, self.durable_version)

    # -- read path -----------------------------------------------------------
    async def _wait_for_version(self, version: Version) -> None:
        """reference: waitForVersion, storageserver.actor.cpp:644."""
        if version < self._read_floor():
            raise error.transaction_too_old()
        if version > self.version.get() + MAX_READ_AHEAD_VERSIONS:
            raise error.future_version()
        await self.version.when_at_least(version)

    def _check_shard(self, begin: Key, end: Key) -> None:
        if begin < self.shard.begin or end > self.shard.end:
            raise error.wrong_shard_server()

    async def _value_at(self, key: Key, version: Version) -> Optional[Value]:
        """Overlay entry at `version` wins; otherwise the durable engine
        (the getValueQ read merge, storageserver.actor.cpp:697)."""
        e = self.store.entry_at(key, version)
        if e is not None:
            return e[1]
        if self.kvs is not None:
            return await self.kvs.get(key)
        return None

    async def _range_at(
        self, begin: Key, end: Key, version: Version, limit: int, reverse: bool
    ) -> Tuple[List[Tuple[Key, Value]], bool]:
        """Range read merging the durable engine with the overlay
        (readRange:936: disk + VersionedMap). Overlay entries at or below
        `version` override engine values (None = cleared); overlay keys
        whose chains start after `version` defer to the engine."""
        if self.kvs is None:
            return self.store.range_at(begin, end, version, limit, reverse)
        from ..core.types import key_after

        okeys = self.store.overlay_keys(begin, end)
        if reverse:
            okeys = list(reversed(okeys))
        oi = 0
        out: List[Tuple[Key, Value]] = []
        cb, ce = begin, end
        exhausted = False
        while len(out) < limit and not exhausted:
            page, more = await self.kvs.get_range(cb, ce, max(limit - len(out), 16),
                                                  reverse=reverse)
            if not more:
                exhausted = True
            elif page:
                if reverse:
                    ce = page[-1][0]
                else:
                    cb = key_after(page[-1][0])
            for k, v in page:
                # overlay keys strictly before k (in scan order) are
                # overlay-only: emit their value if live at `version`
                while oi < len(okeys) and (
                    (okeys[oi] < k) if not reverse else (okeys[oi] > k)
                ):
                    e = self.store.entry_at(okeys[oi], version)
                    if e is not None and e[1] is not None:
                        out.append((okeys[oi], e[1]))
                        if len(out) >= limit:
                            break
                    oi += 1
                if len(out) >= limit:
                    break
                if oi < len(okeys) and okeys[oi] == k:
                    oi += 1
                # the overlay (chain entry OR range tombstone <= version)
                # overrides the engine value; otherwise the engine answers
                e = self.store.entry_at(k, version)
                if e is not None:
                    if e[1] is not None:
                        out.append((k, e[1]))
                else:
                    out.append((k, v))
                if len(out) >= limit:
                    break
            if len(out) >= limit:
                return out, True
        # trailing overlay-only keys past the engine's last page
        while oi < len(okeys) and len(out) < limit:
            e = self.store.entry_at(okeys[oi], version)
            if e is not None and e[1] is not None:
                out.append((okeys[oi], e[1]))
            oi += 1
        return out, oi < len(okeys)

    def _read_floor(self) -> Version:
        """Oldest readable version: the MVCC floor plus any durability
        cycle currently mutating the engine. Reads that awaited across a
        cycle must re-check (and retry via transaction_too_old) rather than
        return values a higher version wrote."""
        return max(self.store.oldest_version, self._durabilizing_to)

    async def get_value(self, req: GetValueRequest) -> GetValueReply:
        if not self.shard.contains(req.key):
            raise error.wrong_shard_server()
        await self._wait_for_version(req.version)
        self.stats.add("get_value")
        value = await self._value_at(req.key, req.version)
        if req.version < self._read_floor():
            raise error.transaction_too_old()
        return GetValueReply(value=value)

    async def watch_value(self, req) -> Optional[Value]:
        """Park until key's value differs from req.value; returns the new
        value (reference: watchValue, storageserver.actor.cpp:773). If the
        value already differs at this server's version, fires immediately —
        the client races with writers, exactly like the reference."""
        from ..sim.loop import Promise

        if not self.shard.contains(req.key):
            raise error.wrong_shard_server()
        await self._wait_for_version(req.version)
        current = await self._value_at(req.key, self.version.get())
        if current != req.value:
            return current
        p = Promise()
        entry = (req.value, p)
        self._watches.setdefault(req.key, []).append(entry)
        # Server-side expiry (reference: watchValue timeout / MAX_WATCHES):
        # a parked watch whose client timed out or died would otherwise sit
        # in _watches forever on a never-changing key.
        from ..sim.actors import any_of

        expiry = delay(WATCH_EXPIRE_SECONDS, TaskPriority.DEFAULT_ENDPOINT)
        idx, _ = await any_of([p.future, expiry])
        if idx == 0:
            # Fire the expiry future now so its callbacks drop; the stale
            # scheduler event retains only the (now ready) future itself.
            if not expiry.is_ready:
                expiry._set(None)
            return p.future.get()
        parked = self._watches.get(req.key)
        if parked is not None:
            try:
                parked.remove(entry)
            except ValueError:
                pass
            if not parked:
                del self._watches[req.key]
        raise error.watch_cancelled()

    async def get_key_values(self, req: GetKeyValuesRequest) -> GetKeyValuesReply:
        self._check_shard(req.begin, req.end)
        await self._wait_for_version(req.version)
        self.stats.add("get_range")
        data, more = await self._range_at(req.begin, req.end, req.version,
                                          req.limit, req.reverse)
        if req.version < self._read_floor():
            raise error.transaction_too_old()
        self.stats.add("rows_read", len(data))
        return GetKeyValuesReply(data=data, more=more)
