"""Static cluster assembly — the reference's seed mode.

Builds a complete minimum transaction system inside a Simulator: one master,
one proxy, N resolvers (pluggable conflict engines), one tlog, M storage
servers with a static uniform shard map. The analog of SimulatedCluster's
setup + masterserver.actor.cpp:325 newSeedServers, before dynamic
recruitment/recovery land in a later round.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..core.types import KeyRange
from ..core.keyshard import KeyShardMap
from ..ops.oracle import OracleConflictEngine
from ..pipeline.service import PipelineConfig
from ..sim.actors import AsyncVar
from ..sim.network import Endpoint
from ..sim.simulator import Simulator
from ..client.database import Database
from .log_system import LogSystemConfig
from .master import GET_COMMIT_VERSION_TOKEN, Master
from .proxy import Proxy, ProxyConfig
from .resolver import RESOLVE_TOKEN, Resolver
from .storage import StorageServer
from .tlog import TLog


@dataclass
class ClusterConfig:
    n_resolvers: int = 1
    n_proxies: int = 1
    n_storage: int = 2          # number of key-range shards
    storage_replication: int = 1  # replicas per shard (the team size K)
    #: () -> conflict engine; default is the reference-exact oracle. Pass
    #: lambda: JaxConflictEngine(...) for the TPU path.
    engine_factory: Callable = OracleConflictEngine
    start_version: int = 1
    #: pipelined resolver service (pipeline/service.py): depth/pack/device
    #: knobs; None keeps the serial one-batch-at-a-time resolver
    resolver_pipeline: Optional["PipelineConfig"] = None
    #: proxy commit batch cap (None = proxy default); size it to the
    #: resolver kernel's compiled T when pipelining
    max_commit_batch: Optional[int] = None
    #: proxy in-flight commit window (None = unbounded)
    commit_pipeline_window: Optional[int] = None
    #: wrap each resolver's engine in the device-fault supervisor
    #: (fault/resilient.py: watchdog, retries, CPU-oracle failover). Off in
    #: the static assembly so engine-level unit suites see the raw engine.
    resilient_resolver: bool = False


class Cluster:
    """Handles to every role plus client factories."""

    def __init__(self, sim: Simulator, cfg: ClusterConfig):
        self.sim = sim
        self.cfg = cfg
        sv = cfg.start_version

        self.master_proc = sim.new_process("master")
        self.master = Master(self.master_proc, start_version=sv)

        self.tlog_proc = sim.new_process("tlog")
        self.tlog = TLog(self.tlog_proc, start_version=sv)
        self.log_config = LogSystemConfig(
            gen_id=(0, 0), tlogs=((self.tlog_proc.address, ""),), start_version=sv
        )
        self.log_view = AsyncVar(self.log_config)

        def make_engine():
            from ..fault import maybe_wrap

            return maybe_wrap(cfg.engine_factory(), cfg)

        self.resolver_shards = KeyShardMap.uniform(cfg.n_resolvers)
        self.resolver_procs = [sim.new_process(f"resolver{i}") for i in range(cfg.n_resolvers)]
        self.resolvers = [
            Resolver(p, make_engine(), start_version=sv, index=i,
                     pipeline=cfg.resolver_pipeline)
            for i, p in enumerate(self.resolver_procs)
        ]

        self.storage_shards = KeyShardMap.uniform(cfg.n_storage)
        # Teams: shard s is stored by `storage_replication` replicas, each
        # its own process + tag (DataDistribution's replica teams reduced
        # to a static seed assignment).
        self.storage_procs = []
        self.storages: List[StorageServer] = []
        self.storage_teams: List[List[tuple]] = []
        tag = 0
        for s in range(cfg.n_storage):
            begin = self.storage_shards.begins[s]
            end = self.storage_shards.span_end(s) or b"\xff\xff\xff"
            team = []
            for r in range(cfg.storage_replication):
                p = sim.new_process(f"storage{s}.{r}")
                self.storage_procs.append(p)
                self.storages.append(
                    StorageServer(
                        p,
                        tag=tag,
                        shard=KeyRange(begin, end),
                        log_view=self.log_view,
                        net=sim.net,
                        start_version=sv,
                    )
                )
                team.append((tag, p.address))
                tag += 1
            self.storage_teams.append(team)

        from .proxy import COMMITTED_VERSION_TOKEN

        self.proxy_procs = [sim.new_process(f"proxy{i}")
                            for i in range(max(1, cfg.n_proxies))]
        peer_grv_eps = [Endpoint(p.address, COMMITTED_VERSION_TOKEN)
                        for p in self.proxy_procs]
        self.proxies = [
            Proxy(
                p,
                sim.net,
                ProxyConfig(
                    master_ep=Endpoint(self.master_proc.address, GET_COMMIT_VERSION_TOKEN),
                    resolver_eps=[Endpoint(q.address, RESOLVE_TOKEN) for q in self.resolver_procs],
                    resolver_shards=self.resolver_shards,
                    log_config=self.log_config,
                    storage_teams=self.storage_teams,
                    storage_shards=self.storage_shards,
                    peer_grv_eps=peer_grv_eps,
                    max_commit_batch=cfg.max_commit_batch,
                    commit_pipeline_window=cfg.commit_pipeline_window,
                ),
                start_version=sv,
            )
            for p in self.proxy_procs
        ]
        self.proxy_proc = self.proxy_procs[0]
        self.proxy = self.proxies[0]
        self._n_clients = 0

    def new_client(self) -> Database:
        self._n_clients += 1
        proc = self.sim.new_process(f"client{self._n_clients}")
        return Database(self.sim.net, proc.address,
                        [p.address for p in self.proxy_procs])


def build_cluster(seed: int = 0, cfg: Optional[ClusterConfig] = None) -> Cluster:
    sim = Simulator(seed)
    return Cluster(sim, cfg or ClusterConfig())


# -- dynamic cluster: coordinators + workers + recovery ----------------------


@dataclass
class DynamicClusterConfig:
    """The recruitment-era cluster shape (reference: DatabaseConfiguration —
    `configure proxies=1 resolvers=2 logs=2`)."""

    n_coordinators: int = 3
    n_workers: int = 5
    n_tlogs: int = 2
    n_resolvers: int = 2
    n_proxies: int = 1
    n_storage: int = 2          # number of key-range shards
    storage_replication: int = 1  # replicas per shard (team size)
    #: per-tag tlog replication factor; 0 = every replica holds every tag
    log_replication_factor: int = 0
    #: resolutionBalancing trigger floor (rows per poll window) and poll
    #: interval; tests lower them to provoke rebalances quickly
    rebalance_min_rows: int = 200
    rebalance_interval: float = 5.0
    #: multi-region (reference: region config in SimulatedCluster:706,
    #: satellite tlogs + DC-preference recovery): workers/coordinators are
    #: spread over n_dcs datacenters; satellite_logs tlog replicas are
    #: placed OUTSIDE the primary DC (synchronous satellites — dc0's total
    #: loss still leaves a complete log); recruitment prefers the DC with
    #: the most live workers, so losing the primary FAILS OVER
    n_dcs: int = 1
    satellite_logs: int = 0
    #: extra one-way latency between processes in different DCs (the
    #: DCN tier; 0 keeps single-region runs byte-identical)
    inter_dc_latency: float = 0.0
    #: pipelined resolver service knobs as a plain dict (wire-friendly for
    #: real-mode recruitment): PipelineConfig(**resolver_pipeline); None
    #: keeps the serial resolver
    resolver_pipeline: Optional[dict] = None
    #: wrap recruited resolver engines in the device-fault supervisor
    #: (fault/resilient.py). Default ON: every dynamic spec — attrition,
    #: clogging, recovery — then exercises the watchdog/retry/failover
    #: machinery for free through its buggify sites, and a misbehaving
    #: device degrades instead of wedging the commit pipeline.
    resilient_resolver: bool = True
    engine_factory: Callable = OracleConflictEngine


import dataclasses as _dc

from ..core import wire as _wire

# wire codec for real-mode recruitment (InitializeMasterRequest carries the
# cluster shape): every field EXCEPT the process-local engine factory — the
# receiving worker constructs engines from its OWN factory
_wire.register_adapter(
    DynamicClusterConfig, "DynamicClusterConfig",
    to_state=lambda c: {f.name: getattr(c, f.name)
                        for f in _dc.fields(c) if f.name != "engine_factory"},
    # filter to known fields: a payload from a version with fields this
    # binary dropped must decode, not TypeError (the record path's
    # schema-evolution contract, wire.py)
    from_state=lambda d: DynamicClusterConfig(
        **{k: v for k, v in d.items()
           if k in {f.name for f in _dc.fields(DynamicClusterConfig)}}),
)


class DynamicCluster:
    """A full bootable cluster: coordinator processes and worker processes
    with boot functions, so kills + reboots re-run the real boot path
    (simulatedFDBDRebooter, SimulatedCluster.actor.cpp:198). Everything
    else — CC election, master recovery, role recruitment — happens through
    the same protocols a live cluster would use."""

    def __init__(self, sim: Simulator, cfg: Optional[DynamicClusterConfig] = None):
        from .coordination import CoordinationServer
        from .worker import Worker

        self.sim = sim
        self.cfg = cfg or DynamicClusterConfig()

        ndc = max(1, self.cfg.n_dcs)
        if self.cfg.inter_dc_latency:
            sim.net.inter_dc_latency = self.cfg.inter_dc_latency

        def coord_boot(simu, proc):
            async def go():
                await CoordinationServer.create(proc, simu.disk_for(proc.address))
            return go()

        # coordinator MAJORITY outside the primary DC (dc0) for ANY
        # coordinator count: losing dc0 entirely must leave a coordination
        # quorum (the reference's 3-site coordinator guidance). The first
        # floor(n/2)+1 coordinators round-robin over the non-primary DCs;
        # the remainder live in dc0.
        nco = self.cfg.n_coordinators
        if ndc > 1:
            maj = nco // 2 + 1
            non_primary = [f"dc{d}" for d in range(1, ndc)]
            coord_dcs = [non_primary[i % len(non_primary)] for i in range(maj)]
            coord_dcs += ["dc0"] * (nco - maj)
        else:
            coord_dcs = ["dc0"] * nco
        self.coord_procs = [
            sim.new_process(f"coord{i}", boot_fn=coord_boot, dc_id=coord_dcs[i])
            for i in range(nco)
        ]
        self.coordinators = [p.address for p in self.coord_procs]

        def worker_boot(index):
            def boot(simu, proc):
                async def go():
                    Worker(simu, proc, self.coordinators, self.cfg.engine_factory,
                           cc_priority=index, cluster_cfg=self.cfg)
                return go()
            return boot

        self.worker_procs = [
            sim.new_process(f"worker{i}", boot_fn=worker_boot(i),
                            dc_id=f"dc{i % ndc}")
            for i in range(self.cfg.n_workers)
        ]
        self._n_clients = 0

    def new_client(self) -> Database:
        self._n_clients += 1
        proc = self.sim.new_process(f"client{self._n_clients}")
        return Database(self.sim.net, proc.address, coordinator_addrs=self.coordinators)


def build_dynamic_cluster(seed: int = 0, cfg: Optional[DynamicClusterConfig] = None) -> DynamicCluster:
    sim = Simulator(seed)
    return DynamicCluster(sim, cfg)
