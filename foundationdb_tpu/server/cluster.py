"""Static cluster assembly — the reference's seed mode.

Builds a complete minimum transaction system inside a Simulator: one master,
one proxy, N resolvers (pluggable conflict engines), one tlog, M storage
servers with a static uniform shard map. The analog of SimulatedCluster's
setup + masterserver.actor.cpp:325 newSeedServers, before dynamic
recruitment/recovery land in a later round.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..core.types import KeyRange
from ..ops.host_engine import KeyShardMap
from ..ops.oracle import OracleConflictEngine
from ..sim.network import Endpoint
from ..sim.simulator import Simulator
from ..client.database import Database
from . import tlog as tlog_mod
from .master import Master
from .proxy import Proxy, ProxyConfig
from .resolver import Resolver
from .storage import StorageServer
from .tlog import TLog


@dataclass
class ClusterConfig:
    n_resolvers: int = 1
    n_storage: int = 2
    #: () -> conflict engine; default is the reference-exact oracle. Pass
    #: lambda: JaxConflictEngine(...) for the TPU path.
    engine_factory: Callable = OracleConflictEngine
    start_version: int = 1


class Cluster:
    """Handles to every role plus client factories."""

    def __init__(self, sim: Simulator, cfg: ClusterConfig):
        self.sim = sim
        self.cfg = cfg
        sv = cfg.start_version

        self.master_proc = sim.new_process("master")
        self.master = Master(self.master_proc, start_version=sv)

        self.tlog_proc = sim.new_process("tlog")
        self.tlog = TLog(self.tlog_proc, start_version=sv)

        self.resolver_shards = KeyShardMap.uniform(cfg.n_resolvers)
        self.resolver_procs = [sim.new_process(f"resolver{i}") for i in range(cfg.n_resolvers)]
        self.resolvers = [
            Resolver(p, cfg.engine_factory(), start_version=sv) for p in self.resolver_procs
        ]

        self.storage_shards = KeyShardMap.uniform(cfg.n_storage)
        self.storage_procs = [sim.new_process(f"storage{i}") for i in range(cfg.n_storage)]
        self.storages: List[StorageServer] = []
        for i, p in enumerate(self.storage_procs):
            begin = self.storage_shards.begins[i]
            end = self.storage_shards.span_end(i) or b"\xff\xff\xff"
            self.storages.append(
                StorageServer(
                    p,
                    tag=i,
                    shard=KeyRange(begin, end),
                    tlog_commit_ep=Endpoint(self.tlog_proc.address, tlog_mod.COMMIT_TOKEN),
                    tlog_peek_ep=Endpoint(self.tlog_proc.address, tlog_mod.PEEK_TOKEN),
                    tlog_pop_ep=Endpoint(self.tlog_proc.address, tlog_mod.POP_TOKEN),
                    net=sim.net,
                    start_version=sv,
                )
            )

        self.proxy_proc = sim.new_process("proxy")
        self.proxy = Proxy(
            self.proxy_proc,
            sim.net,
            ProxyConfig(
                master_addr=self.master_proc.address,
                resolver_addrs=[p.address for p in self.resolver_procs],
                resolver_shards=self.resolver_shards,
                tlog_addr=self.tlog_proc.address,
                storage_addrs=[p.address for p in self.storage_procs],
                storage_shards=self.storage_shards,
            ),
            start_version=sv,
        )
        self._n_clients = 0

    def new_client(self) -> Database:
        self._n_clients += 1
        proc = self.sim.new_process(f"client{self._n_clients}")
        return Database(self.sim.net, proc.address, [self.proxy_proc.address])


def build_cluster(seed: int = 0, cfg: Optional[ClusterConfig] = None) -> Cluster:
    sim = Simulator(seed)
    return Cluster(sim, cfg or ClusterConfig())
