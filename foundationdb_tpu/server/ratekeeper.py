"""Ratekeeper: cluster-wide admission control.

Re-design of fdbserver/Ratekeeper.actor.cpp (updateRate:251-430): poll
every storage server's queue state, translate the worst lag into a
transactions-per-second limit, and meter GRV release at the proxies
(getRate loop, MasterProxyServer.actor.cpp:86). The sim analog of the
reference's storage-queue-bytes signal is the MVCC version lag (how far a
storage server trails the committed version) plus its un-snapshotted WAL
depth — both directly bound crash-recovery work and window health.

Runs as an actor inside the master's epoch (the reference's 6.0 ratekeeper
lives under the master's data distribution); proxies fetch the limit on a
short interval and release that many GRVs per second, queueing the rest —
back-pressure reaches clients as start-transaction latency, exactly like
the reference.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..core import error
from ..core.knobs import SERVER_KNOBS
from ..sim.loop import TaskPriority, delay
from ..sim.network import Endpoint

STORAGE_QUEUE_INFO_TOKEN = "storage.queueInfo"
GET_RATE_INFO_TOKEN = "master.getRateInfo"

#: version lag at which throttling reaches zero admission (the MVCC window
#: itself is 5e6; throttle to a halt well before readable versions fall out)
MAX_STORAGE_LAG_VERSIONS = 4_000_000
#: lag at which throttling begins
TARGET_STORAGE_LAG_VERSIONS = 1_000_000


@dataclass
class StorageQueueInfo:
    tag: int
    version: int
    durable_version: int
    #: overlay bytes not yet in the durable engine (the reference's
    #: storage-queue-bytes signal; durable VERSION lag is by design
    #: ~storage_durability_lag_versions and is NOT a throttling signal)
    queue_bytes: int = 0


@dataclass
class TLogQueueInfo:
    """One tlog replica's queue state (the reference's TLogQueueInfo):
    in-memory index bytes + the spill-tier debt a slow consumer built."""

    mem_bytes: int = 0
    spilled_version: int = 0
    version: int = 0


@dataclass
class GetRateInfoRequest:
    proxy_id: str


@dataclass
class GetRateInfoReply:
    tps_limit: float
    #: adaptive commit-batch cap from the resolvers' budget batchers
    #: (min across resolvers; None = no resolver reported a target) — the
    #: proxy's commit batcher clamps its batch size to it, closing the
    #: resolver -> ratekeeper -> proxy sizing loop
    commit_batch_target: Optional[int] = None


class TenantAdmission:
    """Per-tenant token-bucket admission control for the commit path.

    The ratekeeper publishes ONE cluster rate; under multi-tenant skewed
    load that lets a single hot tenant consume the whole budget and queue
    everyone else past the p99 SLO (docs/real_cluster.md). This splits the
    published rate into per-tenant buckets by weight: `admit()` spends a
    token or answers False, and the proxy (server/proxy.py) turns False
    into the typed `transaction_throttled` error — a microsecond rejection
    the client retries with backoff, instead of a multi-second queue entry
    that blows the budget for every tenant.

    Fed from the same ratekeeper reply the proxy already fetches
    (GetRateInfoReply.tps_limit, refreshed by `set_rate`); the wall-clock
    chaos server (real/nemesis.py) feeds it a degraded-fraction rate the
    same way the ratekeeper's resolver-health signal scales tps_limit.
    Clock-agnostic: callers pass `now` (sim virtual time or monotonic)."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 burst_s: Optional[float] = None):
        #: tenant -> relative weight (unknown tenants weigh 1.0)
        self.weights: Dict[str, float] = dict(weights or {})
        self.burst_s = float(burst_s if burst_s is not None
                             else SERVER_KNOBS.tenant_admission_burst_s)
        #: total admission rate across tenants (inf = admission off)
        self.rate_limit: float = float("inf")
        #: tenant -> [tokens, last_refill_t]
        self._buckets: Dict[str, List[float]] = {}
        self.admitted: Dict[str, int] = {}
        self.rejected: Dict[str, int] = {}
        self.refunded: Dict[str, int] = {}

    def set_rate(self, tps_limit: float) -> None:
        self.rate_limit = float(tps_limit)

    def tenant_rate(self, tenant: str) -> float:
        """This tenant's share: weight-proportional slice of the published
        rate across every tenant seen so far (plus this one)."""
        if self.rate_limit == float("inf"):
            return float("inf")
        active = set(self._buckets) | {tenant}
        total_w = sum(self.weights.get(t, 1.0) for t in active)
        return self.rate_limit * self.weights.get(tenant, 1.0) / max(total_w, 1e-9)

    def admit(self, tenant: str, now: float) -> bool:
        rate = self.tenant_rate(tenant)
        if rate == float("inf"):
            self.admitted[tenant] = self.admitted.get(tenant, 0) + 1
            return True
        bucket = self._buckets.get(tenant)
        burst = max(1.0, rate * self.burst_s)
        if bucket is None:
            bucket = self._buckets[tenant] = [burst, now]
        tokens, last = bucket
        tokens = min(burst, tokens + rate * max(0.0, now - last))
        if tokens >= 1.0:
            bucket[0], bucket[1] = tokens - 1.0, now
            self.admitted[tenant] = self.admitted.get(tenant, 0) + 1
            return True
        bucket[0], bucket[1] = tokens, now
        self.rejected[tenant] = self.rejected.get(tenant, 0) + 1
        return False

    def refund(self, tenant: str) -> None:
        """Return one admission token. The conflict scheduler's pre-abort
        (pipeline/scheduler.py) refuses an admitted transaction before it
        consumes ANY device capacity — the retry the client sends with a
        fresh read version must not be double-charged against the
        tenant's bucket, or pre-abort would convert conflict aborts into
        throttle rejections instead of commits."""
        rate = self.tenant_rate(tenant)
        if rate == float("inf"):
            return
        bucket = self._buckets.get(tenant)
        if bucket is None:
            return
        burst = max(1.0, rate * self.burst_s)
        bucket[0] = min(burst, bucket[0] + 1.0)
        self.refunded[tenant] = self.refunded.get(tenant, 0) + 1

    def as_dict(self) -> dict:
        return {
            "rate_limit": (None if self.rate_limit == float("inf")
                           else round(self.rate_limit, 1)),
            "burst_s": self.burst_s,
            "admitted": dict(self.admitted),
            "rejected": dict(self.rejected),
            "refunded": dict(self.refunded),
        }


class Ratekeeper:
    """Polls storage; computes the cluster TPS limit (rateKeeper:509)."""

    def __init__(self, net, src_addr: str, storage_tags, committed_version_fn,
                 log_config=None, resolver_eps=None):
        self.net = net
        self.src = src_addr
        self.storage_tags = storage_tags            # (tag, begin, end, addr)
        self.committed_version_fn = committed_version_fn
        #: LogSystemConfig of the serving generation: the tlog queue-depth
        #: signal polls its replicas (None = storage signals only)
        self.log_config = log_config
        #: resolver engine-health endpoints (resolver.health tokens): a
        #: degraded conflict engine — retrying under its watchdog, failed
        #: over to the CPU oracle, or on probation (fault/resilient.py) —
        #: is a throttle signal: its service rate is a fraction of the
        #: device's, and piling on admissions just deepens the queue
        self.resolver_eps = list(resolver_eps or [])
        self.tps_limit: float = float(SERVER_KNOBS.max_transactions_per_second)
        self.worst_lag: int = 0
        #: True while NO storage poll has answered in the last update window:
        #: worst_lag is then a reset placeholder, not a live measurement —
        #: status/telemetry must show signal loss, never a frozen reading
        self.lag_stale: bool = True
        self.worst_tlog_bytes: int = 0
        self.resolver_degraded: bool = False
        #: True while any resolver reports a FIRING burn-rate alert from
        #: its cluster watchdog (core/watchdog.py): the SLO error budget
        #: is being spent faster than sustainable, so admission slows
        #: before the breach lands — the same consume-point the online
        #: resharding controller drives from (server/reshard.py)
        self.burn_alert_firing: bool = False
        #: True while any resolver reports an online reshard in flight
        #: (server/reshard.py ReshardController via engine health): the
        #: handoff is spending host/device time on pre-copy + delta
        #: transfer, and the frozen range briefly queues its batches —
        #: clamp admission by `reshard_tps_fraction` until cutover so the
        #: recovery work stays bounded, exactly like the degraded clamp
        self.reshard_in_flight: bool = False
        #: resolver address -> last reported engine health state
        self.resolver_health: Dict[str, str] = {}
        #: resolver address -> last reported telemetry fragment (engine
        #: perf counters, batcher EWMAs — server/resolver.py engine_health):
        #: rides the same health poll into the master status fragment and
        #: the CC status document (docs/observability.md)
        self.resolver_telemetry: Dict[str, dict] = {}
        #: min adaptive batch target across budget-batching resolvers
        #: (pipeline/service.py target_batch_txns); None = none reported
        self.commit_batch_target: Optional[int] = None

    async def run(self) -> None:
        from ..core import buggify

        interval = SERVER_KNOBS.ratekeeper_update_interval
        while True:
            tick = interval
            if buggify.buggify():
                # stale ratekeeper: proxies run on an old budget while the
                # cluster state moves — metering must degrade gracefully
                tick = interval * 10
            await delay(tick, TaskPriority.RATEKEEPER)
            # concurrent polls: a partition must cost ONE timeout window,
            # not one per unreachable replica (the published rate would
            # otherwise go stale by many update intervals)
            s_futs = [
                self.net.request(
                    self.src, Endpoint(addr, STORAGE_QUEUE_INFO_TOKEN), None,
                    TaskPriority.RATEKEEPER, timeout=interval * 2,
                )
                for _tag, _b, _e, addr in self.storage_tags
            ]
            t_futs = []
            if self.log_config is not None:
                t_futs = [
                    self.net.request(
                        self.src, self.log_config.ep(rep, "queue_info"),
                        None, TaskPriority.RATEKEEPER, timeout=interval * 2,
                    )
                    for rep in self.log_config.tlogs
                ]
            r_futs = [
                (ep, self.net.request(
                    self.src, ep, None, TaskPriority.RATEKEEPER,
                    timeout=interval * 2,
                ))
                for ep in self.resolver_eps
            ]
            infos: List[StorageQueueInfo] = []
            for f in s_futs:
                try:
                    infos.append(await f)
                except error.FDBError:
                    continue  # an unreachable storage doesn't stall the loop
            tlog_infos: List[TLogQueueInfo] = []
            for f in t_futs:
                try:
                    tlog_infos.append(await f)
                except error.FDBError:
                    continue
            resolver_infos: List[dict] = []
            for ep, f in r_futs:
                try:
                    h = await f
                except error.FDBError:
                    # a dead resolver is recovery's problem, not a throttle
                    # signal — but its last health state must not linger in
                    # the status map as if freshly measured, and neither may
                    # its telemetry fragment (stale perf counters rendered
                    # as live would mislead exactly during the incident)
                    self.resolver_health[ep.address] = "unreachable"
                    self.resolver_telemetry.pop(ep.address, None)
                    continue
                self.resolver_health[ep.address] = h.get("state", "healthy")
                if h.get("telemetry"):
                    self.resolver_telemetry[ep.address] = h["telemetry"]
                resolver_infos.append(h)
            targets = [h["target_batch_txns"] for h in resolver_infos
                       if h.get("target_batch_txns") is not None]
            # min wins: a commit batch crosses every resolver, so it must
            # fit the slowest engine's in-budget bucket
            self.commit_batch_target = min(targets) if targets else None
            self.tps_limit = self._update_rate(infos, tlog_infos, resolver_infos)

    def _update_rate(self, infos: List[StorageQueueInfo],
                     tlog_infos: Optional[List[TLogQueueInfo]] = None,
                     resolver_infos: Optional[List[dict]] = None) -> float:
        """The core of updateRate (Ratekeeper.actor.cpp:251-430): four
        signals, the minimum wins —
          * worst storage FETCH lag (committed - applied: how far the
            update loop trails the tlogs);
          * worst storage un-durable queue depth (overlay bytes above the
            engine);
          * worst TLOG queue depth (in-memory index bytes — a tlog buried
            in spill debt is exactly the signal the spill tier used to
            hide from admission control; round-4 weak #8);
          * resolver engine health (fault/resilient.py): a degraded
            conflict engine serves through watchdog retries or the CPU
            failover oracle at a fraction of device throughput — admit
            accordingly until it swaps back.
        Durable-version lag is NOT a signal — the durability cycle trails
        by storage_durability_lag_versions on purpose."""
        max_tps = float(SERVER_KNOBS.max_transactions_per_second)
        tps_lag = tps_bytes = max_tps
        if not infos:
            # Every storage poll timed out: the prior worst_lag no longer
            # corresponds to any live measurement. Reset it and mark it
            # stale rather than publishing a frozen reading.
            self.worst_lag = 0
            self.lag_stale = True
        else:       # no storage reply = no storage signal; the TLOG signal
            #         below must still bite (a buried tlog during a storage
            #         partition is exactly when admission must slow)
            self.lag_stale = False
            committed = self.committed_version_fn()
            self.worst_lag = max(max(0, committed - i.version) for i in infos)
            if self.worst_lag >= MAX_STORAGE_LAG_VERSIONS:
                tps_lag = 1.0  # never fully zero: progress drains the lag
            elif self.worst_lag > TARGET_STORAGE_LAG_VERSIONS:
                frac = (MAX_STORAGE_LAG_VERSIONS - self.worst_lag) / (
                    MAX_STORAGE_LAG_VERSIONS - TARGET_STORAGE_LAG_VERSIONS
                )
                tps_lag = max(1.0, max_tps * frac)
            worst_bytes = max(i.queue_bytes for i in infos)
            target_b = SERVER_KNOBS.target_storage_queue_bytes
            spring_b = SERVER_KNOBS.spring_storage_queue_bytes
            if worst_bytes >= target_b:
                tps_bytes = 1.0
            elif worst_bytes > target_b - spring_b:
                frac = (target_b - worst_bytes) / spring_b
                tps_bytes = max(1.0, max_tps * frac)
        tps_tlog = max_tps
        if tlog_infos:
            self.worst_tlog_bytes = max(t.mem_bytes for t in tlog_infos)
            target_t = SERVER_KNOBS.target_tlog_queue_bytes
            spring_t = max(target_t // 2, 1)
            if self.worst_tlog_bytes >= target_t:
                tps_tlog = 1.0
            elif self.worst_tlog_bytes > target_t - spring_t:
                frac = (target_t - self.worst_tlog_bytes) / spring_t
                tps_tlog = max(1.0, max_tps * frac)
        tps_resolver = max_tps
        tps_watchdog = max_tps
        tps_reshard = max_tps
        if resolver_infos is not None:
            self.resolver_degraded = any(h.get("degraded") for h in resolver_infos)
            if self.resolver_degraded:
                tps_resolver = max(
                    1.0, max_tps * SERVER_KNOBS.resolver_degraded_tps_fraction)
            # watchdog burn-rate clamp (core/watchdog.py): a firing
            # multi-window burn alert means the SLO budget is being spent
            # at an unsustainable rate RIGHT NOW — shed load while the
            # budget still has headroom, exactly like the degraded signal
            # but driven by measured SLO spend instead of engine health
            self.burn_alert_firing = any(h.get("burn_alert_firing")
                                         for h in resolver_infos)
            if self.burn_alert_firing:
                tps_watchdog = max(
                    1.0, max_tps * SERVER_KNOBS.watchdog_burn_tps_fraction)
            # reshard clamp (server/reshard.py): while a range handoff is
            # in flight the published rate scales by reshard_tps_fraction
            # — pre-copy/delta transfer work and the frozen range's brief
            # queueing must not compete with full-rate admission; the
            # clamp lifts on the same poll that reports the cutover
            self.reshard_in_flight = any(h.get("reshard_in_flight")
                                         for h in resolver_infos)
            if self.reshard_in_flight:
                tps_reshard = max(
                    1.0, max_tps * SERVER_KNOBS.reshard_tps_fraction)
        return min(tps_lag, tps_bytes, tps_tlog, tps_resolver, tps_watchdog,
                   tps_reshard)

    async def get_rate_info(self, req: GetRateInfoRequest) -> GetRateInfoReply:
        from ..core import buggify

        limit = self.tps_limit
        if buggify.buggify():
            # brief artificial squeeze: the GRV back-pressure path (queued
            # starts, latency instead of errors) runs even on idle clusters
            limit = max(1.0, limit / 100)
        return GetRateInfoReply(tps_limit=limit,
                                commit_batch_target=self.commit_batch_target)
