"""Master server: the epoch recovery state machine + version authority.

Re-design of fdbserver/masterserver.actor.cpp (masterCore:1104,
recoverFrom:728, readTransactionSystemState:586). One master owns one
epoch; recovery is:

  READING_CSTATE   read DBCoreState from a coordinator majority
  LOCKING_CSTATE   write it back with a bumped recovery_count — the
                   exclusive-generation write kills any straggling older
                   master's future cstate writes (split-brain guard)
  LOCKING_TLOGS    lock the previous tlog generation; recovery version =
                   min(end) over the locked set (log_system.lock_generation)
  RECRUITING       fetch the un-popped window from a locked replica, then
                   construct the new generation on chosen workers: K tlogs
                   (seeded with the copy), resolvers, the version authority,
                   one proxy; on an empty cstate also seed storage servers
                   (newSeedServers, masterserver.actor.cpp:325)
  WRITING_CSTATE   write the new generation into the coordinated state —
                   the durable hand-over; only after this may clients see
                   the new proxies
  FULLY_RECOVERED  announce ServerDBInfo to the CC, retire generations
                   older than ours on all workers, then watch every
                   recruited role host: any failure ends this master, and
                   the CC recruits a successor (the whole transaction
                   subsystem is disposable, SURVEY.md §5)

The first commit version of a post-crash epoch jumps past the MVCC window
(Master.first_jump) so pre-recovery read snapshots resolve TOO_OLD at the
fresh resolvers instead of silently missing lost conflict history.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from ..core import buggify, error
from ..core.trace import TraceEvent
from ..core.keyshard import KeyShardMap
from ..sim.actors import all_of, any_of
from ..sim.loop import TaskPriority, delay, spawn
from ..sim.network import Endpoint
from . import system_keys
from .coordinated_state import CoordinatedState, DBCoreState, LogGenerationInfo
from .log_system import LogSystemConfig, fetch_recovery_data, lock_generation
from .master import GET_COMMIT_VERSION_TOKEN, Master, RECOVERY_VERSION_JUMP
from .proxy import ProxyConfig, teams_from_storage_tags
from .ratekeeper import GET_RATE_INFO_TOKEN, Ratekeeper
from .resolver import RESOLVE_TOKEN, RESOLVER_HEALTH_TOKEN
from .wait_failure import WAIT_FAILURE_TOKEN, wait_failure_client
from .worker import (
    InitializeProxyRequest,
    InitializeResolverRequest,
    InitializeStorageRequest,
    InitializeTLogRequest,
    INIT_PROXY_TOKEN,
    INIT_RESOLVER_TOKEN,
    INIT_STORAGE_TOKEN,
    INIT_TLOG_TOKEN,
    RETIRE_TOKEN,
    RETIRE_STORAGE_TOKEN,
    RetireGenerationsRequest,
    RetireStorageRequest,
    ServerDBInfo,
)

RECRUIT_TIMEOUT = 2.0
MOVE_SHARD_TOKEN = "master.moveShard"


def _teams_by_begin(storage_tags) -> "Dict[bytes, List[Tuple[int, str]]]":
    out: Dict[bytes, List[Tuple[int, str]]] = {}
    for tag, b, _e, addr in storage_tags:
        out.setdefault(b, []).append((tag, addr))
    return {b: sorted(t) for b, t in out.items()}


@dataclass
class MoveShardRequest:
    """Management request: move the whole shard beginning at `begin` to a
    team on `dest_workers` (one replica each). reference: MoveKeys
    (MoveKeys.actor.cpp:821), driven here by a DD-lite under the master."""

    begin: bytes
    dest_workers: List[str]


EXCLUDE_TOKEN = "master.exclude"


@dataclass
class ExcludeServersRequest:
    """ManagementAPI excludeServers (ManagementAPI.actor.cpp): drain every
    shard replica off `addresses` by moving affected shards to spare
    workers; with exclude=False, re-admit the addresses as move targets."""

    addresses: List[str]
    exclude: bool = True


class MasterServer:
    def __init__(self, worker, req):
        self.worker = worker
        self.net = worker.net
        self.proc = worker.proc
        self.coords = req.coordinator_addrs
        self.workers = list(req.worker_addrs)
        self.salt = req.salt
        self.cc_addr = req.cc_addr
        self.cfg = req.cluster_cfg
        #: addr -> (machine_id, dc_id) for policy-driven team placement
        self.localities = dict(getattr(req, "worker_localities", None) or {})
        self.master: Optional[Master] = None

    def _state(self, s: str, **details) -> None:
        ev = TraceEvent("MasterRecoveryState", id=self.salt).detail("State", s)
        for k, v in details.items():
            ev.detail(k, v)
        ev.log()

    def _init_role(self, addr: str, token: str, req):
        """Future of the role's Initialize reply (awaitable or all_of-able)."""
        return self.net.request(
            self.proc.address, Endpoint(addr, token), req,
            TaskPriority.CLUSTER_CONTROLLER, timeout=RECRUIT_TIMEOUT,
        )

    async def run(self) -> None:
        try:
            await self._recover_and_serve()
        except error.FDBError as e:
            TraceEvent("MasterTerminated", id=self.salt).detail("Reason", e.name).log()
            if self.master is not None:
                self.master.unregister()
            # Falling out ends the role; the worker unregisters our
            # wait-failure token and the CC recruits a successor.

    async def _move_shard(self, req: MoveShardRequest, dd, dd_db, log_client,
                          cstate, ratekeeper):
        """MoveKeys v0 (MoveKeys.actor.cpp:821 reduced to whole shards):

          1. commit keyServers(begin) = old team + new tags — proxies drain
             this through the metadata stream and start double-tagging the
             range, so the log buffers the destinations' history;
          2. recruit destination replicas, which fetchKeys a snapshot at a
             read version taken AFTER step 1 and then drain their tag;
          3. commit keyServers(begin) = new team — reads/writes flip;
          4. persist the new map in cstate (the recovery authority), then
             retire the old replicas and their tags.
        A crash before (4) recovers with the OLD map: the old team was
        never retired, and dd_init prunes the orphaned destinations."""
        tags = dd["storage_tags"]
        team = sorted((t, a) for (t, b, _e, a) in tags if b == req.begin)
        if not team:
            raise error.client_invalid_operation(f"no shard begins at {req.begin!r}")
        end = next(e for (_t, b, e, _a) in tags if b == req.begin)
        dests = list(req.dest_workers)
        if len(dests) != len(team) or len(set(dests)) != len(dests):
            raise error.client_invalid_operation("need one distinct dest per replica")
        busy_addrs = {a for (_t, _b, _e, a) in tags}
        if any(d in busy_addrs for d in dests):
            raise error.client_invalid_operation("dest already hosts storage")
        if any(d in dd["excluded"] for d in dests):
            raise error.client_invalid_operation("dest is excluded")
        next_tag = dd["next_tag"]                 # monotone allocator:
        dd["next_tag"] += len(dests)              # unique across CONCURRENT
        new_team = [(next_tag + i, d) for i, d in enumerate(dests)]
        TraceEvent("MoveShardStart", id=self.salt).detail(
            "Begin", req.begin).detail("NewTeam", str(new_team)).log()

        # (1) old + new tags: destinations' history starts accumulating
        async def ph1(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(req.begin),
                   system_keys.encode_key_servers(team, tuple(t for t, _ in new_team)))
        await dd_db.run(ph1)

        try:
            # (2) fetch version AFTER (1): the tag stream covers all newer
            tr = dd_db.create_transaction()
            v0 = await tr.get_read_version()
            await all_of([
                self.net.request(
                    self.proc.address, Endpoint(d, INIT_STORAGE_TOKEN),
                    InitializeStorageRequest(
                        tag=nt, begin=req.begin, end=end,
                        fetch_from=[a for _t, a in team], fetch_version=v0,
                    ),
                    TaskPriority.MOVE_KEYS, timeout=60.0,
                )
                for nt, d in new_team
            ])

            # (3) flip
            async def ph2(tr):
                tr.set_access_system_keys()
                tr.set(system_keys.key_servers_key(req.begin),
                       system_keys.encode_key_servers(new_team))
            await dd_db.run(ph2)
        except error.FDBError:
            # Roll back (1): stop double-tagging, retire half-built
            # destinations and their tags. If the rollback commit itself
            # fails, the next epoch's dd_init reseeds keyServers from
            # cstate and prunes the orphans — the backstop.
            TraceEvent("MoveShardAbort", id=self.salt).detail("Begin", req.begin).log()

            async def rollback(tr):
                tr.set_access_system_keys()
                tr.set(system_keys.key_servers_key(req.begin),
                       system_keys.encode_key_servers(team))
            await dd_db.run(rollback)
            for nt, d in new_team:
                self.net.one_way(self.proc.address, Endpoint(d, RETIRE_STORAGE_TOKEN),
                                 RetireStorageRequest(tags=(nt,)),
                                 TaskPriority.MOVE_KEYS)
                log_client.pop(nt, -1)
            raise

        # (4) durable authority + cleanup
        await self._publish_tags(dd, cstate, ratekeeper, lambda cur: (
            [(t, b, e, a) for (t, b, e, a) in cur if b != req.begin]
            + [(nt, req.begin, end, d) for nt, d in new_team]
        ))
        for t, a in team:
            self.net.one_way(self.proc.address, Endpoint(a, RETIRE_STORAGE_TOKEN),
                             RetireStorageRequest(tags=(t,)),
                             TaskPriority.MOVE_KEYS)
            log_client.pop(t, -1)
        TraceEvent("MoveShardDone", id=self.salt).detail("Begin", req.begin).log()
        return {"begin": req.begin, "team": new_team}

    async def _publish_tags(self, dd, cstate, ratekeeper, transform) -> None:
        """Persist a storage-map change in cstate (the recovery authority)
        and fan the new map out to ratekeeper + the CC status document.
        `transform(cur_tags) -> new_tags` is applied to the CURRENT map
        UNDER the publish mutex: concurrent relocations of disjoint shards
        compose instead of overwriting each other's publishes (a
        precomputed list would lose whichever write landed first)."""
        from dataclasses import replace
        from .cluster_controller import CC_MASTER_RECOVERED_TOKEN

        async with dd["publish_mutex"]:
            new_tags = sorted(transform(list(dd["storage_tags"])))
            dd["cstate_val"] = replace(dd["cstate_val"],
                                       storage_tags=tuple(new_tags))
            await cstate.set_exclusive(dd["cstate_val"])
            dd["storage_tags"][:] = new_tags
            ratekeeper.storage_tags = list(new_tags)
            dd["info"] = replace(dd["info"], storage_tags=tuple(new_tags),
                                 dd_version=dd["info"].dd_version + 1)
            self.net.one_way(self.proc.address,
                             Endpoint(self.cc_addr, CC_MASTER_RECOVERED_TOKEN),
                             dd["info"], TaskPriority.CLUSTER_CONTROLLER)

    async def _split_shard(self, begin, split_key, dests, dd, dd_db,
                           log_client, cstate, ratekeeper):
        """DD shard split (DataDistributionTracker's shardSplitter +
        MoveKeys combined): the team keeps [begin, split_key); a fresh team
        is recruited for [split_key, end) — double-tagged, fetched at a
        post-split read version, flipped, then the old replicas SHRINK."""
        from .storage import SHRINK_SHARD_TOKEN, ShrinkShardRequest

        tags = dd["storage_tags"]
        team = sorted((t, a) for (t, b, _e, a) in tags if b == begin)
        if not team:
            raise error.client_invalid_operation(f"no shard begins at {begin!r}")
        end = next(e for (_t, b, e, _a) in tags if b == begin)
        if not (begin < split_key < end):
            raise error.client_invalid_operation("split key outside shard")
        next_tag = dd["next_tag"]                 # monotone allocator
        dd["next_tag"] += len(dests)
        new_team = [(next_tag + i, d) for i, d in enumerate(dests)]
        TraceEvent("ShardSplitStart", id=self.salt).detail(
            "Begin", begin).detail("SplitKey", split_key).log()

        async def ph1(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(split_key),
                   system_keys.encode_key_servers(
                       team, tuple(t for t, _ in new_team)))
        await dd_db.run(ph1)
        try:
            tr = dd_db.create_transaction()
            v0 = await tr.get_read_version()
            await all_of([
                self.net.request(
                    self.proc.address, Endpoint(d, INIT_STORAGE_TOKEN),
                    InitializeStorageRequest(
                        tag=nt, begin=split_key, end=end,
                        fetch_from=[a for _t, a in team], fetch_version=v0,
                    ),
                    TaskPriority.MOVE_KEYS, timeout=60.0,
                )
                for nt, d in new_team
            ])

            async def ph2(tr):
                tr.set_access_system_keys()
                tr.set(system_keys.key_servers_key(split_key),
                       system_keys.encode_key_servers(new_team))
            await dd_db.run(ph2)
        except error.FDBError:
            TraceEvent("ShardSplitAbort", id=self.salt).detail("Begin", begin).log()

            async def rollback(tr):
                tr.set_access_system_keys()
                tr.set(system_keys.key_servers_key(split_key),
                       system_keys.encode_key_servers([]))   # drop boundary
            await dd_db.run(rollback)
            for nt, d in new_team:
                self.net.one_way(self.proc.address, Endpoint(d, RETIRE_STORAGE_TOKEN),
                                 RetireStorageRequest(tags=(nt,)),
                                 TaskPriority.MOVE_KEYS)
                log_client.pop(nt, -1)
            raise

        # durable authority BEFORE shrinking: a crash after this point
        # recovers with the split map and both teams intact
        await self._publish_tags(dd, cstate, ratekeeper, lambda cur: (
            [(t, b, split_key if b == begin else e, a)
             for (t, b, e, a) in cur]
            + [(nt, split_key, end, d) for nt, d in new_team]
        ))
        await all_of([
            self.net.request(
                self.proc.address, Endpoint(a, SHRINK_SHARD_TOKEN),
                ShrinkShardRequest(tag=t, new_end=split_key),
                TaskPriority.MOVE_KEYS, timeout=10.0,
            )
            for t, a in team
        ])
        TraceEvent("ShardSplitDone", id=self.salt).detail(
            "Begin", begin).detail("SplitKey", split_key).log()
        return {"begin": begin, "split_key": split_key, "new_team": new_team}

    async def _merge_shards(self, begin1, begin2, dd, dd_db, log_client,
                            cstate, ratekeeper):
        """DD shard merge (shardMerger): the lower team absorbs the upper
        range — double-tag the upper shard with the lower team's tags,
        EXTEND the lower replicas (fetch at a post-tag version), remove the
        boundary, retire the upper team."""
        from .storage import EXTEND_SHARD_TOKEN, ExtendShardRequest

        tags = dd["storage_tags"]
        team1 = sorted((t, a) for (t, b, _e, a) in tags if b == begin1)
        team2 = sorted((t, a) for (t, b, _e, a) in tags if b == begin2)
        if not team1 or not team2:
            raise error.client_invalid_operation("merge shards not found")
        end1 = next(e for (_t, b, e, _a) in tags if b == begin1)
        end2 = next(e for (_t, b, e, _a) in tags if b == begin2)
        if end1 != begin2:
            raise error.client_invalid_operation("shards not adjacent")
        TraceEvent("ShardMergeStart", id=self.salt).detail(
            "Begin", begin1).detail("Upper", begin2).log()

        async def ph1(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(begin2),
                   system_keys.encode_key_servers(
                       team2, tuple(t for t, _ in team1)))
        await dd_db.run(ph1)
        tr = dd_db.create_transaction()
        v0 = await tr.get_read_version()
        await all_of([
            self.net.request(
                self.proc.address, Endpoint(a, EXTEND_SHARD_TOKEN),
                ExtendShardRequest(tag=t, new_end=end2,
                                   fetch_from=[a2 for _t2, a2 in team2],
                                   fetch_version=v0),
                TaskPriority.MOVE_KEYS, timeout=60.0,
            )
            for t, a in team1
        ])

        async def ph2(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(begin2),
                   system_keys.encode_key_servers([]))   # remove boundary
        await dd_db.run(ph2)

        await self._publish_tags(dd, cstate, ratekeeper, lambda cur: (
            [(t, b, end2 if b == begin1 else e, a)
             for (t, b, e, a) in cur if b != begin2]
        ))
        for t, a in team2:
            self.net.one_way(self.proc.address, Endpoint(a, RETIRE_STORAGE_TOKEN),
                             RetireStorageRequest(tags=(t,)),
                             TaskPriority.MOVE_KEYS)
            log_client.pop(t, -1)
        TraceEvent("ShardMergeDone", id=self.salt).detail("Begin", begin1).log()
        return {"begin": begin1, "end": end2}

    async def _grow_team(self, begin, dest, dd, dd_db, log_client, cstate,
                         ratekeeper) -> None:
        """Add one replica to the shard at `begin` (the replication fixer's
        move toward a raised \\xff/conf/replication): double-tag via
        keyServers, fetch at a post-tag version, flip to the full team —
        the MoveKeys recruit half without a retire half."""
        from .storage import SHRINK_SHARD_TOKEN  # noqa: F401  (parity import)

        tags = dd["storage_tags"]
        team = sorted((t, a) for (t, b, _e, a) in tags if b == begin)
        if not team:
            raise error.client_invalid_operation(f"no shard begins at {begin!r}")
        end = next(e for (_t, b, e, _a) in tags if b == begin)
        nt = dd["next_tag"]                       # monotone allocator
        dd["next_tag"] += 1
        TraceEvent("TeamGrowStart", id=self.salt).detail(
            "Begin", begin).detail("Dest", dest).log()

        async def ph1(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(begin),
                   system_keys.encode_key_servers(team, (nt,)))
        await dd_db.run(ph1)
        try:
            tr = dd_db.create_transaction()
            v0 = await tr.get_read_version()
            await self.net.request(
                self.proc.address, Endpoint(dest, INIT_STORAGE_TOKEN),
                InitializeStorageRequest(
                    tag=nt, begin=begin, end=end,
                    fetch_from=[a for _t, a in team], fetch_version=v0,
                ),
                TaskPriority.MOVE_KEYS, timeout=60.0,
            )

            async def ph2(tr2):
                tr2.set_access_system_keys()
                tr2.set(system_keys.key_servers_key(begin),
                        system_keys.encode_key_servers(team + [(nt, dest)]))
            await dd_db.run(ph2)
        except error.FDBError:
            TraceEvent("TeamGrowAbort", id=self.salt).detail("Begin", begin).log()

            async def rollback(tr2):
                tr2.set_access_system_keys()
                tr2.set(system_keys.key_servers_key(begin),
                        system_keys.encode_key_servers(team))
            await dd_db.run(rollback)
            self.net.one_way(self.proc.address, Endpoint(dest, RETIRE_STORAGE_TOKEN),
                             RetireStorageRequest(tags=(nt,)),
                             TaskPriority.MOVE_KEYS)
            log_client.pop(nt, -1)
            raise
        await self._publish_tags(
            dd, cstate, ratekeeper,
            lambda cur: list(cur) + [(nt, begin, end, dest)])
        TraceEvent("TeamGrowDone", id=self.salt).detail("Begin", begin).log()

    async def _shrink_team(self, begin, dd, dd_db, log_client, cstate,
                           ratekeeper) -> None:
        """Drop the shard's highest-tag replica (a lowered replication
        factor): flip keyServers to the smaller team, publish, retire."""
        tags = dd["storage_tags"]
        team = sorted((t, a) for (t, b, _e, a) in tags if b == begin)
        if len(team) <= 1:
            raise error.client_invalid_operation("cannot shrink below one replica")
        victim_t, victim_a = team[-1]
        keep = team[:-1]

        async def ph(tr):
            tr.set_access_system_keys()
            tr.set(system_keys.key_servers_key(begin),
                   system_keys.encode_key_servers(keep))
        await dd_db.run(ph)
        await self._publish_tags(
            dd, cstate, ratekeeper,
            lambda cur: [t for t in cur
                         if not (t[0] == victim_t and t[1] == begin)])
        self.net.one_way(self.proc.address, Endpoint(victim_a, RETIRE_STORAGE_TOKEN),
                         RetireStorageRequest(tags=(victim_t,)),
                         TaskPriority.MOVE_KEYS)
        log_client.pop(victim_t, -1)
        TraceEvent("TeamShrinkDone", id=self.salt).detail(
            "Begin", begin).detail("Victim", victim_a).log()

    async def _recover_and_serve(self) -> None:
        cfg = self.cfg
        # -- READING_CSTATE / LOCKING_CSTATE ---------------------------------
        self._state("reading_cstate")
        cstate = CoordinatedState(self.net, self.proc.address, self.coords, self.salt)
        prev: Optional[DBCoreState] = await cstate.read()
        first_boot = prev is None
        prev = prev or DBCoreState()
        rc = prev.recovery_count + 1
        self._state("locking_cstate", RecoveryCount=rc)
        if buggify.buggify():
            # gap between reading and locking the cstate: a competing
            # master can slip its own lock in — ours must then lose cleanly
            await delay(0.3, TaskPriority.CLUSTER_CONTROLLER)
        await cstate.set_exclusive(replace(prev, recovery_count=rc))

        # -- LOCKING_TLOGS: end the previous epoch ---------------------------
        preload: Dict[int, list] = {}
        preload_popped: Dict[int, int] = {}
        if prev.generations:
            old_cfg: LogSystemConfig = prev.generations[-1].config
            self._state("locking_tlogs", OldGen=str(old_cfg.gen_id))
            while True:
                try:
                    recovery_version, locked_reps = await lock_generation(
                        self.net, self.proc.address, old_cfg
                    )
                    # durability oracle: the recovery version must cover
                    # every fully-acked push to the generation we locked
                    # (sim_validation.h:20-50)
                    from ..sim import validation as sim_validation

                    sim_validation.check_restored_version(
                        old_cfg.gen_id, recovery_version)
                    preload, preload_popped = await fetch_recovery_data(
                        self.net, self.proc.address, old_cfg, locked_reps,
                        recovery_version,
                    )
                    break
                except error.FDBError:
                    # Below the tag-coverage lock quorum: some tag's
                    # un-popped window is unrecoverable until a subset
                    # member returns. Wait, not guess.
                    await delay(1.0, TaskPriority.CLUSTER_CONTROLLER)
            first_jump = RECOVERY_VERSION_JUMP
        else:
            recovery_version = 1
            first_jump = 0
        if buggify.buggify():
            # stretch LOCKING->RECRUITING: competing masters and worker
            # failures race the recruitment window harder
            await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
        self._state("recruiting", RecoveryVersion=recovery_version)

        # -- RECRUITING ------------------------------------------------------
        # Role counts: the committed configuration (DatabaseConfiguration,
        # mirrored into cstate by the conf watcher) overrides the boot-time
        # cluster shape — `configure proxies=3` etc. apply HERE, at the
        # next recovery after the change committed.
        from .management import conf_int

        conf = dict(prev.conf)
        n_tlogs = conf_int(conf, b"logs", cfg.n_tlogs)
        n_resolvers = conf_int(conf, b"resolvers", cfg.n_resolvers)
        conf_proxies = conf_int(conf, b"proxies", getattr(cfg, "n_proxies", 1))
        log_repl = conf_int(conf, b"log_replication",
                            getattr(cfg, "log_replication_factor", 0))
        storage_repl = conf_int(conf, b"replication",
                                max(1, getattr(cfg, "storage_replication", 1)))
        # Storage is stateful: keep it on dedicated workers and recruit the
        # disposable transaction roles on the rest (the reference's
        # process-class fitness, reduced to storage-vs-stateless).
        alive = [w for w in self.workers if not self.net.monitor.is_failed(w)]
        n_storage_workers = cfg.n_storage * storage_repl
        if first_boot or not (prev.storage_tags if prev else ()):
            storage_workers = sorted(alive)[-n_storage_workers:]
        else:
            storage_workers = sorted({t[3] for t in prev.storage_tags})
        # Multi-region (SimulatedCluster:706 region config): the PRIMARY
        # DC is wherever the most live workers are — when dc0 dies
        # wholesale, the next recovery recruits the transaction system in
        # the surviving DC (DC-preference failover); satellites below keep
        # the log reachable across that flip.
        def dc_of(w: str) -> str:
            loc = self.localities.get(w)
            return loc[1] if loc else "dc0"

        txn_pool = [w for w in alive if w not in storage_workers] or alive
        by_dc: Dict[str, List[str]] = {}
        for w in sorted(txn_pool):
            by_dc.setdefault(dc_of(w), []).append(w)
        if not by_dc:
            # typed failure the recovery loop retries — an IndexError here
            # would crash the master actor instead
            raise error.recruitment_failed("no live workers")
        primary_dc = sorted(by_dc, key=lambda d: (-len(by_dc[d]), d))[0]
        primary_workers = by_dc[primary_dc]
        workers = primary_workers + [w for d in sorted(by_dc)
                                     if d != primary_dc for w in by_dc[d]]
        gen_id = (rc, self.salt)
        suffix = f":{rc}.{self.salt}"

        def pick(n: int, offset: int) -> List[str]:
            # wrap WITHIN the primary DC: resolvers/proxies must not spill
            # into the secondary just because tlogs consumed the primary
            # prefix (co-location beats a cross-DC hop on every commit)
            pool = primary_workers or workers
            return [pool[(offset + i) % len(pool)] for i in range(n)]

        tlog_addrs = pick(n_tlogs, 0)
        # satellite tlog replicas OUTSIDE the primary DC: the commit
        # quorum spans DCs, so total primary loss cannot lose acked data
        # (the reference's synchronous satellite logs)
        n_sat = min(int(getattr(cfg, "satellite_logs", 0)),
                    max(n_tlogs - 1, 0))
        if n_sat > 0:
            if log_repl:
                # partitioned tags can exclude the satellite index from a
                # tag's subset, voiding the durability point of satellites;
                # this generation runs unpartitioned instead
                TraceEvent("SatelliteForcesFullLogReplication",
                           id=self.salt).log()
                log_repl = 0
            sat_pool = [w for w in workers
                        if dc_of(w) != primary_dc
                        and w not in tlog_addrs[: n_tlogs - n_sat]]
            sats = sat_pool[:n_sat]
            if len(sats) < n_sat:
                # Thin non-primary pool: backfill the shortfall from the
                # primary so the generation still runs n_tlogs replicas —
                # reduced satellite coverage, never reduced replication.
                TraceEvent("SatelliteRecruitmentShort", id=self.salt).detail(
                    "Requested", n_sat).detail(
                    "Recruited", len(sats)).detail(
                    "BackfilledFromPrimary", n_sat - len(sats)).log()
            # keep enough primary tlogs that kept + sats == n_tlogs
            kept = tlog_addrs[: n_tlogs - len(sats)]
            if sats:
                tlog_addrs = kept + sats
        TraceEvent("RecruitPlacement", id=self.salt).detail(
            "PrimaryDC", primary_dc).detail(
            "TLogDCs", str([dc_of(a) for a in tlog_addrs])).detail(
            "TxnPoolDCs", str(sorted((d, len(ws)) for d, ws in by_dc.items()))).detail(
            "Localities", len(self.localities)).log()
        resolver_addrs = pick(n_resolvers, n_tlogs)
        n_proxies = max(1, conf_proxies)
        proxy_addrs = pick(n_proxies, n_tlogs + n_resolvers)
        if len(set(proxy_addrs)) < n_proxies:
            # proxy tokens are per-process: never co-locate two proxies
            proxy_addrs = list(dict.fromkeys(proxy_addrs))

        # Per-replica token suffixes: duplicate placement (a thin worker
        # pool) degrades fault isolation but must never alias two role
        # instances into one (that would split one version stream).
        tlog_reps = tuple((a, f"{suffix}.{i}") for i, a in enumerate(tlog_addrs))
        new_log = LogSystemConfig(
            gen_id=gen_id, tlogs=tlog_reps, start_version=recovery_version,
            replication_factor=log_repl,
        )
        # Seed each new replica with only the tags it will hold (per-tag
        # subsets), and only tags that still EXIST: a tag retired by a
        # finished move — or minted by an unfinished one — must not ride
        # the recovery copy into the new generation, where nothing would
        # ever pop it (it would pin the disk-queue front forever).
        live_tags = {t for (t, _b, _e, _a) in prev.storage_tags}

        def keep_tag(t: int) -> bool:
            # negative tags (metadata stream, live backup logs) always ride
            # the recovery copy; positive tags only while a storage server
            # still owns them
            return t < 0 or t in live_tags

        await all_of([
            self._init_role(a, INIT_TLOG_TOKEN, InitializeTLogRequest(
                gen_id=gen_id, start_version=recovery_version,
                token_suffix=rep_suffix, replica_index=i,
                preload={t: e for t, e in preload.items()
                         if keep_tag(t) and i in new_log.tag_subset(t)},
                preload_popped={t: v for t, v in preload_popped.items()
                                if keep_tag(t) and i in new_log.tag_subset(t)},
            ))
            for i, (a, rep_suffix) in enumerate(tlog_reps)
        ])
        await all_of([
            self._init_role(a, INIT_RESOLVER_TOKEN, InitializeResolverRequest(
                gen_id=gen_id, start_version=recovery_version,
                token_suffix=f"{suffix}.{i}", replica_index=i,
            ))
            for i, a in enumerate(resolver_addrs)
        ])

        # Seed storage servers on first boot (newSeedServers:325): each
        # shard gets a team of `storage_replication` replicas on distinct
        # workers (storage tokens are per-process, and same-worker replicas
        # would share a fault domain anyway).
        repl = storage_repl
        # seed when there IS no storage map — including the crash window
        # where a previous first-boot master locked the cstate but died
        # before the WRITING_CSTATE hand-over (its seeded servers, if any,
        # are re-initialized idempotently by tag)
        if first_boot or not prev.storage_tags:
            storage_shards = KeyShardMap.uniform(cfg.n_storage)
            if len(storage_workers) < cfg.n_storage * repl:
                raise error.recruitment_failed(
                    f"need {cfg.n_storage * repl} storage workers for "
                    f"{cfg.n_storage} shards x {repl} replicas, have {len(storage_workers)}"
                )
            storage_tags = []
            tag = 0
            # team placement: spread each shard's replicas across DCs when
            # there are several (a dc-wide loss keeps every shard served),
            # else across machines (DDTeamCollection's policy ladder)
            from .replication_policy import PolicyAcross

            field = "dc_id" if getattr(cfg, "n_dcs", 1) > 1 else "machine_id"
            pool = list(storage_workers)
            for s in range(cfg.n_storage):
                begin = storage_shards.begins[s]
                end = storage_shards.span_end(s) or b"\xff\xff\xff"
                team = (PolicyAcross(repl, field).select(pool, self.localities)
                        if repl > 1 else None) or pool[:repl]
                for addr in team:
                    pool.remove(addr)
                    await self._init_role(addr, INIT_STORAGE_TOKEN,
                                          InitializeStorageRequest(tag=tag, begin=begin, end=end))
                    storage_tags.append((tag, begin, end, addr))
                    tag += 1
            storage_tags = tuple(storage_tags)
        else:
            storage_tags = prev.storage_tags

        # -- RECOVERY_TRANSACTION (masterserver.actor.cpp:730-780) -----------
        # The master itself commits the first (empty) transaction of the new
        # epoch, at recovery_version + jump: it drives the version chain —
        # and with it the tlog KCV horizon and the storage servers — past
        # the MVCC-window jump. Without it, a post-recovery cluster
        # deadlocks: reads need storage at the jumped GRV, storage advances
        # only on commits, and every client transaction starts with a read.
        # Resolver key shards: the splits chosen by a previous epoch's
        # resolutionBalancing, else uniform (rebalancing hands over by
        # epoch bounce: fresh resolvers + the MVCC-window version jump
        # make the empty conflict history safe).
        splits = list(prev.resolver_splits)
        if len(splits) == n_resolvers - 1 and splits == sorted(splits) and all(splits):
            resolver_map = KeyShardMap(splits)
            used_splits = tuple(splits)
        else:
            resolver_map = KeyShardMap.uniform(n_resolvers)
            used_splits = ()

        recovery_txn_version = recovery_version + max(first_jump, 1)
        from .log_system import LogSystemClient
        from .messages import ResolveTransactionBatchRequest

        log_client = LogSystemClient(self.net, self.proc.address, new_log)
        self._state("recovery_transaction", Version=recovery_txn_version)
        await all_of([
            self.net.request(
                self.proc.address, Endpoint(a, RESOLVE_TOKEN + f"{suffix}.{i}"),
                ResolveTransactionBatchRequest(
                    prev_version=recovery_version, version=recovery_txn_version,
                    last_received_version=recovery_version, transactions=[],
                ),
                TaskPriority.PROXY_RESOLVER_REPLY, timeout=RECRUIT_TIMEOUT,
            )
            for i, a in enumerate(resolver_addrs)
        ])
        await log_client.push(recovery_version, recovery_txn_version, {},
                              known_committed=recovery_version)

        # Version authority for the new epoch, starting past the recovery
        # transaction.
        self.master = Master(self.proc, start_version=recovery_txn_version,
                             token_suffix=suffix)

        # Admission control for the epoch (the reference's ratekeeper runs
        # under the master's data distribution in 6.0).
        ratekeeper = Ratekeeper(
            self.net, self.proc.address, storage_tags,
            lambda: self.master.version,
            log_config=new_log,
            # degraded conflict engines (device faults, failover to the CPU
            # oracle — fault/resilient.py) are an admission-control signal
            resolver_eps=[
                Endpoint(a, RESOLVER_HEALTH_TOKEN + f"{suffix}.{i}")
                for i, a in enumerate(resolver_addrs)
            ],
        )
        rate_token = GET_RATE_INFO_TOKEN + suffix
        self.proc.register(rate_token, ratekeeper.get_rate_info)
        rk_task = spawn(ratekeeper.run(), TaskPriority.RATEKEEPER,
                        name=f"ratekeeper:{self.salt}")
        self.proc.actors.add(rk_task)

        # Status fragment for the CC's status document (Status.actor.cpp).
        status_token = f"master.status{suffix}"

        async def master_status(_req):
            return {
                "version": self.master.version,
                "recovery_count": rc,
                "recovery_version": recovery_version,
                "tps_limit": ratekeeper.tps_limit,
                "worst_storage_lag_versions": ratekeeper.worst_lag,
                "storage_lag_stale": ratekeeper.lag_stale,
                "resolvers_degraded": ratekeeper.resolver_degraded,
                "resolver_health": dict(ratekeeper.resolver_health),
                "resolver_telemetry": dict(ratekeeper.resolver_telemetry),
                "tlogs": list(tlog_addrs),
                "resolvers": list(resolver_addrs),
                "proxies": list(proxy_addrs),
            }

        self.proc.register(status_token, master_status)

        from .proxy import COMMITTED_VERSION_TOKEN

        storage_shards, storage_teams = teams_from_storage_tags(storage_tags)
        peer_grv_eps = [Endpoint(a, COMMITTED_VERSION_TOKEN) for a in proxy_addrs]
        proxy_cfg = ProxyConfig(
            master_ep=Endpoint(self.proc.address, GET_COMMIT_VERSION_TOKEN + suffix),
            resolver_eps=[Endpoint(a, RESOLVE_TOKEN + f"{suffix}.{i}")
                          for i, a in enumerate(resolver_addrs)],
            resolver_shards=resolver_map,
            log_config=new_log,
            storage_teams=storage_teams,
            storage_shards=storage_shards,
            master_wf_ep=Endpoint(self.proc.address, f"waitFailure:master:{self.salt}"),
            rate_ep=Endpoint(self.proc.address, rate_token),
            peer_grv_eps=peer_grv_eps,
        )
        await all_of([
            self._init_role(a, INIT_PROXY_TOKEN, InitializeProxyRequest(
                gen_id=gen_id, cfg=proxy_cfg, start_version=recovery_txn_version,
            ))
            for a in proxy_addrs
        ])

        # -- WRITING_CSTATE: the durable hand-over ---------------------------
        if buggify.buggify():
            # a slow hand-over widens the window where the old generation
            # is locked but the new one is not yet authoritative
            await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
        self._state("writing_cstate")
        cstate_val = DBCoreState(
            recovery_count=rc,
            generations=(LogGenerationInfo(config=new_log, end_version=None),),
            storage_tags=storage_tags,
            resolver_splits=used_splits,  # balanced splits survive epochs
            excluded=prev.excluded,       # exclusions survive epochs too
            conf=prev.conf,               # the committed configuration
        )
        await cstate.set_exclusive(cstate_val)

        # -- FULLY_RECOVERED -------------------------------------------------
        info = ServerDBInfo(
            recovery_count=rc, recovery_state="fully_recovered",
            master_addr=self.proc.address, proxy_addrs=tuple(proxy_addrs),
            log_config=new_log, storage_tags=storage_tags,
            master_status_ep=Endpoint(self.proc.address, status_token),
        )
        from .cluster_controller import CC_MASTER_RECOVERED_TOKEN

        self.net.one_way(self.proc.address,
                         Endpoint(self.cc_addr, CC_MASTER_RECOVERED_TOKEN), info,
                         TaskPriority.CLUSTER_CONTROLLER)
        # Predecessor generations are now unreachable from the cstate:
        # retire their roles everywhere (best-effort one-ways).
        for a in self.workers:
            self.net.one_way(self.proc.address, Endpoint(a, RETIRE_TOKEN),
                             RetireGenerationsRequest(keep_min=rc),
                             TaskPriority.CLUSTER_CONTROLLER)
        self._state("fully_recovered", RecoveryCount=rc)

        # -- DD-lite: the shard-movement coordinator -------------------------
        # (DataDistribution reduced to explicit whole-shard MoveKeys; the
        # authoritative map is cstate.storage_tags, mirrored into
        # \xff/keyServers by real transactions at epoch start and on every
        # move, so proxies and clients follow transactionally.)
        from ..client.database import Database as ClientDatabase

        from ..sim.loop import Promise as _Promise

        from ..sim.actors import AsyncMutex as _AsyncMutex

        dd = {
            "storage_tags": list(storage_tags),
            "cstate_val": cstate_val,
            "busy": False,
            "info": info,
            "init_done": _Promise(),
            # monotone storage-tag allocator: concurrent queue relocations
            # taking max(tags)+1 would mint DUPLICATE tags
            "next_tag": max((t for (t, _b, _e, _a) in storage_tags),
                            default=-1) + 1,
            # read-transform-write publishes compose under this
            "publish_mutex": _AsyncMutex(),
        }
        dd_db = ClientDatabase(self.net, self.proc.address, list(proxy_addrs))
        move_token = MOVE_SHARD_TOKEN + suffix

        async def dd_init() -> None:
            # Mirror the authoritative map into the system keyspace and
            # prune orphaned destinations of a move the last epoch never
            # finished (their tags are absent from cstate).
            valid = tuple(t for (t, _b, _e, _a) in dd["storage_tags"])
            for a in self.workers:
                self.net.one_way(
                    self.proc.address, Endpoint(a, RETIRE_STORAGE_TOKEN),
                    RetireStorageRequest(tags=valid, prune=True),
                    TaskPriority.MOVE_KEYS,
                )

            async def seed(tr):
                tr.set_access_system_keys()
                for begin, team in _teams_by_begin(dd["storage_tags"]).items():
                    tr.set(system_keys.key_servers_key(begin),
                           system_keys.encode_key_servers(team))
                # a backup that straddled the recovery: re-advertise its
                # flag so this generation's proxies resume copying into the
                # backup tag (commits between recovery and this rewrite are
                # a known v0 gap; agents should restart on generation turn)
                active = await tr.get(system_keys.BACKUP_ACTIVE_KEY)
                if active:
                    tr.set(system_keys.BACKUP_ACTIVE_KEY, active)
                # a database lock equally straddles recoveries
                locked = await tr.get(system_keys.DB_LOCK_KEY)
                if locked:
                    tr.set(system_keys.DB_LOCK_KEY, locked)
            await dd_db.run(seed)
            dd["init_done"].send(None)

        async def dd_metadata_gc() -> None:
            """Pop METADATA_TAG at the minimum drained version over every
            proxy (the resolver's oldest-proxy-version GC): without this
            the tag pins every tlog's disk-queue front forever."""
            from .proxy import METADATA_VERSION_TOKEN

            while True:
                await delay(2.0, TaskPriority.MOVE_KEYS)
                versions = []
                ok = True
                for a in proxy_addrs:
                    try:
                        versions.append(await self.net.request(
                            self.proc.address, Endpoint(a, METADATA_VERSION_TOKEN),
                            None, TaskPriority.MOVE_KEYS, timeout=1.0,
                        ))
                    except error.FDBError:
                        ok = False
                        break
                if ok and versions:
                    log_client.pop(system_keys.METADATA_TAG, min(versions))

        async def move_shard(req: MoveShardRequest):
            await dd["init_done"].future  # serialize vs the seed transaction
            # the external move joins the DD queue's shard-exclusion
            # discipline: wait (bounded) for any queued relocation of this
            # shard to finish, then hold the shard for the move's duration
            deadline = 120
            while req.begin in dd["busy_shards"] or dd["busy"]:
                deadline -= 1
                if deadline <= 0:
                    raise error.client_invalid_operation(
                        "shard is being relocated; retry later")
                await delay(0.5, TaskPriority.MOVE_KEYS)
            if set(req.dest_workers) & dd["reserved"]:
                raise error.client_invalid_operation(
                    "a destination is reserved by a concurrent relocation")
            dd["busy"] = True
            dd["busy_shards"].add(req.begin)
            dd["reserved"] |= set(req.dest_workers)
            try:
                return await self._move_shard(req, dd, dd_db, log_client, cstate,
                                              ratekeeper)
            finally:
                dd["busy"] = False
                dd["busy_shards"].discard(req.begin)
                dd["reserved"] -= set(req.dest_workers)

        dd["reserved"] = set()   # in-flight relocation destinations

        def pick_spares(n: int):
            """Policy-selected destination workers: alive, not hosting
            storage, not excluded, not already RESERVED by a concurrent
            relocation (two parallel ops landing on one worker would alias
            its per-process storage tokens), spread across machines
            (DDTeamCollection's team builder behind PolicyAcross)."""
            from .replication_policy import PolicyAcross

            hosts = {a for (_t, _b, _e, a) in dd["storage_tags"]}
            cands = sorted(
                w for w in self.workers
                if not self.net.monitor.is_failed(w)
                and w not in hosts and w not in dd["excluded"]
                and w not in dd["reserved"]
            )
            return PolicyAcross(n, "machine_id").select(cands, self.localities)

        # -- DataDistributionQueue (DataDistributionQueue.actor.cpp) ---------
        # A prioritized relocation queue with bounded parallelism: the
        # tracker/fixer DECIDE (fast polls), runner actors EXECUTE (slow
        # fetches overlap across disjoint shards; metadata commits and
        # cstate publishes serialize through their own paths). Lower
        # priority value = more urgent (the reference's move priorities:
        # team health above load balancing above space reclamation).
        PRI_TEAM, PRI_SPLIT, PRI_MERGE = 0, 1, 2
        dd["queue"] = []              # [(priority, seq, key, shards, fn)]
        dd["queued_keys"] = set()     # dedupe: one pending op per key
        dd["busy_shards"] = set()     # shard begins under relocation
        dd["qseq"] = 0

        def dd_enqueue(priority: int, key: tuple, shards: tuple, fn) -> None:
            if key in dd["queued_keys"]:
                return
            dd["qseq"] += 1
            dd["queue"].append((priority, dd["qseq"], key, shards, fn))
            dd["queued_keys"].add(key)

        async def dd_queue_runner(slot: int) -> None:
            await dd["init_done"].future
            while True:
                await delay(0.3, TaskPriority.DATA_DISTRIBUTION_LAUNCH)
                if buggify.buggify():
                    # a stalled runner: the other slots must carry the queue
                    await delay(2.0, TaskPriority.DATA_DISTRIBUTION_LAUNCH)
                best = None
                for item in sorted(dd["queue"]):
                    _p, _s, _k, shards, _fn = item
                    if not (set(shards) & dd["busy_shards"]):
                        best = item
                        break
                if best is None:
                    continue
                dd["queue"].remove(best)
                priority, _seq, key, shards, fn = best
                dd["busy_shards"] |= set(shards)
                try:
                    await fn()
                except error.FDBError as exc:
                    # the op re-validates against the live map; a stale
                    # decision (shard gone, team changed) drops out here
                    TraceEvent("DDQueueOpFailed", id=self.salt).detail(
                        "Key", str(key)).detail("Reason", exc.name).log()
                finally:
                    dd["busy_shards"] -= set(shards)
                    # only NOW may the key re-enqueue: releasing at dequeue
                    # would let the decision loops queue a duplicate that
                    # re-applies a finished op (e.g. growing a team past
                    # the configured replication)
                    dd["queued_keys"].discard(key)

        async def dd_tracker() -> None:
            """Shard size + write-bandwidth tracking and split/merge
            DECISIONS (DataDistributionTracker): poll each team's byte
            sample and applied-write bandwidth; a shard over the size
            threshold OR the bandwidth threshold (a hot-WRITE shard whose
            size alone would never trigger) splits at its sample median
            onto policy-picked spares; adjacent dwarf shards merge.
            Execution goes through the DD queue."""
            from ..core.knobs import SERVER_KNOBS
            from .storage import STORAGE_METRICS_TOKEN

            await dd["init_done"].future
            while True:
                interval = SERVER_KNOBS.dd_tracker_interval
                if buggify.buggify():
                    # frantic tracker: split/merge decisions race fresh
                    # moves and each other's metadata transactions
                    interval = interval / 8
                await delay(interval, TaskPriority.MOVE_KEYS)
                tags = list(dd["storage_tags"])
                teams = _teams_by_begin(tags)
                ranges = sorted({(b, e) for (_t, b, e, _a) in tags})
                metrics = {}
                ok = True
                for b, _e in ranges:
                    _t0, a0 = teams[b][0]
                    try:
                        metrics[b] = await self.net.request(
                            self.proc.address, Endpoint(a0, STORAGE_METRICS_TOKEN),
                            None, TaskPriority.MOVE_KEYS, timeout=1.0,
                        )
                    except error.FDBError:
                        ok = False
                        break
                if not ok:
                    continue
                split_bytes = SERVER_KNOBS.dd_shard_split_bytes
                split_bw = SERVER_KNOBS.dd_shard_split_bandwidth
                for b, e in sorted(ranges, key=lambda r: -metrics[r[0]]["bytes"]):
                    m = metrics[b]
                    k = m.get("split_key")
                    hot = (m["bytes"] > split_bytes
                           or m.get("write_bw", 0.0) > split_bw)
                    if not hot or not k or not (b < k < e):
                        continue
                    n_repl = len(teams[b])

                    def mk_split(b=b, k=k, n_repl=n_repl):
                        async def run():
                            dests = pick_spares(n_repl)
                            if not dests:
                                TraceEvent("ShardSplitNoSpares",
                                           id=self.salt).detail("Begin", b).log()
                                return
                            dd["reserved"] |= set(dests)
                            try:
                                await self._split_shard(b, k, dests, dd, dd_db,
                                                        log_client, cstate,
                                                        ratekeeper)
                            finally:
                                dd["reserved"] -= set(dests)
                        return run
                    dd_enqueue(PRI_SPLIT, ("split", b), (b,), mk_split())
                merge_bytes = SERVER_KNOBS.dd_shard_merge_bytes
                if len(ranges) <= self.cfg.n_storage:
                    # merge only what splitting created: the seeded shard
                    # count is the configured floor (an empty cluster would
                    # otherwise collapse to one shard at boot)
                    continue
                for (b1, e1), (b2, _e2) in zip(ranges, ranges[1:]):
                    if e1 != b2:
                        continue
                    if (metrics[b1]["bytes"] < merge_bytes
                            and metrics[b2]["bytes"] < merge_bytes
                            and metrics[b1]["bytes"] + metrics[b2]["bytes"]
                            < split_bytes // 4):

                        def mk_merge(b1=b1, b2=b2):
                            async def run():
                                await self._merge_shards(b1, b2, dd, dd_db,
                                                         log_client, cstate,
                                                         ratekeeper)
                            return run
                        dd_enqueue(PRI_MERGE, ("merge", b1, b2), (b1, b2),
                                   mk_merge())

        dd["excluded"] = set(cstate_val.excluded)
        exclude_token = EXCLUDE_TOKEN + suffix

        async def persist_excluded():
            dd["cstate_val"] = replace(dd["cstate_val"],
                                       excluded=tuple(sorted(dd["excluded"])))
            await cstate.set_exclusive(dd["cstate_val"])

        async def exclude_servers(req: ExcludeServersRequest):
            """Drain shards off the excluded addresses, one move at a time
            (ManagementAPI excludeServers + DD's trackExcludedServers)."""
            await dd["init_done"].future
            if not req.exclude:
                dd["excluded"] -= set(req.addresses)
                await persist_excluded()
                return {"excluded": sorted(dd["excluded"])}
            dd["excluded"] |= set(req.addresses)
            await persist_excluded()
            moved = []
            while True:
                tags = dd["storage_tags"]
                victim = next(
                    ((t, b, e, a) for (t, b, e, a) in tags
                     if a in dd["excluded"]), None)
                if victim is None:
                    break
                _t, begin, _e, _a = victim
                # join the queue's shard-exclusion discipline (a queued
                # relocation of this shard finishes first)
                deadline = 240
                while begin in dd["busy_shards"] or dd["busy"]:
                    deadline -= 1
                    if deadline <= 0:
                        raise error.client_invalid_operation(
                            "shard is being relocated; retry later")
                    await delay(0.5, TaskPriority.MOVE_KEYS)
                # pick AFTER the wait (the map may have changed) and
                # RESERVE: a concurrent queued relocation must not land on
                # the same spare worker
                team = sorted((t, a) for (t, b2, _e2, a)
                              in dd["storage_tags"] if b2 == begin)
                if not team:
                    continue   # the shard was merged/moved away meanwhile
                dests = pick_spares(len(team))
                if not dests:
                    raise error.recruitment_failed(
                        "not enough non-excluded spare workers to drain onto")
                dd["busy"] = True
                dd["busy_shards"].add(begin)
                dd["reserved"] |= set(dests)
                try:
                    await self._move_shard(
                        MoveShardRequest(begin=begin, dest_workers=dests),
                        dd, dd_db, log_client, cstate, ratekeeper)
                finally:
                    dd["busy"] = False
                    dd["busy_shards"].discard(begin)
                    dd["reserved"] -= set(dests)
                moved.append(begin)
            return {"excluded": sorted(dd["excluded"]), "moved": moved}

        self.proc.register(move_token, move_shard)
        self.proc.register(exclude_token, exclude_servers)
        dd_task = spawn(dd_init(), TaskPriority.MOVE_KEYS, name=f"ddInit:{self.salt}")
        self.proc.actors.add(dd_task)
        dd_gc_task = spawn(dd_metadata_gc(), TaskPriority.MOVE_KEYS,
                           name=f"ddMetaGC:{self.salt}")
        self.proc.actors.add(dd_gc_task)
        dd_tracker_task = spawn(dd_tracker(), TaskPriority.MOVE_KEYS,
                                name=f"ddTracker:{self.salt}")
        self.proc.actors.add(dd_tracker_task)
        from ..core.knobs import SERVER_KNOBS as _SK

        runner_tasks = [
            spawn(dd_queue_runner(i), TaskPriority.DATA_DISTRIBUTION_LAUNCH,
                  name=f"ddQueue:{self.salt}.{i}")
            for i in range(max(1, int(_SK.dd_move_parallelism)))
        ]
        for t in runner_tasks:
            self.proc.actors.add(t)

        # -- resolutionBalancing (masterserver.actor.cpp:919-977) -------------
        # Poll resolver row counts; on sustained imbalance, persist new
        # split keys (quantiles of the resolvers' key samples) in cstate
        # and flip the routing LIVE — zero recoveries: the master (version
        # authority) piggybacks (flip_version, old, new splits) on its
        # version replies, proxies split batches >= flip by the new map,
        # and each resolver seeds a synthetic whole-span write over its
        # gained ranges at its first post-flip batch (conservative
        # conflicts stand in for the donor's unshipped history — the
        # "rebuild past the MVCC window" handoff; exact once snapshots
        # pass the flip). The reference ships state via
        # ResolutionSplitRequest; the conservative seed needs no transfer.
        conf_p = _Promise()

        async def conf_watcher() -> None:
            """Watch the committed \\xff/conf/ map (DatabaseConfiguration):
            a change is mirrored into the coordinated state — where the
            NEXT recovery reads its role counts — and bounces the epoch to
            apply it (the reference's configuration-triggered recovery)."""
            from .management import CONF_END, CONF_PREFIX

            await dd["init_done"].future
            while True:
                await delay(1.0, TaskPriority.MOVE_KEYS)
                try:
                    async def rd(tr):
                        return await tr.get_range(CONF_PREFIX, CONF_END,
                                                  limit=1000, snapshot=True)
                    rows = await dd_db.run(rd)
                except error.FDBError:
                    continue
                committed = tuple(sorted(
                    (k[len(CONF_PREFIX):], v) for k, v in rows))
                if committed == dd["cstate_val"].conf:
                    continue
                TraceEvent("ConfigurationChanged", id=self.salt).detail(
                    "Conf", str(committed)).log()
                dd["cstate_val"] = replace(dd["cstate_val"], conf=committed)
                try:
                    await cstate.set_exclusive(dd["cstate_val"])
                except error.FDBError:
                    return   # a successor owns the cstate
                if not conf_p.is_set:
                    conf_p.send(None)
                return

        async def replication_fixer() -> None:
            """Converge every shard's team size to the configured storage
            replication (the DD side of `configure single|double|triple`):
            DECISIONS here, execution through the DD queue at team
            priority (above load-balancing splits/merges — the reference's
            unhealthy-team precedence)."""
            await dd["init_done"].future
            while True:
                await delay(1.5, TaskPriority.MOVE_KEYS)
                want = storage_repl
                teams = _teams_by_begin(dd["storage_tags"])
                for begin in sorted(teams):
                    team = teams[begin]
                    if len(team) == want:
                        continue
                    grow = len(team) < want

                    def mk_fix(begin=begin, grow=grow):
                        async def run():
                            # re-validate: the decision may be stale by the
                            # time a runner slot frees (another fix ran, a
                            # split re-teamed the shard)
                            cur = _teams_by_begin(dd["storage_tags"]).get(begin)
                            if cur is None or len(cur) == want                                     or (len(cur) < want) != grow:
                                return
                            if grow:
                                dests = pick_spares(1)
                                if not dests:
                                    TraceEvent("TeamGrowNoSpares",
                                               id=self.salt).detail(
                                        "Begin", begin).log()
                                    return
                                dd["reserved"] |= set(dests)
                                try:
                                    await self._grow_team(begin, dests[0], dd,
                                                          dd_db, log_client,
                                                          cstate, ratekeeper)
                                finally:
                                    dd["reserved"] -= set(dests)
                            else:
                                await self._shrink_team(begin, dd, dd_db,
                                                        log_client, cstate,
                                                        ratekeeper)
                        return run
                    dd_enqueue(PRI_TEAM, ("team", begin), (begin,), mk_fix())

        async def resolution_balancing() -> None:
            from .resolver import RESOLUTION_METRICS_TOKEN

            interval = float(cfg.rebalance_interval)
            min_rows = int(cfg.rebalance_min_rows)
            ratio = 3.0
            current_splits = used_splits or tuple(
                KeyShardMap.uniform(n_resolvers).begins[1:])
            while True:
                await delay(interval, TaskPriority.RESOLUTION_METRICS)
                stats = []
                try:
                    for i, a in enumerate(resolver_addrs):
                        stats.append(await self.net.request(
                            self.proc.address,
                            Endpoint(a, RESOLUTION_METRICS_TOKEN + f"{suffix}.{i}"),
                            None, TaskPriority.RESOLUTION_METRICS, timeout=1.0,
                        ))
                except error.FDBError:
                    continue
                rows = [s["rows"] for s in stats]
                if len(rows) < 2 or sum(rows) < min_rows:
                    continue
                if max(rows) <= ratio * (min(rows) + 10):
                    continue
                # new splits: quantiles of the union of key samples, each
                # sample weighted by its resolver's observed rows
                weighted: List[bytes] = []
                for s in stats:
                    sample = [k for k in s["sample"] if k]
                    if not sample:
                        continue
                    w = max(1, s["rows"] // len(sample))
                    for k in sample:
                        weighted.extend([k] * min(w, 64))
                if not weighted:
                    continue
                weighted.sort()
                n = len(resolver_addrs)
                new_splits = []
                for i in range(1, n):
                    new_splits.append(weighted[(len(weighted) * i) // n])
                new_splits = sorted(set(new_splits))
                if len(new_splits) != n - 1 or not all(new_splits):
                    continue
                if tuple(new_splits) == current_splits:
                    # an unsplittable hot spot (e.g. one hot key): identical
                    # splits would churn flips forever
                    continue
                # durable FIRST (the next recovery recruits on the new
                # splits), then flip the live generation with zero downtime
                dd["cstate_val"] = replace(dd["cstate_val"],
                                           resolver_splits=tuple(new_splits))
                try:
                    await cstate.set_exclusive(dd["cstate_val"])
                except error.FDBError:
                    return  # a successor owns the cstate; we are done anyway
                flip = self.master.set_routing_flip(current_splits,
                                                    tuple(new_splits))
                TraceEvent("ResolutionBalancing", id=self.salt).detail(
                    "Rows", str(rows)).detail("NewSplits", str(new_splits)).detail(
                    "FlipVersion", flip).log()
                current_splits = tuple(new_splits)
                # keep watching: further imbalance flips again, live

        balance_task = spawn(resolution_balancing(), TaskPriority.RESOLUTION_METRICS,
                             name=f"resBalance:{self.salt}")
        self.proc.actors.add(balance_task)
        conf_task = spawn(conf_watcher(), TaskPriority.MOVE_KEYS,
                          name=f"confWatch:{self.salt}")
        self.proc.actors.add(conf_task)
        fixer_task = spawn(replication_fixer(), TaskPriority.MOVE_KEYS,
                           name=f"replFixer:{self.salt}")
        self.proc.actors.add(fixer_task)

        # Serve until any recruited role host dies (process-level watch;
        # role death on a live worker only happens when a successor
        # generation replaces us, in which case we are dead already).
        watch_addrs = sorted(set(tlog_addrs + resolver_addrs + list(proxy_addrs)))
        watchers = [
            spawn(
                wait_failure_client(self.net, self.proc.address,
                                    Endpoint(a, WAIT_FAILURE_TOKEN)),
                TaskPriority.FAILURE_MONITOR, name=f"masterWatch:{a}",
            )
            for a in watch_addrs
        ]
        try:
            which, _ = await any_of([conf_p.future] + watchers)
        finally:
            for w in watchers:
                w.cancel()
            rk_task.cancel()
            dd_task.cancel()
            dd_gc_task.cancel()
            dd_tracker_task.cancel()
            balance_task.cancel()
            conf_task.cancel()
            fixer_task.cancel()
            for t in runner_tasks:
                t.cancel()
            self.proc.unregister(rate_token)
            self.proc.unregister(status_token)
            self.proc.unregister(move_token)
            self.proc.unregister(exclude_token)
        self.master.unregister()
        if which == 0:
            # Deliberate epoch bounce: the successor recruits with the new
            # configuration mirrored into cstate by the conf watcher.
            raise error.master_recovery_failed("configuration changed epoch bounce")
        raise error.master_tlog_failed("a transaction-role host failed")
