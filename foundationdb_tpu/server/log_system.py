"""Log system: the proxy/storage-facing view of one tlog generation.

Re-design of fdbserver/TagPartitionedLogSystem.actor.cpp round-2 scope:
one team of K replicas per generation, all-ack pushes, KCV-clipped peeks,
and the epoch-end lock + recovery-version math:

  * push(): fan a version out to every replica; committed only when ALL
    have fsynced (anti-quorum 0). After the ack, advance the KCV on every
    replica so peeks (and therefore storage servers) may serve it.
  * peek()/pop(): any single replica holds every served version (all-ack),
    so peeks go to one replica chosen by tag; pops fan out to all.
  * lock_generation(): lock every reachable replica. Because pushes need
    all replicas, ONE locked replica freezes the generation forever. The
    recovery version is min(end_version) over the locked set: every
    client-acked version is durable on ALL replicas, hence <= every
    replica's end; versions above the min were never fully acked and may
    be discarded (commit_unknown_result semantics). Every version <= the
    min is durable on every locked replica, so any one of them can seed
    the successor generation (getDurableVersion, TagPartitionedLogSystem
    .actor.cpp:61; the copy replaces old-generation peek cursors).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core import error
from ..core.types import Mutation, Version
from ..sim.actors import all_of
from ..sim.loop import Future, TaskPriority
from ..sim.network import Endpoint
from .messages import (
    TLogCommitRequest,
    TLogKnownCommittedRequest,
    TLogLockRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
    TLogRecoveryDataRequest,
)
from . import tlog as tlog_mod

LOCK_TIMEOUT = 2.0


@dataclass(frozen=True)
class LogSystemConfig:
    """reference: LogSystemConfig (fdbserver/LogSystemConfig.h): the
    current generation's identity, membership and version floor. Each
    replica is (address, token_suffix): the suffix carries the generation
    AND the replica index, so two replicas recruited onto one worker are
    still distinct tlog instances (duplicate placement must degrade
    replication, never correctness)."""

    gen_id: Tuple[int, int] = (0, 0)       # (recovery_count, master_salt)
    tlogs: tuple = ()                      # ((address, token_suffix), ...)
    start_version: Version = 0

    def ep(self, replica: Tuple[str, str], kind: str) -> Endpoint:
        base = {
            "commit": tlog_mod.COMMIT_TOKEN,
            "peek": tlog_mod.PEEK_TOKEN,
            "pop": tlog_mod.POP_TOKEN,
            "lock": tlog_mod.LOCK_TOKEN,
            "kcv": tlog_mod.KCV_TOKEN,
            "recovery": tlog_mod.RECOVERY_DATA_TOKEN,
        }[kind]
        addr, suffix = replica
        return Endpoint(addr, base + suffix)


class LogSystemClient:
    """Push/peek/pop against one generation (held by proxies and storage)."""

    def __init__(self, net, src_addr: str, config: LogSystemConfig,
                 push_timeout: float = 5.0):
        self.net = net
        self.src = src_addr
        self.config = config
        self.push_timeout = push_timeout

    async def push(
        self,
        prev_version: Version,
        version: Version,
        messages: Dict[int, List[Mutation]],
        known_committed: Version,
    ) -> Version:
        """All-ack push of one version (ILogSystem::push). Raises on any
        replica failure/timeout — the commit outcome is then unknown."""
        req = TLogCommitRequest(
            prev_version=prev_version,
            version=version,
            messages=messages,
            gen_id=self.config.gen_id,
            known_committed=known_committed,
        )
        await all_of([
            self.net.request(
                self.src, self.config.ep(rep, "commit"), req,
                TaskPriority.TLOG_COMMIT, timeout=self.push_timeout,
            )
            for rep in self.config.tlogs
        ])
        # Every replica is durable at `version`: advance the peek horizon.
        # Unreliable one-ways — the next push carries the same KCV anyway.
        for rep in self.config.tlogs:
            self.net.one_way(
                self.src, self.config.ep(rep, "kcv"),
                TLogKnownCommittedRequest(version=version),
                TaskPriority.TLOG_COMMIT,
            )
        return version

    def peek_endpoint(self, tag: int) -> Endpoint:
        reps = self.config.tlogs
        return self.config.ep(reps[tag % len(reps)], "peek")

    async def peek(self, tag: int, begin_version: Version, timeout: float = 5.0) -> TLogPeekReply:
        return await self.net.request(
            self.src, self.peek_endpoint(tag),
            TLogPeekRequest(tag=tag, begin_version=begin_version),
            TaskPriority.TLOG_PEEK, timeout=timeout,
        )

    def pop(self, tag: int, version: Version) -> None:
        for rep in self.config.tlogs:
            self.net.one_way(
                self.src, self.config.ep(rep, "pop"),
                TLogPopRequest(tag=tag, version=version),
                TaskPriority.TLOG_POP,
            )


async def lock_generation(
    net, src_addr: str, config: LogSystemConfig
) -> Tuple[Version, str]:
    """Lock every reachable replica of `config`; returns (recovery_version,
    a locked replica to copy from). Raises master_recovery_failed
    if no replica can be locked (retry later — a generation with zero
    reachable replicas means the un-popped window is unrecoverable until
    one comes back)."""
    futures = [
        (rep, net.request(
            src_addr, config.ep(rep, "lock"), TLogLockRequest(),
            TaskPriority.TLOG_COMMIT, timeout=LOCK_TIMEOUT,
        ))
        for rep in config.tlogs
    ]
    locked: List[Tuple[Tuple[str, str], Version]] = []
    for rep, f in futures:
        try:
            reply = await f
        except error.FDBError:
            continue
        locked.append((rep, reply.end_version))
    if not locked:
        raise error.master_recovery_failed("no old-generation tlog reachable to lock")
    recovery_version = min(end for _, end in locked)
    # Any locked replica serves: all have every version <= recovery_version.
    return recovery_version, locked[0][0]


async def fetch_recovery_data(
    net, src_addr: str, config: LogSystemConfig, replica: Tuple[str, str],
    end_version: Version
):
    """Un-popped data <= end_version from one locked replica."""
    return await net.request(
        src_addr, config.ep(replica, "recovery"),
        TLogRecoveryDataRequest(end_version=end_version),
        TaskPriority.TLOG_PEEK, timeout=LOCK_TIMEOUT,
    )
