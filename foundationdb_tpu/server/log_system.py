"""Log system: the proxy/storage-facing view of one tlog generation.

Re-design of fdbserver/TagPartitionedLogSystem.actor.cpp round-3 scope:
one team of K replicas per generation with optional PER-TAG replica
subsets, all-ack pushes, KCV-clipped peeks with replica failover, and the
epoch-end lock + recovery-version math:

  * Tag partitioning (TagPartitionedLogSystem.actor.cpp:61): with
    replication_factor R < K, tag t's mutations are stored only on the R
    replicas tag_subset(t) — the reference's per-tag tLog sets chosen by
    locality policy, reduced to a deterministic round-robin. Every replica
    still receives every version (possibly with no messages for its tags):
    the version chain is what makes epoch-end min(end) math valid.
  * push(): fan a version out to every replica, messages filtered to each
    replica's tags; committed only when ALL have fsynced (anti-quorum 0).
    After the ack, advance the KCV on every replica so peeks (and
    therefore storage servers) may serve it.
  * peek(): served by any live member of the tag's subset — all-ack means
    each member holds every served version of its tags, so failover is a
    pure availability upgrade (LogSystemPeekCursor's best-server-else-
    others policy). A dead replica no longer stalls a storage tag until
    epoch end (round-2 VERDICT weak #4).
  * lock_generation(): lock replicas until the locked set both bounds the
    recovery version and COVERS every tag subset (any R-subset must
    intersect the locked set: |locked| >= K-R+1). The recovery version is
    min(end_version) over the locked set: every client-acked version is
    durable on ALL replicas, hence <= every replica's end; versions above
    the min were never fully acked and may be discarded
    (commit_unknown_result semantics). Recovery data is fetched from every
    locked replica and merged per tag (getDurableVersion,
    TagPartitionedLogSystem.actor.cpp:61; the copy replaces
    old-generation peek cursors).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core import buggify, error
from ..core.types import Mutation, Version
from ..sim.actors import all_of
from ..sim.loop import Future, TaskPriority
from ..sim.network import Endpoint
from .messages import (
    TLogCommitRequest,
    TLogKnownCommittedRequest,
    TLogLockRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
    TLogRecoveryDataRequest,
)
from . import tlog as tlog_mod

LOCK_TIMEOUT = 2.0

#: (n_tlogs, replication_factor, tag) -> replica-index subset
_SUBSET_MEMO: Dict[Tuple[int, int, int], Tuple[int, ...]] = {}


@dataclass(frozen=True)
class LogSystemConfig:
    """reference: LogSystemConfig (fdbserver/LogSystemConfig.h): the
    current generation's identity, membership and version floor. Each
    replica is (address, token_suffix): the suffix carries the generation
    AND the replica index, so two replicas recruited onto one worker are
    still distinct tlog instances (duplicate placement must degrade
    replication, never correctness)."""

    gen_id: Tuple[int, int] = (0, 0)       # (recovery_count, master_salt)
    tlogs: tuple = ()                      # ((address, token_suffix), ...)
    start_version: Version = 0
    #: tag replication factor; 0 (or >= len(tlogs)) = every replica holds
    #: every tag (the round-2 behavior)
    replication_factor: int = 0

    @property
    def partitioned(self) -> bool:
        """True when tags live on strict subsets of the replicas."""
        return 0 < self.replication_factor < len(self.tlogs)

    def tag_subset(self, tag: int) -> Tuple[int, ...]:
        """Replica indices holding `tag`'s data (the per-tag tLog set).
        Memoized: the commit hot path asks for every tag of every batch."""
        if not self.partitioned:
            return tuple(range(len(self.tlogs)))
        k = len(self.tlogs)
        key = (k, self.replication_factor, tag)
        got = _SUBSET_MEMO.get(key)
        if got is None:
            got = _SUBSET_MEMO[key] = tuple(
                sorted((tag + i) % k for i in range(self.replication_factor))
            )
        return got

    def lock_quorum(self) -> int:
        """Min locked replicas so every tag subset intersects the locked
        set (tag data coverage): any R-subset misses at most K-|locked|
        replicas, so |locked| >= K-R+1 guarantees intersection."""
        if not self.partitioned:
            return 1
        return len(self.tlogs) - self.replication_factor + 1

    def filter_messages_for_replica(
        self, index: int, messages: Dict[int, List[Mutation]]
    ) -> Dict[int, List[Mutation]]:
        """The tags of `messages` stored by replica `index`."""
        if not self.partitioned:
            return messages
        return {t: m for t, m in messages.items() if index in self.tag_subset(t)}

    def ep(self, replica: Tuple[str, str], kind: str) -> Endpoint:
        base = {
            "commit": tlog_mod.COMMIT_TOKEN,
            "peek": tlog_mod.PEEK_TOKEN,
            "pop": tlog_mod.POP_TOKEN,
            "lock": tlog_mod.LOCK_TOKEN,
            "kcv": tlog_mod.KCV_TOKEN,
            "recovery": tlog_mod.RECOVERY_DATA_TOKEN,
            "queue_info": tlog_mod.QUEUE_INFO_TOKEN,
        }[kind]
        addr, suffix = replica
        return Endpoint(addr, base + suffix)


class LogSystemClient:
    """Push/peek/pop against one generation (held by proxies and storage)."""

    def __init__(self, net, src_addr: str, config: LogSystemConfig,
                 push_timeout: float = 5.0):
        self.net = net
        self.src = src_addr
        self.config = config
        self.push_timeout = push_timeout

    async def push(
        self,
        prev_version: Version,
        version: Version,
        messages: Dict[int, List[Mutation]],
        known_committed: Version,
    ) -> Version:
        """All-ack push of one version (ILogSystem::push). Raises on any
        replica failure/timeout — the commit outcome is then unknown."""
        if self.config.partitioned:
            reqs = [
                TLogCommitRequest(
                    prev_version=prev_version, version=version,
                    messages=self.config.filter_messages_for_replica(i, messages),
                    gen_id=self.config.gen_id, known_committed=known_committed,
                )
                for i in range(len(self.config.tlogs))
            ]
        else:
            shared = TLogCommitRequest(
                prev_version=prev_version, version=version, messages=messages,
                gen_id=self.config.gen_id, known_committed=known_committed,
            )
            reqs = [shared] * len(self.config.tlogs)
        await all_of([
            self.net.request(
                self.src, self.config.ep(rep, "commit"), req,
                TaskPriority.TLOG_COMMIT, timeout=self.push_timeout,
            )
            for req, rep in zip(reqs, self.config.tlogs)
        ])
        # sim-only durability oracle (fdbrpc/sim_validation.h): this push
        # fully acked, so no recovery of THIS generation may pick a
        # version below it
        from ..sim import validation as sim_validation

        sim_validation.advance_max_committed(self.config.gen_id, version)
        # Every replica is durable at `version`: advance the peek horizon.
        # Unreliable one-ways — the next push carries the same KCV anyway.
        # BUGGIFY: drop them entirely; peeks must survive on the belt
        # (drain re-advertising / subsequent pushes).
        if not buggify.buggify():
            for rep in self.config.tlogs:
                self.net.one_way(
                    self.src, self.config.ep(rep, "kcv"),
                    TLogKnownCommittedRequest(version=version),
                    TaskPriority.TLOG_COMMIT,
                )
        return version

    async def peek(self, tag: int, begin_version: Version, timeout: float = 5.0) -> TLogPeekReply:
        """Peek with replica failover: try the tag's subset members in a
        tag-rotated preference order; any live member can serve (all-ack).
        Raises the last member's error only when every member fails
        (LogSystemPeekCursor: best server first, then the others)."""
        subset = self.config.tag_subset(tag)
        last_err: Optional[error.FDBError] = None
        start = tag
        if buggify.buggify():
            # randomize the preferred replica: the failover order and the
            # "any member can serve" property get exercised without a death
            start = tag + 1
        for attempt in range(len(subset)):
            idx = subset[(start + attempt) % len(subset)]
            try:
                return await self.net.request(
                    self.src, self.config.ep(self.config.tlogs[idx], "peek"),
                    TLogPeekRequest(tag=tag, begin_version=begin_version),
                    TaskPriority.TLOG_PEEK, timeout=timeout,
                )
            except error.FDBError as e:
                last_err = e
        raise last_err if last_err is not None else error.connection_failed()

    def send_kcv(self, version: Version) -> None:
        """Advertise a known-committed version to every replica
        (unreliable one-ways; the same payload pushes piggyback)."""
        for rep in self.config.tlogs:
            self.net.one_way(
                self.src, self.config.ep(rep, "kcv"),
                TLogKnownCommittedRequest(version=version),
                TaskPriority.TLOG_COMMIT,
            )

    def pop(self, tag: int, version: Version) -> None:
        for rep in self.config.tlogs:
            self.net.one_way(
                self.src, self.config.ep(rep, "pop"),
                TLogPopRequest(tag=tag, version=version),
                TaskPriority.TLOG_POP,
            )


async def lock_generation(
    net, src_addr: str, config: LogSystemConfig
) -> Tuple[Version, List[Tuple[str, str]]]:
    """Lock every reachable replica of `config`; returns (recovery_version,
    the locked replicas to copy from). Raises master_recovery_failed when
    the locked set is smaller than the tag-coverage quorum (retry later —
    some tag's un-popped window would be unrecoverable until a subset
    member comes back)."""
    if buggify.buggify():
        # stalled epoch end: in-flight pushes race the lock fan-out, so
        # some replicas take the commit and some reject it (the
        # maybe-committed window recovery's min(end) math must cover)
        from ..sim.loop import delay
        await delay(0.1, TaskPriority.TLOG_COMMIT)
    futures = [
        (rep, net.request(
            src_addr, config.ep(rep, "lock"), TLogLockRequest(),
            TaskPriority.TLOG_COMMIT, timeout=LOCK_TIMEOUT,
        ))
        for rep in config.tlogs
    ]
    locked: List[Tuple[Tuple[str, str], Version]] = []
    for rep, f in futures:
        try:
            reply = await f
        except error.FDBError:
            continue
        locked.append((rep, reply.end_version))
    if len(locked) < config.lock_quorum():
        raise error.master_recovery_failed(
            f"locked {len(locked)}/{len(config.tlogs)} tlogs < quorum {config.lock_quorum()}"
        )
    recovery_version = min(end for _, end in locked)
    return recovery_version, [rep for rep, _ in locked]


async def fetch_recovery_data(
    net, src_addr: str, config: LogSystemConfig,
    replicas: List[Tuple[str, str]], end_version: Version
) -> Tuple[Dict[int, List[Tuple[Version, List[Mutation]]]], Dict[int, Version]]:
    """Un-popped data <= end_version merged across the locked replicas.

    With per-tag subsets, each tag's data lives only on its subset; the
    locked set covers every subset (lock_quorum), so the per-tag union is
    complete. Entries for a (tag, version) are identical on every holder
    (all-ack pushes), so merging dedupes by version. Returns
    (tag_data, popped)."""
    if config.lock_quorum() == 1:
        # Every replica holds every tag: any one locked replica's window is
        # the whole window — no need to transfer K identical copies.
        for rep in replicas:
            try:
                reply = await net.request(
                    src_addr, config.ep(rep, "recovery"),
                    TLogRecoveryDataRequest(end_version=end_version),
                    TaskPriority.TLOG_PEEK, timeout=LOCK_TIMEOUT,
                )
                return dict(reply.tag_data), dict(reply.popped)
            except error.FDBError:
                continue
        raise error.master_recovery_failed("no locked tlog reachable for recovery data")
    if buggify.buggify():
        # a replica dying between lock and fetch is the races this fan-out
        # must survive; stretch the window they land in
        from ..sim.loop import delay
        await delay(0.2, TaskPriority.TLOG_PEEK)
    futures = [
        net.request(
            src_addr, config.ep(rep, "recovery"),
            TLogRecoveryDataRequest(end_version=end_version),
            TaskPriority.TLOG_PEEK, timeout=LOCK_TIMEOUT,
        )
        for rep in replicas
    ]
    replies = []
    for f in futures:
        try:
            replies.append(await f)
        except error.FDBError:
            continue
    # A replica that died between lock and fetch can remove a tag's only
    # locked holder: anything below the coverage quorum may silently drop
    # a tag's acked window — re-raise so the master's retry loop waits.
    if len(replies) < config.lock_quorum():
        raise error.master_recovery_failed(
            f"{len(replies)}/{len(replicas)} locked tlogs served recovery data "
            f"< coverage quorum {config.lock_quorum()}"
        )
    tag_data: Dict[int, Dict[Version, List[Mutation]]] = {}
    popped: Dict[int, Version] = {}
    for reply in replies:
        for tag, entries in reply.tag_data.items():
            dst = tag_data.setdefault(tag, {})
            for v, muts in entries:
                dst.setdefault(v, muts)
        for tag, v in reply.popped.items():
            popped[tag] = max(popped.get(tag, 0), v)
    merged = {
        tag: sorted(by_ver.items())
        for tag, by_ver in tag_data.items()
    }
    return merged, popped


from ..core import wire as _wire

_wire.register_record(LogSystemConfig)
