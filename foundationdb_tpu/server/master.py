"""Master: the commit-version authority.

Round-1 scope of masterserver.actor.cpp: getVersion (:786) — monotonically
increasing commit versions advancing ~VERSIONS_PER_SECOND with virtual wall
clock, handed out as (prev_version, version) pairs so resolvers and tlogs
can chain batches into a total order. Per-proxy request_num dedup mirrors
the reference's replyToProxies window. Recovery epochs arrive in a later
round; the seed master starts its epoch at version 1.
"""
from __future__ import annotations

from typing import Dict, Tuple

from ..core.types import VERSIONS_PER_SECOND, Version
from ..sim.loop import TaskPriority, now
from ..sim.network import SimProcess
from .messages import GetCommitVersionRequest, GetCommitVersionReply

GET_COMMIT_VERSION_TOKEN = "master.getCommitVersion"


class Master:
    def __init__(self, proc: SimProcess, start_version: Version = 1):
        self.proc = proc
        self.version: Version = start_version
        self.last_version_time: float = now()
        # proxy_id -> (request_num, reply) replay window
        self._proxy_window: Dict[str, Tuple[int, GetCommitVersionReply]] = {}
        proc.register(GET_COMMIT_VERSION_TOKEN, self.get_commit_version)

    async def get_commit_version(self, req: GetCommitVersionRequest) -> GetCommitVersionReply:
        """reference: getVersion, masterserver.actor.cpp:786-850."""
        last = self._proxy_window.get(req.proxy_id)
        if last is not None and last[0] == req.request_num:
            return last[1]  # retried request: same version pair
        t = now()
        advance = max(1, int((t - self.last_version_time) * VERSIONS_PER_SECOND))
        prev = self.version
        self.version = prev + advance
        self.last_version_time = t
        reply = GetCommitVersionReply(version=self.version, prev_version=prev)
        self._proxy_window[req.proxy_id] = (req.request_num, reply)
        return reply
