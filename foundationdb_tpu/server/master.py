"""Master: the commit-version authority.

Round-1 scope of masterserver.actor.cpp: getVersion (:786) — monotonically
increasing commit versions advancing ~VERSIONS_PER_SECOND with virtual wall
clock, handed out as (prev_version, version) pairs so resolvers and tlogs
can chain batches into a total order. Per-proxy request_num dedup mirrors
the reference's replyToProxies window. Recovery epochs arrive in a later
round; the seed master starts its epoch at version 1.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict

from ..core.knobs import SERVER_KNOBS
from ..core.types import VERSIONS_PER_SECOND, Version
from ..sim.loop import TaskPriority, now
from ..sim.network import SimProcess
from .messages import GetCommitVersionRequest, GetCommitVersionReply

GET_COMMIT_VERSION_TOKEN = "master.getCommitVersion"

#: Replies kept per proxy so a lost-reply repair re-query (by request_num)
#: replays the original version pair even after newer requests landed
#: (reference: lastCommitProxyVersionReplies window, masterserver.actor.cpp).
#: Correctness requires the lost request_num to still be inside the window
#: when the repair re-query lands; the proxy pipelines at most a handful of
#: phase-1 exchanges, and repair fires promptly on failure, so 256 leaves
#: orders of magnitude of headroom. Epoch recovery (which ends the whole
#: chain) is the backstop for anything outside it.
PROXY_REPLY_WINDOW = 256


#: a new epoch's recovery transaction jumps the version chain past the whole
#: MVCC window, so every pre-recovery read snapshot resolves TOO_OLD at the
#: fresh (empty) resolvers instead of silently missing lost conflict history
#: (reference: recoveryTransactionVersion jump, masterserver.actor.cpp:330;
#: applied by MasterServer's recovery transaction, masterserver.py).
RECOVERY_VERSION_JUMP = 2 * 5_000_000


class Master:
    def __init__(self, proc: SimProcess, start_version: Version = 1,
                 token_suffix: str = ""):
        self.proc = proc
        self.version: Version = start_version
        self.last_version_time: float = now()
        self.token = GET_COMMIT_VERSION_TOKEN + token_suffix
        # proxy_id -> {request_num: reply}, trimmed to PROXY_REPLY_WINDOW
        self._proxy_window: Dict[str, "OrderedDict[int, GetCommitVersionReply]"] = {}
        #: live resolutionBalancing flip: (flip_version, old_splits,
        #: new_splits) piggybacked on every version reply — versions below
        #: the flip were all handed out under the old map, versions at or
        #: above it are only ever handed out carrying the new one
        self._routing_flip: tuple = (0, (), ())
        #: future grants never fall below this (armed by a flip): the chain
        #: itself stays exactly the granted-version sequence — a BURNED
        #: version would wedge resolvers waiting when_at_least(prev) on a
        #: version nobody ever resolves
        self._version_floor: Version = 0
        proc.register(self.token, self.get_commit_version)

    def set_routing_flip(self, old_splits: tuple, new_splits: tuple) -> Version:
        """Arm a live resolver-map change: strictly newer than any granted
        version AND any earlier flip (back-to-back flips must not share a
        version — proxies order flips strictly); every later grant jumps to
        at least the flip, so no version in no-man's-land is ever handed
        out under an ambiguous map. Returns the flip version."""
        flip = max(self.version + 1, self._routing_flip[0] + 1)
        self._version_floor = flip
        self._routing_flip = (flip, tuple(old_splits), tuple(new_splits))
        return flip

    def unregister(self) -> None:
        self.proc.unregister(self.token)

    async def get_commit_version(self, req: GetCommitVersionRequest) -> GetCommitVersionReply:
        """reference: getVersion, masterserver.actor.cpp:786-850."""
        window = self._proxy_window.setdefault(req.proxy_id, OrderedDict())
        cached = window.get(req.request_num)
        if cached is not None:
            return cached  # retried request: same version pair
        t = now()
        advance = max(1, int((t - self.last_version_time) * SERVER_KNOBS.versions_per_second))
        prev = self.version
        self.version = max(prev + advance, self._version_floor)
        self.last_version_time = t
        flip, olds, news = self._routing_flip
        reply = GetCommitVersionReply(version=self.version, prev_version=prev,
                                      routing_version=flip,
                                      routing_old_splits=olds,
                                      routing_splits=news)
        window[req.request_num] = reply
        while len(window) > PROXY_REPLY_WINDOW:
            window.popitem(last=False)
        return reply
