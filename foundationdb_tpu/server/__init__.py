"""Server roles of the transaction system.

The reference hosts every role in one binary (fdbserver/worker.actor.cpp);
here each role is an async actor class registered on a SimProcess. Round-1
scope is the reference's "seed mode" minimum (masterserver.actor.cpp:325
newSeedServers + SURVEY.md §7.5): master (version authority), proxies (GRV +
5-phase pipelined commit), resolvers (TPU/oracle conflict engines behind the
same interface), tlogs (tag-partitioned in-memory log), storage servers
(MVCC reads), recruited statically by cluster.py.
"""
