"""Proxy: the transaction front door.

Re-design of fdbserver/MasterProxyServer.actor.cpp round-1 scope:

  * GRV path: requests batch over a short interval and are answered with the
    proxy's committed version (queueTransactionStartRequests:113,
    transactionStarter:947; ratekeeper admission arrives in a later round).
  * Commit path: commitBatch:319's five phases, pipelined across batches via
    two NotifiedVersion tokens exactly like the reference's
    latestLocalCommitBatchResolving/Logging (:362-364,414-415,424-426,
    800-803): batch N+1 may fetch its commit version while batch N resolves,
    and may resolve while N logs — but version-order is preserved at the
    resolver and tlog by (prev_version -> version) chaining.
  * Key-range sharding of resolution: each transaction's conflict ranges are
    split/clipped across resolvers by the static resolver shard map
    (ResolutionRequestBuilder::addTransaction:263-316); every touched
    resolver must vote COMMITTED; votes combine with min (:489-500). Every
    resolver receives every batch (possibly with zero transactions) so its
    version chain never stalls.
  * Serves GetKeyServerLocationsRequest from the static storage shard map
    (readRequestServer:1058).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core import buggify, error
from ..core.knobs import SERVER_KNOBS
from ..core.stats import CounterCollection
from ..core.trace import g_spans, span_event, span_now
from ..core.types import (
    CommitTransaction,
    Key,
    KeyRange,
    Mutation,
    MutationType,
    TransactionCommitResult,
    VERSIONSTAMP_MUTATIONS,
    Version,
    transform_versionstamp_mutation,
)
from ..core.keyshard import KeyShardMap
from ..sim.actors import ActorCollection, NotifiedVersion, PromiseStream, all_of, any_of
from ..sim.loop import Future, Promise, TaskPriority, delay, spawn
from ..sim.network import Endpoint, SimProcess
from .log_system import LogSystemClient, LogSystemConfig
from .system_keys import (
    BACKUP_ACTIVE_KEY,
    DB_LOCK_KEY,
    KEY_SERVERS_PREFIX,
    METADATA_TAG,
    decode_backup_active,
    decode_key_servers,
    is_system_key,
    shard_begin_of,
)
from .messages import (
    CommitReply,
    CommitTransactionRequest,
    GetCommitVersionRequest,
    GetKeyServerLocationsReply,
    GetKeyServerLocationsRequest,
    GetReadVersionReply,
    GetReadVersionRequest,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
)

GRV_TOKEN = "proxy.getReadVersion"
COMMIT_TOKEN = "proxy.commit"
LOCATIONS_TOKEN = "proxy.getKeyServerLocations"
STATS_TOKEN = "proxy.stats"
COMMITTED_VERSION_TOKEN = "proxy.committedVersion"
METADATA_VERSION_TOKEN = "proxy.metadataVersion"

#: batching intervals/caps come from the knob registry so BUGGIFY can
#: randomize them per simulation (reference: START_TRANSACTION_BATCH_* /
#: COMMIT_TRANSACTION_BATCH_* knobs, fdbserver/Knobs.cpp)
MAX_COMMIT_BATCH = 512
#: verdict sentinel: committed by the resolvers but rejected by the
#: database lock (never a TransactionCommitResult value)
_VERDICT_LOCKED = -2
#: empty-batch tick when idle (reference: the commitBatcher's max interval)
IDLE_COMMIT_INTERVAL = 0.5
#: reply timeout on proxy->master/resolver/tlog requests: an alive-but-
#: partitioned peer must fail the batch (commit_unknown_result + repair)
#: rather than wedge the pipeline forever (round-2 review finding).
SERVER_REQUEST_TIMEOUT = 5.0

_TLOG_STOPPED = error.tlog_stopped("").code


class RoutingState:
    """Mutable shard routing: the seed teams from ProxyConfig plus every
    applied `\\xff/keyServers/` mutation (ApplyMetadataMutation's effect on
    the proxy's keyServers cache). Whole-shard granularity: a keyServers
    key must name an existing shard begin."""

    def __init__(self, shards: KeyShardMap, teams):
        # private copy: splits/merges mutate the boundary list in place
        self.shards = KeyShardMap(list(shards.begins[1:]))
        self.teams = [list(t) for t in teams]
        self.extra_tags: List[tuple] = [() for _ in self.teams]
        #: live backup's log tag (None = no backup running)
        self.backup_tag: Optional[int] = None
        #: database lock (lockDatabase / DR switchover fence): user commits
        #: are rejected while set; lock-aware transactions pass
        self.db_locked = False

    def write_tags(self, s: int) -> List[int]:
        return [t for t, _a in self.teams[s]] + list(self.extra_tags[s])

    def addrs(self, s: int) -> List[str]:
        return [a for _t, a in self.teams[s]]

    def apply_mutation(self, m: Mutation) -> None:
        if m.type != MutationType.SET_VALUE:
            return
        if m.param1 == BACKUP_ACTIVE_KEY:
            self.backup_tag = decode_backup_active(m.param2)
            return
        if m.param1 == DB_LOCK_KEY:
            self.db_locked = m.param2 != b""
            return
        if not m.param1.startswith(KEY_SERVERS_PREFIX):
            return
        begin = shard_begin_of(m.param1)
        s = self.shards.shard_of_key(begin) if begin else 0
        team, extra = decode_key_servers(m.param2)
        if self.shards.begins[s] == begin:
            if not team:
                # boundary removal (DD merge): [begin, next) joins the
                # PREDECESSOR shard, whose team already absorbed the data
                if s > 0:
                    del self.shards.begins[s]
                    self.shards.n_shards -= 1
                    del self.teams[s]
                    del self.extra_tags[s]
                return
            self.teams[s] = list(team)
            self.extra_tags[s] = tuple(extra)
            return
        if not team:
            return
        # new boundary inside shard s (DD split): [begin, old_next) gets the
        # value's team; the lower part keeps shard s's current team
        self.shards.begins.insert(s + 1, begin)
        self.shards.n_shards += 1
        self.teams.insert(s + 1, list(team))
        self.extra_tags.insert(s + 1, tuple(extra))


def teams_from_storage_tags(storage_tags):
    """Group flat (tag, begin, end, addr) server records into the shard map
    + per-shard replica teams (servers with an identical range form a
    team). The inverse of the master's seed loop; also used wherever a
    persisted DBCoreState.storage_tags must become routing state."""
    by_range: Dict[Tuple[Key, Key], List[Tuple[int, str]]] = {}
    for tag, b, e, addr in storage_tags:
        by_range.setdefault((b, e), []).append((tag, addr))
    ordered = sorted(by_range.items(), key=lambda kv: kv[0][0])
    assert ordered and ordered[0][0][0] == b"", "shard map must start at ''"
    shard_map = KeyShardMap([b for (b, _e), _m in ordered[1:]])
    teams = [sorted(members) for (_rng, members) in ordered]
    return shard_map, teams


@dataclass
class ProxyConfig:
    """Wiring for one proxy of one generation: the master and resolvers are
    endpoint-addressed (tokens carry the generation suffix so a stale proxy
    can never reach a newer generation's roles), and commits flow through
    the replicated log system rather than a single tlog."""

    master_ep: Endpoint
    resolver_eps: List[Endpoint]
    resolver_shards: KeyShardMap
    log_config: LogSystemConfig
    #: per shard: the replica team [(tag, address), ...] — every member
    #: stores the shard (DataDistribution's keyServers reduced to a static
    #: team map; tags address tlog streams, one per storage server)
    storage_teams: List[List[Tuple[int, str]]]
    storage_shards: KeyShardMap
    #: the master's role-scoped wait-failure endpoint; the proxy watches it
    #: and shuts down when the master dies (its generation is over)
    master_wf_ep: Optional[Endpoint] = None
    #: ratekeeper endpoint (GetRateInfo); None = unthrottled
    rate_ep: Optional[Endpoint] = None
    #: committed-version endpoints of EVERY proxy in this generation
    #: (including this one); GRVs confirm the max committed version across
    #: all of them (getLiveCommittedVersion, MasterProxyServer.actor.cpp:897)
    peer_grv_eps: List[Endpoint] = field(default_factory=list)
    #: transactions per commit batch (None = MAX_COMMIT_BATCH); a pipelined
    #: resolver is fed batches sized to its compiled kernel shape T
    max_commit_batch: Optional[int] = None
    #: in-flight commit window (None = unbounded, today's behavior): at
    #: most this many batches between dispatch and fully-logged. While the
    #: window is full the batcher KEEPS ACCUMULATING arrivals, so resolver
    #: backpressure turns into larger batches — the feed a multi-batch
    #: in-flight resolver pipeline needs — instead of a deeper queue of
    #: tiny batches stalled at the version chain. Size it to the resolver
    #: pipeline depth + 1 (one batch accumulating, `depth` in service).
    commit_pipeline_window: Optional[int] = None
    #: per-tenant admission control (server/ratekeeper.py TenantAdmission;
    #: docs/real_cluster.md): None = off (every request rides the legacy
    #: path). Set, commits carrying a tenant id are token-bucket gated on
    #: the ratekeeper-published rate — one hot tenant sheds as fast typed
    #: transaction_throttled errors instead of queueing every tenant
    tenant_admission: Optional[object] = None


class Proxy:
    def __init__(self, proc: SimProcess, net, cfg: ProxyConfig, start_version: Version = 1):
        self.proc = proc
        self.net = net
        self.cfg = cfg
        self.log = LogSystemClient(net, proc.address, cfg.log_config,
                                   push_timeout=SERVER_REQUEST_TIMEOUT)
        self.committed_version = NotifiedVersion(start_version)
        self.batch_resolving = NotifiedVersion(0)
        self.batch_logging = NotifiedVersion(0)
        self._batch_num = 0
        self._request_num = 0
        #: bn -> (prev_version, version) for batches whose version is taken
        #: from the master but not yet durably chained (crash repair)
        self._batch_versions: Dict[int, Tuple[Version, Version]] = {}
        #: bn -> tagged messages, stashed once a push is ATTEMPTED: a failed
        #: push may have landed on some tlog replicas, so repair must re-push
        #: the identical payload (replicas dedupe by version) — an empty
        #: repair would leave the replicas of one version divergent
        self._batch_messages: Dict[int, Dict[int, List[Mutation]]] = {}
        #: bn -> master request_num for batches whose GetCommitVersion request
        #: is in flight; a lost reply may still have advanced the master's
        #: chain, so repair must re-query by request_num (the master's
        #: per-proxy dedup window replays the same version pair)
        self._pending_master_req: Dict[int, int] = {}
        self._grv_waiters: List[Promise] = []
        self._grv_flush_active = False
        #: dynamic shard routing (seed + applied keyServers metadata)
        self.routing = RoutingState(cfg.storage_shards, cfg.storage_teams)
        #: live resolutionBalancing flips, version-ascending: (flip_version,
        #: old_splits, new_splits) learned from the master's version
        #: replies. A batch splits by the newest flip at or below its
        #: commit version (never the single latest: with back-to-back
        #: flips, a batch between them must use the FIRST flip's map)
        self._routing_flips: List[tuple] = []
        #: metadata stream drained through this version (system_keys.py)
        self._metadata_version = start_version
        self._last_batch_time = 0.0
        self._commit_queue: PromiseStream = PromiseStream()
        #: reference: ProxyStats (MasterProxyServer.actor.cpp:48-80);
        #: counters ALSO feed the per-process TDMetric time-series, which
        #: a MetricLogger can persist into \xff/metrics/
        from ..core.tdmetric import TDMetricCollection
        from ..sim.loop import now as _sim_now

        self.tdmetrics = TDMetricCollection(now=_sim_now)
        self.stats = CounterCollection("Proxy", proc.address,
                                       tdmetrics=self.tdmetrics)
        #: ratekeeper admission (transactionStarter:947): GRVs are released
        #: from a budget replenished at tps_limit per second
        self._tps_limit: float = float("inf")
        #: adaptive commit-batch cap relayed from the resolvers' budget
        #: batchers through the ratekeeper (GetRateInfoReply); None =
        #: static cfg.max_commit_batch sizing only
        self._commit_batch_target: Optional[int] = None
        self._grv_budget: float = 0.0
        self._grv_budget_t: float = 0.0
        self._dead = False
        #: conflict-aware admission scheduling (pipeline/scheduler.py),
        #: knob-gated hard off by default (`resolver_sched`): between the
        #: dynamic batcher and dispatch, the scheduler may pre-abort
        #: predicted-doomed commits, capture hot-range writers into
        #: serialization lanes, and defer separation losers into
        #: `_sched_carry` (consumed ahead of the next batch's arrivals)
        from ..pipeline.scheduler import ConflictScheduler, SchedConfig

        self.conflict_sched = ConflictScheduler(
            SchedConfig.from_knobs(), entry_txn=lambda e: e[0])
        self._sched_carry: List[Tuple[CommitTransaction, Promise]] = []
        #: proxy-owned tasks: cancelled on shutdown() without touching other
        #: roles hosted by the same worker process
        self.actors = ActorCollection()
        proc.register(GRV_TOKEN, self.get_read_version)
        proc.register(COMMIT_TOKEN, self.commit)
        proc.register(LOCATIONS_TOKEN, self.get_key_server_locations)
        proc.register(STATS_TOKEN, self._stats_req)
        proc.register(COMMITTED_VERSION_TOKEN, self._committed_version_req)
        proc.register(METADATA_VERSION_TOKEN, self._metadata_version_req)
        self._spawn(self.commit_batcher(), TaskPriority.PROXY_COMMIT_BATCHER, "commitBatcher")
        self._spawn(self.idle_committer(), TaskPriority.PROXY_COMMIT_BATCHER, "idleCommitter")
        self._spawn(self.stats.run_logger(), TaskPriority.PROXY_GRV_TIMER, "proxyStats")
        if cfg.master_wf_ep is not None:
            self._spawn(self._watch_master(), TaskPriority.FAILURE_MONITOR, "watchMaster")
        if cfg.rate_ep is not None:
            self._spawn(self._rate_fetcher(), TaskPriority.RATEKEEPER, "rateFetcher")

    async def _rate_fetcher(self) -> None:
        """Fetch the admission rate (getRate loop,
        MasterProxyServer.actor.cpp:86); a stale limit is kept on errors."""
        from .ratekeeper import GetRateInfoRequest

        while True:
            try:
                reply = await self.net.request(
                    self.proc.address, self.cfg.rate_ep,
                    GetRateInfoRequest(self.proc.address),
                    TaskPriority.RATEKEEPER, timeout=1.0,
                )
                self._tps_limit = reply.tps_limit
                self._commit_batch_target = getattr(
                    reply, "commit_batch_target", None)
                if self.cfg.tenant_admission is not None:
                    # the same published rate that meters GRV release also
                    # feeds the per-tenant commit admission buckets
                    self.cfg.tenant_admission.set_rate(reply.tps_limit)
            except error.FDBError:
                pass
            await delay(SERVER_KNOBS.ratekeeper_update_interval, TaskPriority.RATEKEEPER)

    def _replenish_grv_budget(self) -> None:
        from ..sim.loop import now

        t = now()
        if self._tps_limit == float("inf"):
            self._grv_budget = float("inf")
        else:
            dt = max(0.0, t - self._grv_budget_t)
            if self._grv_budget == float("inf"):
                self._grv_budget = 0.0
            # cap the burst at ~100ms of budget (reference: the smoothed
            # release window in transactionStarter)
            self._grv_budget = min(self._grv_budget + self._tps_limit * dt,
                                   max(1.0, self._tps_limit * 0.1))
        self._grv_budget_t = t

    async def _watch_master(self) -> None:
        """The master's death ends this generation: stop serving
        (reference: proxies monitor masterLifetime through ServerDBInfo)."""
        from .wait_failure import wait_failure_client

        await wait_failure_client(self.net, self.proc.address, self.cfg.master_wf_ep)
        self.shutdown()

    def _spawn(self, coro, priority, name):
        t = spawn(coro, priority, name=name)
        self.proc.actors.add(t)
        self.actors.add(t)
        return t

    def shutdown(self) -> None:
        """This generation is over (epoch ended by a successor, or the role
        was replaced): stop serving, cancel proxy-owned actors. In-flight
        clients get commit_unknown_result via cancellation/broken futures —
        the honest answer, since the successor generation decides which of
        our versions survived."""
        if self._dead:
            return
        self._dead = True
        for tok in (GRV_TOKEN, COMMIT_TOKEN, LOCATIONS_TOKEN, STATS_TOKEN,
                    COMMITTED_VERSION_TOKEN, METADATA_VERSION_TOKEN):
            self.proc.unregister(tok)
        # laned/carried commits this generation will never dispatch: the
        # successor decides nothing about them, so the honest answer is
        # the same broken-promise path every other queued commit gets
        for _t, pr in self.conflict_sched.flush() + self._sched_carry:
            if not pr.is_set:
                pr.send_error(error.commit_unknown_result("proxy shutdown"))
        self._sched_carry = []
        self.actors.cancel_all()

    async def _stats_req(self, _req):
        return self.stats.as_dict()

    async def _committed_version_req(self, _req) -> Version:
        return self.committed_version.get()

    async def _metadata_version_req(self, _req) -> Version:
        """How far this proxy has drained METADATA_TAG — the master's DD
        pops the tag at the minimum over proxies (the reference resolver's
        GC by oldest proxy version, Resolver.actor.cpp:198-224)."""
        return self._metadata_version

    # -- GRV path ------------------------------------------------------------
    async def get_read_version(self, req: GetReadVersionRequest) -> GetReadVersionReply:
        p = Promise()
        self._grv_waiters.append(p)
        if not self._grv_flush_active:
            # explicit flag, not len()==1: the flusher empties the list and
            # then awaits the peer quorum, during which a new arrival would
            # otherwise spawn a second concurrent flusher
            self._grv_flush_active = True
            self._spawn(self._grv_flush(), TaskPriority.PROXY_GRV_TIMER, "grvBatch")
        version = await p.future
        if buggify.buggify():
            # reply delivery lag: the client's GRV is extra stale by the
            # time it reads — MVCC windows and too-old paths get exercised
            await delay(0.05, TaskPriority.PROXY_GRV_TIMER)
        self.stats.add("txn_start_out")
        return GetReadVersionReply(version=max(version, self.committed_version.get()))

    async def _live_committed_version(self) -> Version:
        """Max committed version across EVERY proxy of the generation
        (getLiveCommittedVersion:897): a commit acked by a peer proxy must
        be visible to reads started here afterwards. All peers must reply —
        an unreachable peer may hold the newest acks, so GRVs fail (clients
        retry) until it answers or recovery replaces the generation, exactly
        the reference's confirm-epoch-live stall."""
        own = self.committed_version.get()
        others = [ep for ep in self.cfg.peer_grv_eps
                  if ep.address != self.proc.address]
        if not others:
            return own
        replies = await all_of([
            self.net.request(self.proc.address, ep, None,
                             TaskPriority.PROXY_GRV_TIMER,
                             timeout=SERVER_REQUEST_TIMEOUT)
            for ep in others
        ])
        return max(own, *replies)

    async def _grv_flush(self) -> None:
        """Release queued GRVs within the ratekeeper budget; leftovers wait
        for the next interval's replenishment (back-pressure surfaces as
        start-transaction latency, never an error)."""
        try:
            while True:
                await delay(SERVER_KNOBS.grv_batch_interval, TaskPriority.PROXY_GRV_TIMER)
                self._replenish_grv_budget()
                n = len(self._grv_waiters)
                if self._grv_budget != float("inf"):
                    n = min(n, int(self._grv_budget))
                    self._grv_budget -= n
                release, self._grv_waiters = self._grv_waiters[:n], self._grv_waiters[n:]
                try:
                    version = await self._live_committed_version()
                except error.FDBError as e:
                    # A peer proxy is unreachable: these starts cannot be
                    # causally confirmed. Fail them retryably.
                    for p in release:
                        if not p.is_set:
                            p.send_error(error.connection_failed(
                                f"proxy liveness quorum failed: {e.name}"))
                    if not self._grv_waiters:
                        return
                    continue
                for p in release:
                    p.send(version)
                if not self._grv_waiters:
                    return
        finally:
            self._grv_flush_active = False

    # -- locations -----------------------------------------------------------
    async def get_key_server_locations(self, req: GetKeyServerLocationsRequest) -> GetKeyServerLocationsReply:
        out: List[Tuple[KeyRange, List[str]]] = []
        for s, cb, ce in self.routing.shards.shards_of_range(req.begin, req.end):
            out.append((KeyRange(cb, ce), self.routing.addrs(s)))
        return GetKeyServerLocationsReply(results=out)

    # -- commit path -----------------------------------------------------------
    async def commit(self, req: CommitTransactionRequest) -> CommitReply:
        self.stats.add("txn_commit_in")
        adm = self.cfg.tenant_admission
        tenant = getattr(req, "tenant", None)
        if adm is not None and tenant is not None:
            from ..sim.loop import now as _now

            if not adm.admit(tenant, _now()):
                # shed BEFORE the batcher: a rejected commit costs the
                # tenant a typed error and a client-side backoff, never a
                # slot in the batch queue (docs/real_cluster.md)
                self.stats.add("txn_commit_throttled")
                raise error.transaction_throttled(f"tenant {tenant}")
        p = Promise()
        self._commit_queue.send((req.transaction, p))
        try:
            return await p.future
        except error.FDBError as e:
            if (e.name == "transaction_conflict_predicted"
                    and adm is not None and tenant is not None):
                # a pre-abort consumed no resolver capacity: hand the
                # admission token back so the client's refreshed retry
                # isn't double-charged (server/ratekeeper.py refund)
                adm.refund(tenant)
            raise

    async def idle_committer(self) -> None:
        """Commit an empty batch when idle (the reference's interval-driven
        commitBatcher): keeps the version chain, the tlogs' KCV horizon and
        — critically — every proxy's metadata drain advancing even with no
        client traffic, so routing changes (MoveKeys) become visible
        without waiting for the next client commit."""
        from ..sim.loop import now

        while not self._dead:
            interval = IDLE_COMMIT_INTERVAL
            if buggify.buggify():
                # hyperactive idle committer: floods the version chain with
                # empty batches (tiny version deltas, KCV churn)
                interval = IDLE_COMMIT_INTERVAL / 10
            await delay(interval, TaskPriority.PROXY_COMMIT_BATCHER)
            if now() - self._last_batch_time < IDLE_COMMIT_INTERVAL:
                continue
            W = self.cfg.commit_pipeline_window
            if W and self.batch_logging.get() < self._batch_num + 1 - W:
                # in-flight window full: an empty batch can't advance the
                # KCV horizon (phase-4 pushes are ordered behind the stall)
                # and would breach the bound the window exists to enforce
                continue
            sched = self.conflict_sched
            items: List[Tuple[CommitTransaction, Promise]] = []
            if sched.enabled and (self._sched_carry or sched.pending_laned()):
                # idle drain: laned and carried transactions must keep
                # flowing when no fresh commit wakes the batcher — the
                # idle batch carries them instead of running empty
                cap = min(self.cfg.max_commit_batch or MAX_COMMIT_BATCH,
                          SERVER_KNOBS.commit_transaction_batch_count_max)
                plan = sched.select(self._sched_carry, cap)
                self._sched_carry = plan.remaining
                for (_t, pr), rng in plan.preaborts:
                    if not pr.is_set:
                        self.stats.add("txn_commit_preaborted")
                        pr.send_error(error.transaction_conflict_predicted(
                            f"range {rng.hex()}"))
                items = plan.dispatch
            self._batch_num += 1
            self._last_batch_time = now()
            self._spawn(
                self.commit_batch(self._batch_num, items),
                TaskPriority.PROXY_COMMIT_DISPATCH,
                f"idleBatch:{self._batch_num}",
            )

    async def commit_batcher(self) -> None:
        """Dynamic-interval batcher (reference: batcher.actor.h via
        MasterProxyServer.actor.cpp:880-886)."""
        pending = self._commit_queue.stream.pop()
        while True:
            first = await pending
            pending = self._commit_queue.stream.pop()
            batch = [first]
            deadline = delay(SERVER_KNOBS.commit_transaction_batch_interval,
                             TaskPriority.PROXY_COMMIT_BATCHER)
            cap = min(self.cfg.max_commit_batch or MAX_COMMIT_BATCH,
                      SERVER_KNOBS.commit_transaction_batch_count_max)
            if self._commit_batch_target is not None:
                # budget-driven sizing (pipeline/resolver_pipeline.py
                # BudgetBatcher via ratekeeper): batches beyond the largest
                # in-budget resolver bucket would blow the p99 commit budget
                cap = max(1, min(cap, self._commit_batch_target))
            if buggify.buggify():
                cap = 1  # force single-transaction batches: deep pipelines
            while len(batch) < cap:
                which, _ = await any_of([pending, deadline])
                if which == 1:
                    break
                batch.append(pending.get())
                pending = self._commit_queue.stream.pop()
            W = self.cfg.commit_pipeline_window
            # In-flight window gate: dispatch only when fewer than W batches
            # sit between dispatch and fully-logged; keep filling the batch
            # (up to cap) while waiting so backpressure becomes batch size,
            # not queue depth. Re-checked against a fresh _batch_num each
            # pass — the idle committer may claim numbers while we wait.
            while W and self.batch_logging.get() < self._batch_num + 1 - W:
                gate = self.batch_logging.when_at_least(self._batch_num + 1 - W)
                while not gate.is_ready and len(batch) < cap:
                    which, _ = await any_of([pending, gate])
                    if which == 0:
                        batch.append(pending.get())
                        pending = self._commit_queue.stream.pop()
                if not gate.is_ready:
                    await gate
            sched = self.conflict_sched
            if sched.enabled:
                # conflict-aware admission (pipeline/scheduler.py): the
                # carry (previous ticks' deferrals) goes ahead of this
                # batch's arrivals; pre-aborted commits are rejected here
                # with the retryable typed error, laned/deferred entries
                # wait in the scheduler or the carry for a later batch
                plan = sched.select(self._sched_carry + batch, cap)
                self._sched_carry = plan.remaining
                for (_t, pr), rng in plan.preaborts:
                    if not pr.is_set:
                        self.stats.add("txn_commit_preaborted")
                        pr.send_error(error.transaction_conflict_predicted(
                            f"range {rng.hex()}"))
                batch = plan.dispatch
            self._batch_num += 1
            from ..sim.loop import now as _now

            self._last_batch_time = _now()
            self._spawn(
                self.commit_batch(self._batch_num, batch),
                TaskPriority.PROXY_COMMIT_DISPATCH,
                f"commitBatch:{self._batch_num}",
            )

    async def commit_batch(self, bn: int, items: List[Tuple[CommitTransaction, Promise]]) -> None:
        try:
            await self._commit_batch_impl(bn, items)
        except error.FDBError as e:
            # A role failed mid-batch: clients must assume the worst
            # (commit_unknown_result); epoch-end recovery decides which
            # in-flight versions survived.
            self.batch_resolving.advance(bn)
            self.batch_logging.advance(bn)
            versions = self._batch_versions.pop(bn, None)
            attempted = self._batch_messages.pop(bn, None)
            pending_rn = self._pending_master_req.pop(bn, None)
            if e.code == _TLOG_STOPPED:
                # Our generation has been locked by a successor: this proxy
                # is permanently done. No repair — the successor owns the
                # chain now.
                for _, pr in items:
                    if not pr.is_set:
                        pr.send_error(error.commit_unknown_result(e.name))
                self.shutdown()
                return
            if versions is not None:
                # Version v is in the master's chain but may never have
                # reached the resolvers/tlog; plug the hole or every later
                # batch waits on when_at_least(v) forever. Resolvers and the
                # tlog dedupe versions, so repair is idempotent. A batch
                # that already ATTEMPTED its push repairs with the original
                # payload (see _batch_messages).
                self._spawn(self._repair_chain(*versions, messages=attempted or {}),
                            TaskPriority.PROXY_COMMIT, f"repair:{bn}")
            elif pending_rn is not None:
                # The GetCommitVersion reply was lost (request_maybe_delivered)
                # — the master may still have advanced its chain for us. Ask
                # again with the same request_num: the dedup window replays the
                # same (prev, version) pair if the original landed, or mints a
                # fresh pair (which we immediately plug) if it never did.
                self._spawn(
                    self._repair_unknown_version(pending_rn),
                    TaskPriority.PROXY_COMMIT,
                    f"repairUnknown:{bn}",
                )
            for _, p in items:
                if not p.is_set:
                    p.send_error(error.commit_unknown_result(e.name))

    async def _drain_metadata(self, upto: Version) -> None:
        """Apply every METADATA_TAG entry with version <= upto to the
        routing state. The peek horizon is the log's known-committed
        version; while it trails, re-advertise our own committed version to
        the replicas (the KCV one-ways after an ack are unreliable, and the
        next carrier would otherwise be the very push this drain gates)."""
        attempts = 0
        while self._metadata_version < upto and not self._dead:
            if buggify.buggify():
                # stall the drain: later batches pile up behind phase 3.5
                await delay(0.05, TaskPriority.PROXY_COMMIT)
            floor_before = self._metadata_version
            try:
                # Advertise our committed version first: the peek blocks on
                # the tlog's known-committed horizon, and when no later push
                # is in flight to carry the KCV forward (an idle or sparse
                # commit pipeline), the replica would otherwise sit at the
                # full peek timeout before the retry path advertises it.
                self.log.send_kcv(self.committed_version.get())
                reply = await self.log.peek(
                    METADATA_TAG, self._metadata_version + 1, timeout=1.0)
            except error.FDBError as e:
                attempts += 1
                if attempts >= int(SERVER_REQUEST_TIMEOUT * 4):
                    raise
                self.log.send_kcv(self.committed_version.get())
                await delay(0.25, TaskPriority.PROXY_COMMIT)
                continue
            for mv, muts in reply.messages:
                if mv <= self._metadata_version or mv > upto:
                    continue
                for m in muts:
                    self.routing.apply_mutation(m)
            new_floor = min(reply.end_version, upto)
            if new_floor <= floor_before:
                if self._metadata_version > floor_before:
                    # A concurrent drain (phase 3.5 of an overlapping batch)
                    # advanced the floor while our peek was in flight: that
                    # is progress, not a stall — re-check immediately. The
                    # backoff below would otherwise park this batch (and,
                    # through the ordered phase-4 push, every batch behind
                    # it) for the full retry interval.
                    continue
                attempts += 1
                if attempts >= int(SERVER_REQUEST_TIMEOUT * 4):
                    raise error.timed_out("metadata drain stalled")
                self.log.send_kcv(self.committed_version.get())
                await delay(0.25, TaskPriority.PROXY_COMMIT)
                continue
            attempts = 0
            self._metadata_version = max(self._metadata_version, new_floor)

    async def _repair_unknown_version(self, request_num: int) -> None:
        """Recover the version pair for a lost GetCommitVersion exchange and
        plug the resulting chain hole (ADVICE r1: a lost master reply after
        the master advanced left an orphaned version that stalled every later
        batch's when_at_least)."""
        while not self._dead:
            try:
                vr = await self.net.request(
                    self.proc.address,
                    self.cfg.master_ep,
                    GetCommitVersionRequest(request_num, self.proc.address),
                    TaskPriority.PROXY_COMMIT,
                    timeout=SERVER_REQUEST_TIMEOUT,
                )
                break
            except error.FDBError as e:
                if e.code == _TLOG_STOPPED:
                    self.shutdown()
                    return
                await delay(0.1)
        if self._dead:
            return
        await self._repair_chain(vr.prev_version, vr.version)

    async def _repair_chain(self, prev_v: Version, v: Version,
                            messages: Optional[Dict[int, List[Mutation]]] = None) -> None:
        """Push a batch for (prev_v, v) until every chained consumer has it
        — with the ORIGINAL payload when the failed batch had already
        attempted its push (a partial push may have landed on some tlog
        replicas; re-pushing identical bytes converges them, an empty push
        would diverge them). Epoch-ending recovery supersedes it when this
        generation is deposed (shutdown cancels the loop)."""
        while not self._dead:
            try:
                for ep in self.cfg.resolver_eps:
                    await self.net.request(
                        self.proc.address,
                        ep,
                        ResolveTransactionBatchRequest(
                            prev_version=prev_v, version=v,
                            last_received_version=prev_v, transactions=[],
                        ),
                        TaskPriority.PROXY_RESOLVER_REPLY,
                        timeout=SERVER_REQUEST_TIMEOUT,
                    )
                await self.log.push(prev_v, v, messages or {},
                                    self.committed_version.get())
                if v > self.committed_version.get():
                    self.committed_version.set(v)
                return
            except error.FDBError as e:
                if e.code == _TLOG_STOPPED:
                    self.shutdown()
                    return
                await delay(0.1)

    async def _commit_batch_impl(self, bn: int, items: List[Tuple[CommitTransaction, Promise]]) -> None:
        cfg = self.cfg
        n_res = len(cfg.resolver_eps)
        # span anchors (docs/observability.md): the batch's trace id is its
        # commit version, known only after phase 1 — timestamps are taken
        # along the way and the spans emitted retroactively
        spans_on = g_spans.enabled
        t_start = span_now() if spans_on else 0.0

        # ---- Phase 1: take a commit version, in batch order (:361) ----
        await self.batch_resolving.when_at_least(bn - 1)
        self._request_num += 1
        self._pending_master_req[bn] = self._request_num
        vr = await self.net.request(
            self.proc.address,
            cfg.master_ep,
            GetCommitVersionRequest(self._request_num, self.proc.address),
            TaskPriority.PROXY_COMMIT,
            timeout=SERVER_REQUEST_TIMEOUT,
        )
        self._pending_master_req.pop(bn, None)
        prev_v, v = vr.prev_version, vr.version
        self._batch_versions[bn] = (prev_v, v)
        if spans_on:
            t_version = span_now()
            span_event("proxy.get_version", v, t_start, t_version,
                       parent="proxy.commit_batch")
        rv = getattr(vr, "routing_version", 0)
        if rv and (not self._routing_flips or rv > self._routing_flips[-1][0]):
            self._routing_flips.append((rv, tuple(vr.routing_old_splits),
                                        tuple(vr.routing_splits)))
        # The resolver map THIS batch splits by: the newest flip at or
        # below its commit version (phase 1 orders flips exactly —
        # versions >= a flip are only ever handed out carrying it)
        flip_v, _flip_old, flip_new = 0, (), ()
        for fv, fo, fn in reversed(self._routing_flips):
            if v >= fv:
                flip_v, _flip_old, flip_new = fv, fo, fn
                break
        if flip_v:
            res_shards = KeyShardMap(list(flip_new))
        else:
            res_shards = cfg.resolver_shards

        # Build per-resolver transaction views (clipped conflict ranges).
        per_res: List[List[CommitTransaction]] = [[] for _ in range(n_res)]
        # txn -> [(resolver, index within that resolver's batch)]
        per_res_idx: List[List[Tuple[int, int]]] = []
        for t, (txn, _) in enumerate(items):
            views: Dict[int, CommitTransaction] = {}

            def view(r: int) -> CommitTransaction:
                if r not in views:
                    views[r] = CommitTransaction(read_snapshot=txn.read_snapshot)
                return views[r]

            for rng in txn.read_conflict_ranges:
                if rng.begin >= rng.end:
                    r = res_shards.shard_of_point_below(rng.begin)
                    view(r).read_conflict_ranges.append(rng)
                else:
                    for r, cb, ce in res_shards.shards_of_range(rng.begin, rng.end):
                        view(r).read_conflict_ranges.append(KeyRange(cb, ce))
            for rng in txn.write_conflict_ranges:
                if rng.begin < rng.end:
                    for r, cb, ce in res_shards.shards_of_range(rng.begin, rng.end):
                        view(r).write_conflict_ranges.append(KeyRange(cb, ce))
            placed = []
            for r, vw in views.items():
                placed.append((r, len(per_res[r])))
                per_res[r].append(vw)
            per_res_idx.append(placed)

        if buggify.buggify():
            # Stretch phase 1->2 so more batches pile into the pipeline.
            await delay(0.01, TaskPriority.PROXY_COMMIT)

        # ---- Phase 2: resolve everywhere; next batch may start (:417) ----
        attach_flip = flip_v if (flip_v and v >= flip_v) else 0
        resolve_futures = [
            self.net.request(
                self.proc.address,
                ep,
                ResolveTransactionBatchRequest(
                    prev_version=prev_v,
                    version=v,
                    last_received_version=prev_v,
                    transactions=per_res[r],
                    routing_version=attach_flip,
                    routing_old_splits=_flip_old if attach_flip else (),
                    routing_splits=flip_new if attach_flip else (),
                ),
                TaskPriority.PROXY_RESOLVER_REPLY,
                timeout=SERVER_REQUEST_TIMEOUT,
            )
            for r, ep in enumerate(cfg.resolver_eps)
        ]
        self.batch_resolving.advance(bn)
        replies: List[ResolveTransactionBatchReply] = await all_of(resolve_futures)
        if spans_on:
            t_resolved = span_now()
            span_event("proxy.resolve_rpc", v, t_version, t_resolved,
                       parent="proxy.commit_batch")

        # ---- Phase 3: combine votes with min (:489-500) ----
        verdicts: List[int] = []
        for t in range(len(items)):
            placed = per_res_idx[t]
            if not placed:
                verdicts.append(int(TransactionCommitResult.COMMITTED))
            else:
                verdicts.append(min(int(replies[r].committed[i]) for r, i in placed))

        # ---- Phase 3.5: drain the metadata stream to prev_v ----
        # Routing below must reflect every keyServers change with version
        # <= prev_v (commit versions form one global chain, so prev_v is
        # exactly "everything before this batch"). The committing proxy of
        # a metadata txn copies its system mutations into METADATA_TAG
        # (phase 4 below), which this drain consumes — the txnState-tag /
        # ApplyMetadataMutation circuit of the reference.
        await self._drain_metadata(prev_v)
        if spans_on:
            t_drained = span_now()
            span_event("proxy.meta_drain", v, t_resolved, t_drained,
                       parent="proxy.commit_batch")

        # Database lock (lockDatabase / DR switchover): authoritative
        # through prev_v after the drain. User transactions are rejected;
        # lock-aware (management) transactions pass. A commit sharing the
        # LOCK transaction's own batch still lands at the fence version and
        # is drained by DR — nothing a client saw acked is lost.
        if self.routing.db_locked:
            for t, (txn, _p) in enumerate(items):
                if (verdicts[t] == int(TransactionCommitResult.COMMITTED)
                        and not getattr(txn, "lock_aware", False)):
                    verdicts[t] = _VERDICT_LOCKED

        if self.conflict_sched.enabled and items:
            # predictor feedback (pipeline/scheduler.py): committed writes
            # stamp last-write versions, conflicts re-score their ranges
            self.conflict_sched.observe_batch(
                [txn for txn, _p in items], verdicts, v)

        # Assign committed mutations to storage tags, preserving batch order.
        # Versionstamped mutations become SET_VALUE here, stamped with
        # (commit version, index in batch) — the reference does this while
        # building resolver requests (MasterProxyServer.actor.cpp:270-275);
        # doing it post-verdict is equivalent because only the mutation
        # payload changes, never the conflict ranges.
        messages: Dict[int, List[Mutation]] = {}
        meta_muts: List[Mutation] = []
        backup_muts: List[Mutation] = []
        for t, (txn, _) in enumerate(items):
            if verdicts[t] != int(TransactionCommitResult.COMMITTED):
                continue
            for m in txn.mutations:
                if m.type in VERSIONSTAMP_MUTATIONS:
                    m = transform_versionstamp_mutation(m, v, t)
                if m.type != MutationType.CLEAR_RANGE and is_system_key(m.param1):
                    meta_muts.append(m)
                elif self.routing.backup_tag is not None:
                    # live backup: copy every committed USER mutation into
                    # the backup's log tag (the reference's backup ranges
                    # via ApplyMetadataMutation)
                    backup_muts.append(m)
                # Every team member's tag receives the mutation (the
                # reference tags each mutation for all replicas of its
                # shard, MasterProxyServer.actor.cpp:516-756).
                if m.type == MutationType.CLEAR_RANGE:
                    for s, cb, ce in self.routing.shards.shards_of_range(m.param1, m.param2):
                        clipped = Mutation(m.type, cb, ce)
                        for tag in self.routing.write_tags(s):
                            messages.setdefault(tag, []).append(clipped)
                else:
                    s = self.routing.shards.shard_of_key(m.param1)
                    for tag in self.routing.write_tags(s):
                        messages.setdefault(tag, []).append(m)
        if meta_muts:
            messages[METADATA_TAG] = meta_muts
        if backup_muts and self.routing.backup_tag is not None:
            messages[self.routing.backup_tag] = backup_muts

        # ---- Phase 4: log, in version order (:805) ----
        await self.batch_logging.when_at_least(bn - 1)
        self._batch_messages[bn] = messages
        await self.log.push(prev_v, v, messages, self.committed_version.get())
        self._batch_messages.pop(bn, None)
        self.batch_logging.advance(bn)
        if spans_on:
            t_logged = span_now()
            span_event("proxy.log_push", v, t_drained, t_logged,
                       parent="proxy.commit_batch")
            span_event("proxy.commit_batch", v, t_start, t_logged,
                       txns=len(items))
        # Apply our own committed metadata now (idempotent under the later
        # drain): this proxy's location replies must reflect a move it
        # itself just committed.
        for m in meta_muts:
            self.routing.apply_mutation(m)

        # ---- Phase 5: report (:824-860) ----
        self._batch_versions.pop(bn, None)
        if v > self.committed_version.get():
            self.committed_version.set(v)
            # Advertise the new KCV to the replicas now (unreliable one-way;
            # the drain's retry path re-sends on loss). Without this the
            # next batch's metadata drain waits on its OWN push for the
            # horizon — storage pops used to paper over it by carrying
            # fresh durable versions, which the durable tier no longer does.
            self.log.send_kcv(v)
        for t, (_, p) in enumerate(items):
            verdict = verdicts[t]
            if verdict == int(TransactionCommitResult.COMMITTED):
                self.stats.add("txn_committed")
                p.send(CommitReply(version=v, txn_batch_index=t))
            elif verdict == int(TransactionCommitResult.TOO_OLD):
                self.stats.add("txn_too_old")
                p.send_error(error.transaction_too_old())
            elif verdict == _VERDICT_LOCKED:
                self.stats.add("txn_rejected_locked")
                p.send_error(error.database_locked())
            else:
                self.stats.add("txn_conflicted")
                p.send_error(error.not_committed())
