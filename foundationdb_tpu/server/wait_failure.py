"""Wait-failure keepalive protocol.

Re-design of fdbserver/WaitFailure.actor.cpp: a role exposes a tiny ping
endpoint; watchers ping it in a loop with a reply-timeout. Silence — whether
from death, partition, or severe clogging — is treated as failure. This is
the mechanism by which the cluster controller notices a dead master and the
master notices dead tlogs/resolvers/proxies, turning "a partitioned request
hangs forever" into a detected role failure (round-1 VERDICT weak #4).

The server holds each ping for `hold` seconds before replying, so a healthy
link costs one round trip per `hold` interval; the client allows
`hold + react` seconds before declaring failure, giving a detection latency
of about `react` after the last successful exchange.
"""
from __future__ import annotations

from ..core import error
from ..sim.loop import TaskPriority, delay
from ..sim.network import Endpoint, SimProcess

WAIT_FAILURE_TOKEN = "waitFailure"

#: reference knobs WAIT_FAILURE_DELAY_LIMIT / FAILURE_REACTION_TIME analogs
HOLD_SECONDS = 0.5
REACT_SECONDS = 1.0


def serve_wait_failure(proc: SimProcess, token: str = WAIT_FAILURE_TOKEN) -> Endpoint:
    """Register the keepalive endpoint on a role's process."""

    async def handler(_req) -> None:
        await delay(HOLD_SECONDS, TaskPriority.FAILURE_MONITOR)
        return None

    return proc.register(token, handler)


async def wait_failure_client(
    net,
    src_addr: str,
    endpoint: Endpoint,
    react_seconds: float = REACT_SECONDS,
) -> None:
    """Returns (normally) when the endpoint is considered failed
    (reference: waitFailureClient). Cancel the surrounding actor to stop
    watching."""
    while True:
        try:
            await net.request(
                src_addr,
                endpoint,
                None,
                TaskPriority.FAILURE_MONITOR,
                timeout=HOLD_SECONDS + react_seconds,
            )
        except error.FDBError:
            # connection_failed / request_maybe_delivered / timeout: failed.
            return
