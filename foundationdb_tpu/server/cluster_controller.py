"""Cluster controller: the elected singleton that owns recruitment.

Re-design of fdbserver/ClusterController.actor.cpp round-2 scope:

  * worker registry fed by registration heartbeats (registrationClient /
    workerAvailabilityWatch:1272); replies carry the latest ServerDBInfo so
    registration doubles as the broadcast channel.
  * clusterWatchDatabase (:1000): keep exactly one master alive — pick a
    worker, hand it the recovery brief, watch its role-scoped wait-failure
    endpoint, recruit a successor the moment it dies. The master itself
    runs the epoch recovery state machine (masterserver.py) and reports
    back with the recovered ServerDBInfo.
  * openDatabase (:1127): clients fetch the proxy list here.

The CC is pure control plane: killing it stalls recruitment until a new
leader is elected but never blocks the data path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..core import error
from ..core.trace import TraceEvent
from ..sim.actors import ActorCollection
from ..sim.loop import TaskPriority, delay, now, spawn
from ..sim.network import Endpoint
from .wait_failure import wait_failure_client
from .worker import InitializeMasterRequest, ServerDBInfo

CC_REGISTER_TOKEN = "cc.registerWorker"
CC_OPEN_DATABASE_TOKEN = "cc.openDatabase"
CC_MASTER_RECOVERED_TOKEN = "cc.masterRecovered"
CC_STATUS_TOKEN = "cc.status"

#: a worker silent this long is not considered for recruitment
WORKER_STALE_SECONDS = 2.0


@dataclass
class WorkerRegisterRequest:
    addr: str
    known_info_version: int = -1
    #: role kinds this worker currently hosts (for the status document's
    #: machine layer; reference: worker details in Status.actor.cpp)
    roles: tuple = ()
    #: (machine_id, dc_id) — the sim's LocalityData (fdbrpc/Locality.h),
    #: feeding the master's replication policy
    locality: tuple = ("", "")


@dataclass
class OpenDatabaseRequest:
    known_info_version: int = -1


class ClusterController:
    def __init__(self, worker):
        """Constructed by the winning worker's candidacy loop; `worker` is
        the hosting Worker (its process, net, coordinators, cluster_cfg)."""
        self.worker = worker
        self.net = worker.net
        self.proc = worker.proc
        #: addr -> role kinds last reported in registration
        self.worker_roles = {}
        self.worker_locality = {}
        #: (recovery_count, sim time) for every master hand-over seen
        self.recovery_history = []
        self.coords = worker.coords
        self.cluster_cfg = worker.cluster_cfg
        self.workers: Dict[str, float] = {}            # addr -> last_seen
        self.db_info = ServerDBInfo(info_version=0, recovery_state="recruiting")
        self.actors = ActorCollection()
        self._dead = False
        self.proc.register(CC_REGISTER_TOKEN, self.register_worker)
        self.proc.register(CC_OPEN_DATABASE_TOKEN, self.open_database)
        self.proc.register(CC_MASTER_RECOVERED_TOKEN, self.master_recovered)
        self.proc.register(CC_STATUS_TOKEN, self.get_status)
        self._spawn(self.cluster_watch_database(), "clusterWatchDatabase")

    def _spawn(self, coro, name):
        t = spawn(coro, TaskPriority.CLUSTER_CONTROLLER, name=name)
        self.proc.actors.add(t)
        self.actors.add(t)
        return t

    def shutdown(self) -> None:
        """Leadership lost: stop recruiting (a successor CC owns it now)."""
        if self._dead:
            return
        self._dead = True
        for tok in (CC_REGISTER_TOKEN, CC_OPEN_DATABASE_TOKEN,
                    CC_MASTER_RECOVERED_TOKEN, CC_STATUS_TOKEN):
            self.proc.unregister(tok)
        self.actors.cancel_all()

    # -- worker registry ------------------------------------------------------
    async def register_worker(self, req: WorkerRegisterRequest) -> Optional[ServerDBInfo]:
        from ..core import buggify

        self.workers[req.addr] = now()
        self.worker_roles[req.addr] = tuple(req.roles)
        self.worker_locality[req.addr] = tuple(req.locality)
        if buggify.buggify():
            # drop the broadcast piggyback once: the worker stays a beat
            # stale and must pick the view up on its next heartbeat
            return None
        if req.known_info_version < self.db_info.info_version:
            return self.db_info
        return None

    def _alive_workers(self) -> list:
        t = now()
        return [
            a for a, seen in sorted(self.workers.items())
            if t - seen < WORKER_STALE_SECONDS and not self.net.monitor.is_failed(a)
        ]

    # -- client surface -------------------------------------------------------
    async def open_database(self, req: OpenDatabaseRequest) -> ServerDBInfo:
        return self.db_info

    async def get_status(self, _req) -> dict:
        """The machine-readable cluster status document (clusterGetStatus,
        Status.actor.cpp:1759), aggregated live from the master's fragment
        and the storage servers' queue info."""
        from .ratekeeper import STORAGE_QUEUE_INFO_TOKEN

        info = self.db_info
        t = now()
        doc = {
            "cluster": {
                "controller": self.proc.address,
                "recovery_state": info.recovery_state,
                "generation": info.recovery_count,
                "master": info.master_addr,
                "proxies": list(info.proxy_addrs),
                "log_generation": (str(info.log_config.gen_id)
                                   if info.log_config is not None else None),
                "workers": {
                    addr: {
                        "seconds_since_heartbeat": round(t - seen, 3),
                        "roles": sorted(self.worker_roles.get(addr, ())),
                    }
                    for addr, seen in sorted(self.workers.items())
                },
                "recovery_history": list(self.recovery_history),
            },
            "qos": {},
            "storage": [],
            "data": {"shards": []},
        }
        if info.master_status_ep is not None:
            try:
                frag = await self.net.request(
                    self.proc.address, info.master_status_ep, None,
                    TaskPriority.CLUSTER_CONTROLLER, timeout=1.0,
                )
                doc["cluster"]["version"] = frag["version"]
                doc["cluster"]["roles"] = {
                    "tlogs": frag["tlogs"], "resolvers": frag["resolvers"],
                    "proxies": frag["proxies"],
                }
                doc["qos"] = {
                    "transactions_per_second_limit": frag["tps_limit"],
                    "worst_storage_lag_versions": frag["worst_storage_lag_versions"],
                    # stale = every storage poll timed out; worst_lag is a
                    # reset placeholder, not a healthy 0 (ratekeeper.py)
                    "storage_lag_stale": frag.get("storage_lag_stale", False),
                    # conflict-engine health (fault/resilient.py): degraded
                    # = some resolver is retrying/failed over/on probation
                    "resolver_degraded": frag.get("resolvers_degraded", False),
                    "resolver_health": frag.get("resolver_health", {}),
                    # unified resolver telemetry (docs/observability.md):
                    # engine perf counters + budget-batcher EWMAs per
                    # resolver, consumed by `tools/cli.py telemetry`
                    "resolver_telemetry": frag.get("resolver_telemetry", {}),
                }
            except error.FDBError:
                doc["cluster"]["version"] = None
        for addr in info.proxy_addrs:
            try:
                doc.setdefault("proxy_stats", {})[addr] = await self.net.request(
                    self.proc.address, Endpoint(addr, "proxy.stats"), None,
                    TaskPriority.CLUSTER_CONTROLLER, timeout=1.0,
                )
            except error.FDBError:
                pass
        committed = doc["cluster"].get("version")
        shards = {}
        for tag, b, e, addr in info.storage_tags:
            entry = {"tag": tag, "address": addr,
                     "shard_begin": b.hex(), "shard_end": e.hex()}
            try:
                qi = await self.net.request(
                    self.proc.address, Endpoint(addr, STORAGE_QUEUE_INFO_TOKEN),
                    None, TaskPriority.CLUSTER_CONTROLLER, timeout=1.0,
                )
                entry["version"] = qi.version
                entry["durable_version"] = qi.durable_version
                entry["queue_bytes"] = getattr(qi, "queue_bytes", 0)
                if committed is not None:
                    # fetch lag, not durability lag: the durable version
                    # trails by design (storage_durability_lag_versions)
                    entry["lag_versions"] = max(0, committed - qi.version)
                entry["counters"] = await self.net.request(
                    self.proc.address, Endpoint(addr, "storage.stats"), None,
                    TaskPriority.CLUSTER_CONTROLLER, timeout=1.0,
                )
            except error.FDBError:
                entry["unreachable"] = True
            doc["storage"].append(entry)
            shards.setdefault((b, e), []).append(entry)
        doc["data"]["shards"] = [
            {
                "begin": b.hex(), "end": e.hex(),
                "replicas": [x["address"] for x in team],
                "replication": len(team),
                "healthy": all(not x.get("unreachable") for x in team),
            }
            for (b, e), team in sorted(shards.items())
        ]
        return doc

    # -- database watch -------------------------------------------------------
    async def master_recovered(self, info: ServerDBInfo) -> None:
        """The master finished its recovery transaction + cstate write. A
        delayed report from an older, deposed generation must not overwrite
        a newer one (one-ways can reorder under clogging)."""
        cur = (self.db_info.recovery_count, self.db_info.dd_version)
        if (info.recovery_count, getattr(info, "dd_version", 0)) <= cur:
            return
        info.info_version = self.db_info.info_version + 1
        self.db_info = info
        self.recovery_history.append((info.recovery_count, round(now(), 3)))
        del self.recovery_history[:-20]
        TraceEvent("MasterRecoveredToCC").detail("RecoveryCount", info.recovery_count).log()

    async def cluster_watch_database(self) -> None:
        """Keep one master alive (clusterWatchDatabase:1000)."""
        # Enough registered workers to separate storage from transaction
        # roles and spread tlog replicas (the reference waits for a viable
        # RecruitFromConfiguration before starting a master).
        min_workers = min(self.cluster_cfg.n_workers,
                          self.cluster_cfg.n_storage + 2)
        ndc = max(1, getattr(self.cluster_cfg, "n_dcs", 1))
        dc_grace_until = None
        while True:
            candidates = self._alive_workers()
            if len(candidates) < min_workers:
                await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
                continue
            # placement quality: wait (bounded) for the FULL fleet — and in
            # multi-region, for every DC — to register before recruiting a
            # master; a partial registry places tlogs/satellites/teams
            # blind. The bound keeps dead workers or a dead DC from
            # wedging recovery (failover recruits with whoever is left).
            dcs = {self.worker_locality.get(a, ("", "dc0"))[1]
                   for a in candidates}
            complete = (len(candidates) >= self.cluster_cfg.n_workers
                        and len(dcs) >= ndc)
            if not complete:
                if dc_grace_until is None:
                    dc_grace_until = now() + 5.0
                if now() < dc_grace_until:
                    await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
                    continue
            dc_grace_until = None
            # Prefer not to co-locate the master with the CC when possible
            # (the reference's fitness preference, reduced to its core).
            others = [a for a in candidates if a != self.proc.address]
            target = (others or candidates)[0]
            from ..core import buggify

            if buggify.buggify() and len(others) > 1:
                # adversarial placement: recruit the master on a different
                # worker than the deterministic preference would pick
                target = others[-1]
            salt = self.worker.sim.sched.rng.random_unique_id()
            from .worker import INIT_MASTER_TOKEN

            try:
                wf_ep = await self.net.request(
                    self.proc.address,
                    Endpoint(target, INIT_MASTER_TOKEN),
                    InitializeMasterRequest(
                        coordinator_addrs=self.coords,
                        worker_addrs=self._alive_workers(),
                        salt=salt,
                        cc_addr=self.proc.address,
                        cluster_cfg=self.cluster_cfg,
                        worker_localities=dict(self.worker_locality),
                    ),
                    TaskPriority.CLUSTER_CONTROLLER,
                    timeout=2.0,
                )
            except error.FDBError:
                self.workers.pop(target, None)
                self.worker_roles.pop(target, None)
                await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
                continue
            TraceEvent("CCRecruitedMaster").detail("Worker", target).detail("Salt", salt).log()
            # Watch the master role; silence = dead role (or dead process).
            await wait_failure_client(self.net, self.proc.address, wf_ep)
            TraceEvent("CCMasterFailed").detail("Worker", target).log()
            stale = ServerDBInfo(
                info_version=self.db_info.info_version + 1,
                recovery_count=self.db_info.recovery_count,
                recovery_state="recruiting",
                master_addr=None,
                proxy_addrs=(),
                log_config=self.db_info.log_config,
                storage_tags=self.db_info.storage_tags,
            )
            self.db_info = stale
