"""Resolver: OCC conflict detection behind the ConflictSet interface.

Re-design of fdbserver/Resolver.actor.cpp (320 LoC): batches are serialized
into the global commit order by (prev_version -> version) chaining
(resolveBatch:110 `version.whenAtLeast(req.prevVersion)`), each batch runs
through a pluggable ConflictSet engine — the reference-exact oracle or the
TPU kernel engine (the north star) — and the GC horizon advances to
version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS (SkipList removeBefore).

The engine's resolve() is synchronous from the actor's point of view: in
simulation the JAX dispatch happens inline on the one logical device queue,
which keeps runs deterministic (SURVEY.md §5 race-detection strategy).
"""
from __future__ import annotations

from typing import Dict

from ..core import error
from ..core.types import MAX_WRITE_TRANSACTION_LIFE_VERSIONS, Version
from ..sim.actors import NotifiedVersion
from ..sim.network import SimProcess
from .messages import ResolveTransactionBatchRequest, ResolveTransactionBatchReply

RESOLVE_TOKEN = "resolver.resolve"


class Resolver:
    def __init__(self, proc: SimProcess, engine, start_version: Version = 0,
                 token_suffix: str = ""):
        """`engine` implements resolve(transactions, now, new_oldest) and
        clear(version) — OracleConflictEngine, JaxConflictEngine or
        ShardedConflictEngine (ops/, parallel/). token_suffix scopes the
        endpoint to one recovery generation."""
        self.proc = proc
        self.engine = engine
        self.version = NotifiedVersion(start_version)
        self.token = RESOLVE_TOKEN + token_suffix
        # replay window: version -> reply, for proxy retries after
        # request_maybe_delivered (reference keeps recentStateTransactions)
        self._recent: Dict[Version, ResolveTransactionBatchReply] = {}
        proc.register(self.token, self.resolve_batch)

    def unregister(self) -> None:
        self.proc.unregister(self.token)

    async def resolve_batch(self, req: ResolveTransactionBatchRequest) -> ResolveTransactionBatchReply:
        """reference: resolveBatch, Resolver.actor.cpp:71-260."""
        if req.version <= self.version.get():
            # Already resolved (proxy retry): replay the recorded verdicts.
            return self._replay(req.version)
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get():
            # A duplicate delivery resolved this version while we waited.
            return self._replay(req.version)
        new_oldest = max(0, req.version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
        verdicts = self.engine.resolve(req.transactions, req.version, new_oldest)
        reply = ResolveTransactionBatchReply(committed=[int(v) for v in verdicts])
        self._recent[req.version] = reply
        # GC the replay window along with the conflict window.
        for v in [v for v in self._recent if v < new_oldest]:
            del self._recent[v]
        self.version.set(req.version)
        return reply

    def _replay(self, version: Version) -> ResolveTransactionBatchReply:
        """A sufficiently delayed duplicate may ask for a version already
        GC'd from the replay window; that is a typed error the proxy's
        commit_unknown_result path absorbs, never a process crash."""
        cached = self._recent.get(version)
        if cached is None:
            raise error.please_reboot(f"resolve replay window GC'd version {version}")
        return cached
