"""Resolver: OCC conflict detection behind the ConflictSet interface.

Re-design of fdbserver/Resolver.actor.cpp (320 LoC): batches are serialized
into the global commit order by (prev_version -> version) chaining
(resolveBatch:110 `version.whenAtLeast(req.prevVersion)`), each batch runs
through a pluggable ConflictSet engine — the reference-exact oracle or the
TPU kernel engine (the north star) — and the GC horizon advances to
version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS (SkipList removeBefore).

The engine's resolve() is synchronous from the actor's point of view: in
simulation the JAX dispatch happens inline on the one logical device queue,
which keeps runs deterministic (SURVEY.md §5 race-detection strategy).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..core import blackbox, buggify, error
from ..core import telemetry
from ..core.knobs import SERVER_KNOBS
from ..core.stats import CounterCollection
from ..core.trace import g_spans, span_event, span_now
from ..core.types import (
    CommitTransaction,
    KeyRange,
    MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
    Version,
)
from ..pipeline.service import PipelineConfig, PipelinedResolverService
from ..sim.actors import NotifiedVersion
from ..sim.loop import Promise, TaskPriority, spawn
from ..sim.network import SimProcess
from .messages import ResolveTransactionBatchRequest, ResolveTransactionBatchReply

RESOLVE_TOKEN = "resolver.resolve"
RESOLUTION_METRICS_TOKEN = "resolver.metrics"
RESOLVER_HEALTH_TOKEN = "resolver.health"

#: reservoir size for the split-key sample (the analog of the resolver's
#: iops TransientStorageMetricSample feeding ResolutionSplitRequest)
KEY_SAMPLE_SIZE = 64

#: virtual end of the conflict keyspace for whole-span synthetic writes
#: (above every real key, including the \xff system space and the cluster
#: shard end \xff\xff\xff)
CONFLICT_KEYSPACE_END = b"\xff\xff\xff\xff\xff"


def _span_of(splits: tuple, i: int) -> tuple:
    """Resolver i's key span under `splits` (n-1 split keys)."""
    begins = [b""] + list(splits)
    b = begins[i] if i < len(begins) else begins[-1]
    e = begins[i + 1] if i + 1 < len(begins) else CONFLICT_KEYSPACE_END
    return b, e


def gained_ranges(old_splits: tuple, new_splits: tuple, i: int) -> list:
    """The key ranges resolver i owns under new_splits but not under
    old_splits — the incoming spans of a live rebalance."""
    nb, ne = _span_of(new_splits, i)
    ob, oe = _span_of(old_splits, i)
    out = []
    if nb < ob:
        out.append((nb, min(ne, ob)))
    if ne > oe:
        out.append((max(nb, oe), ne))
    return [(b, e) for b, e in out if b < e]


#: shared with the telemetry hub's health sync, which exports the same
#: figures as `resolver.<label>.state_bytes`/`state_memory_pressure`
#: series for the watchdog's pressure rule (core/telemetry.py)
_engine_state_bytes = telemetry._engine_state_bytes


class Resolver:
    def __init__(self, proc: SimProcess, engine, start_version: Version = 0,
                 token_suffix: str = "", index: int = 0,
                 pipeline: Optional[PipelineConfig] = None):
        """`engine` implements resolve(transactions, now, new_oldest) and
        clear(version) — OracleConflictEngine, JaxConflictEngine or
        ShardedConflictEngine (ops/, parallel/). token_suffix scopes the
        endpoint to one recovery generation; `index` is this resolver's
        key-shard slot (live rebalancing computes its gained spans).
        `pipeline` turns the one-batch-at-a-time path into the windowed
        multi-batch in-flight service (pipeline/service.py): up to
        `pipeline.depth` batches overlap pack/device stages, verdicts stay
        bit-identical to the serial path."""
        from ..sim.loop import current_scheduler

        self.proc = proc
        self.engine = engine
        self.index = index
        #: newest routing flip already seeded into the engine
        self._flip_seen: Version = 0
        self.version = NotifiedVersion(start_version)
        self.token = RESOLVE_TOKEN + token_suffix
        self.metrics_token = RESOLUTION_METRICS_TOKEN + token_suffix
        self.health_token = RESOLVER_HEALTH_TOKEN + token_suffix
        # replay window: version -> reply, for proxy retries after
        # request_maybe_delivered (reference keeps recentStateTransactions)
        self._recent: Dict[Version, ResolveTransactionBatchReply] = {}
        #: versions accepted into the pipeline but not yet resolved: a
        #: duplicate delivery awaits the in-flight future instead of
        #: missing the replay window
        self._inflight: Dict[Version, Promise] = {}
        self._service = (PipelinedResolverService(pipeline, engine)
                         if pipeline is not None else None)
        #: conflict-range rows since the last metrics poll + a reservoir
        #: sample of range-begin keys (reference: ResolutionMetricsRequest /
        #: ResolutionSplitRequest, Resolver.actor.cpp:276-284)
        self._rows_since_poll = 0
        self._rows_total = 0
        self._key_sample: list = []
        self._sample_rng = current_scheduler().rng
        #: reference: Resolver.actor.cpp's resolverCounters via traceCounters
        #: — the logger is a real scheduled task (cancelled on unregister),
        #: not a dropped coroutine, so resolver counters actually trace.
        #: Counters also feed the unified telemetry hub's TDMetric registry
        #: (core/telemetry.py), so a MetricLogger persists them alongside
        #: engine perf / batcher / health series.
        self.stats = CounterCollection("Resolver", proc.address,
                                       tdmetrics=telemetry.hub().tdmetrics)
        self._stats_task = spawn(self.stats.run_logger(),
                                 TaskPriority.RESOLUTION_METRICS,
                                 name="resolverStats")
        proc.actors.add(self._stats_task)
        proc.register(self.token, self.resolve_batch)
        proc.register(self.metrics_token, self.resolution_metrics)
        proc.register(self.health_token, self.engine_health)

    def unregister(self) -> None:
        self.proc.unregister(self.token)
        self.proc.unregister(self.metrics_token)
        self.proc.unregister(self.health_token)
        self._stats_task.cancel()

    async def engine_health(self, _req) -> dict:
        """Engine-health fragment (the device-fault analog of
        ResolutionMetricsRequest): the ratekeeper polls it as a throttle
        signal and the status document surfaces it (tools/cli.py). A
        budget-batching pipeline additionally reports its adaptive batch
        target, which the ratekeeper relays to proxies as the commit-batch
        cap (the resolver -> ratekeeper -> proxy sizing loop)."""
        out = {"state": "healthy", "degraded": False}
        fn = getattr(self.engine, "health_stats", None)
        if fn is not None:
            out.update(fn())
        out["resolve_errors"] = self.stats.counter("resolve_errors").value
        # state-memory accounting (reference: RESOLVER_STATE_MEMORY_LIMIT):
        # the footprint of the conflict-history state, and a pressure flag
        # when it exceeds the knob — a throttle/alert signal surfaced
        # through the same ratekeeper -> status-doc path as health
        sb = _engine_state_bytes(self.engine)
        if sb is not None:
            out["state_bytes"] = sb
            out["state_memory_pressure"] = (
                sb > SERVER_KNOBS.resolver_state_memory_limit)
        if self._service is not None and self._service.batcher is not None:
            out["target_batch_txns"] = self._service.target_batch_txns()
        # Unified telemetry fragment (docs/observability.md): engine perf
        # counters and the budget batcher's per-bucket EWMAs ride the same
        # poll, so they reach the master status fragment -> CC status doc ->
        # `tools/cli.py telemetry` without a second collection path.
        tel: Dict[str, dict] = {}
        perf = getattr(self.engine, "perf", None)
        if perf is None:
            # supervised engine: the device under the ResilientEngine
            perf = getattr(getattr(self.engine, "device", None), "perf", None)
        if perf is not None:
            tel["engine_perf"] = perf.as_dict()
        # compile & memory ledger (core/perfledger.py): per-compile
        # durations + flops/bytes/peak-HBM ride the same poll, joined by
        # `tools/cli.py perf` with the state-memory gauge below into one
        # memory view
        ledger = getattr(self.engine, "perf_ledger", None)
        if ledger is None:
            ledger = getattr(getattr(self.engine, "device", None),
                             "perf_ledger", None)
        if ledger is not None:
            tel["perf_ledger"] = ledger.snapshot()
        if sb is not None:
            # mirrored into the telemetry fragment so `cli perf` renders
            # the whole memory story from one status-doc subtree
            tel["state_bytes"] = sb
            tel["state_memory_pressure"] = out["state_memory_pressure"]
        if self._service is not None and self._service.batcher is not None:
            tel["batcher"] = self._service.batcher.as_dict()
        flight = getattr(self.engine, "flight", None)
        if flight is not None:
            tel["flight_recorder_entries"] = len(flight)
        # cluster watchdog (core/watchdog.py): evaluate-on-sync, then ride
        # the health poll -> ratekeeper -> master status -> CC status doc
        # -> `tools/cli.py alerts|incidents`. The firing burn-rate bit is
        # top-level like `degraded`: the ratekeeper consumes it as a rate
        # clamp without digging through the telemetry fragment.
        wd = telemetry.hub().watchdog
        if wd is not None:
            telemetry.hub().sync()
            tel["watchdog"] = wd.snapshot()
            out["burn_alert_firing"] = tel["watchdog"]["burn_firing"]
        # keyspace heat & occupancy (core/heatmap.py): hot ranges, table
        # headroom and suggested split points ride the same poll ->
        # ratekeeper -> CC status doc -> `tools/cli.py heat`
        heat_fn = getattr(self.engine, "heat_snapshot", None)
        if heat_fn is not None:
            heat = heat_fn()
            if heat is not None:
                tel["heat"] = heat
        # conflict-aware admission (pipeline/scheduler.py): predictor
        # scores, lane occupancy and pre-abort counters ride the same
        # poll -> ratekeeper -> CC status doc -> `tools/cli.py sched`
        cs = getattr(self._service, "conflict_sched", None) \
            if self._service is not None else None
        if cs is not None and cs.enabled:
            tel["sched"] = cs.snapshot()
        if tel:
            out["telemetry"] = tel
        return out

    def _sample_rows(self, transactions) -> None:
        rng = self._sample_rng
        for txn in transactions:
            for rng_list in (txn.read_conflict_ranges, txn.write_conflict_ranges):
                self._rows_since_poll += len(rng_list)
                self._rows_total += len(rng_list)
                for r in rng_list:
                    # reservoir sampling keyed by the running row count
                    if len(self._key_sample) < KEY_SAMPLE_SIZE:
                        self._key_sample.append(r.begin)
                    elif rng.random_int(0, self._rows_total) < KEY_SAMPLE_SIZE:
                        self._key_sample[rng.random_int(0, KEY_SAMPLE_SIZE)] = r.begin

    async def resolution_metrics(self, _req) -> dict:
        out = {"rows": self._rows_since_poll, "sample": list(self._key_sample)}
        # window-scoped: the split chooser must see the CURRENT key
        # distribution, not a lifetime-weighted one (a long uniform phase
        # would otherwise drown the hot range that triggered rebalancing)
        self._rows_since_poll = 0
        self._rows_total = 0
        self._key_sample = []
        return out

    async def resolve_batch(self, req: ResolveTransactionBatchRequest) -> ResolveTransactionBatchReply:
        """reference: resolveBatch, Resolver.actor.cpp:71-260."""
        # span anchor: queue wait = arrival -> the batch holds the version
        # chain (serial) or a service window slot (pipelined)
        t_enter = span_now() if g_spans.enabled else 0.0
        if req.version <= self.version.get():
            # Already resolved (proxy retry): replay the recorded verdicts.
            return await self._replay(req.version)
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get():
            # A duplicate delivery resolved this version while we waited.
            return await self._replay(req.version)
        if buggify.buggify():
            # slow resolve: batches queue up behind the version chain, so
            # proxies see deep pipelining + retry races
            from ..sim.loop import delay
            await delay(0.05, TaskPriority.PROXY_COMMIT)
            if req.version <= self.version.get():
                return await self._replay(req.version)
        window = MAX_WRITE_TRANSACTION_LIFE_VERSIONS
        if buggify.buggify():
            # tight replay/conflict window: drives the too-old and
            # replay-window-GC'd paths that normally need huge lag
            window = window // 100
        new_oldest = max(0, req.version - window)
        inflight = self._inflight.get(req.version)
        if inflight is not None:
            # A duplicate delivery of a version still in dispatch (possible
            # once the engine awaits: pipeline slots, watchdogs, failover)
            # waits for the first delivery's outcome — checked BEFORE
            # sampling, so retried batches don't bias the split-key
            # reservoir twice.
            return await inflight.future
        transactions = req.transactions
        prepended = False
        if (getattr(req, "routing_version", 0)
                and req.version >= req.routing_version
                and req.routing_version > self._flip_seen):
            # Live rebalance handoff (bounce-free resolutionBalancing): this
            # is the first chained batch at or past the flip. Seed a
            # synthetic whole-span write over the ranges we GAINED: reads
            # with pre-flip snapshots conflict conservatively (we lack the
            # donor's history for them — exactly the reference's
            # "insufficient history => abort" rule), and everything with a
            # post-flip snapshot is checked exactly against the complete
            # history accumulated here from the flip on.
            self._flip_seen = req.routing_version
            gained = gained_ranges(tuple(req.routing_old_splits),
                                   tuple(req.routing_splits), self.index)
            if gained:
                synth = CommitTransaction(
                    read_snapshot=req.version,
                    write_conflict_ranges=[KeyRange(b, e) for b, e in gained],
                )
                transactions = [synth] + list(req.transactions)
                prepended = True
        self._sample_rows(req.transactions)

        if self._service is None:
            # Serial path: one batch at a time, the chain advances when the
            # batch is fully resolved. Once the engine can await (watchdog,
            # retries, failover — fault/resilient.py), duplicates of the
            # in-flight version are caught by the _inflight check above
            # (nothing awaits between it and the registration here).
            p = Promise()
            self._inflight[req.version] = p
            if g_spans.enabled:
                span_event("resolver.queue_wait", req.version,
                           t_enter, span_now(),
                           parent="proxy.resolve_rpc")
            try:
                verdicts = await self._engine_resolve(
                    transactions, req.version, new_oldest)
            except Exception as e:
                # Typed wrapping (the serial analog of the pipelined
                # except below): an engine/device fault must reach the
                # proxy as an FDBError it absorbs as commit_unknown_result
                # + chain repair, never an untyped exception that kills
                # the resolver actor mid-chain.
                self.stats.add("resolve_errors")
                self._inflight.pop(req.version, None)
                if not p.is_set:
                    p.send_error(error.please_reboot(
                        f"resolve {req.version} failed in engine"))
                if isinstance(e, error.FDBError):
                    raise
                raise error.please_reboot(
                    f"resolve {req.version} failed in engine: {e}") from e
            except BaseException:
                # cancellation (role killed): waiters get the honest answer
                self._inflight.pop(req.version, None)
                if not p.is_set:
                    p.send_error(error.please_reboot(
                        f"resolve {req.version} cancelled"))
                raise
            reply = self._finish(req.version, verdicts, prepended,
                                 new_oldest, transactions)
            self._inflight.pop(req.version, None)
            p.send(reply)
            return reply

        # Pipelined path: acquire a window slot, ADVANCE THE CHAIN AT
        # ACCEPT so the next batch enters its pack stage while this one is
        # still on the device (multi-batch in flight), and resolve through
        # the service — which runs engine.resolve strictly in commit-version
        # order, so abort sets are bit-identical to the serial path.
        await self._service.acquire()
        if req.version <= self.version.get():
            # A duplicate delivery accepted this version while we waited
            # for a slot; hand the slot back and follow the replay path.
            self._service.release()
            return await self._replay(req.version)
        p = Promise()
        self._inflight[req.version] = p
        self.version.set(req.version)
        if g_spans.enabled:
            span_event("resolver.queue_wait", req.version, t_enter,
                       span_now(), parent="proxy.resolve_rpc")
        try:
            verdicts = await self._service.resolve(
                transactions, req.version, new_oldest)
        except BaseException as e:
            self._inflight.pop(req.version, None)
            if not p.is_set:
                # duplicates waiting on this version get the honest answer:
                # the batch died in service; the proxy absorbs it as
                # commit_unknown_result + chain repair
                p.send_error(error.please_reboot(
                    f"resolve {req.version} failed in pipeline"))
            if isinstance(e, Exception):
                self.stats.add("resolve_errors")
                if not isinstance(e, error.FDBError):
                    # typed wrapping: an untyped engine exception would
                    # escape the handler and crash the whole run loop
                    raise error.please_reboot(
                        f"resolve {req.version} failed in pipeline: {e}") from e
            raise
        reply = self._finish(req.version, verdicts, prepended, new_oldest,
                             transactions, advance_chain=False)
        self._inflight.pop(req.version, None)
        p.send(reply)
        return reply

    async def _engine_resolve(self, transactions, version: Version,
                              new_oldest: Version):
        """Dispatch one batch to the conflict engine, awaiting engines whose
        resolve is a coroutine (fault/resilient.py's supervisor). Device
        faults under sim come from the supervisor's engine-boundary buggify
        sites (every dynamic spec wraps engines by default) — not here,
        where a raw-engine fault would need the proxy's retry machinery to
        absorb (direct resolver harnesses have none)."""
        t0 = span_now() if g_spans.enabled else 0.0
        r = self.engine.resolve(transactions, version, new_oldest)
        if hasattr(r, "__await__"):
            r = await r
        if g_spans.enabled:
            # serial path: no service stages, so the whole engine dispatch
            # is the device segment (pack rides inside it in zero vtime)
            span_event("resolver.device_dispatch", version, t0, span_now(),
                       txns=len(transactions),
                       parent="resolver.queue_wait")
        return r

    def _finish(self, version: Version, verdicts, prepended: bool,
                new_oldest: Version, transactions=None,
                advance_chain: bool = True) -> ResolveTransactionBatchReply:
        from ..core.types import TransactionCommitResult

        if transactions is not None and blackbox.enabled():
            # durable black-box record of the batch AS RESOLVED (synthetic
            # handoff writes included — differential replay re-resolves
            # exactly what the engine saw; core/blackbox.py)
            blackbox.record_batch(
                transactions, version, new_oldest, verdicts,
                shard=self.index,
                engine=getattr(self.engine, "name",
                               type(self.engine).__name__),
                proc=self.proc.address)
        if prepended:
            verdicts = verdicts[1:]   # the synthetic is ours, not a txn
        reply = ResolveTransactionBatchReply(committed=[int(v) for v in verdicts])
        self._recent[version] = reply
        # GC the replay window along with the conflict window (completions
        # are version-ordered even when pipelined, so this stays monotone).
        for v in [v for v in self._recent if v < new_oldest]:
            del self._recent[v]
        if advance_chain:
            self.version.set(version)
        self.stats.add("batches_resolved")
        self.stats.add("txns_in", len(reply.committed))
        for v in reply.committed:
            if v == int(TransactionCommitResult.COMMITTED):
                self.stats.add("txns_committed")
            elif v == int(TransactionCommitResult.TOO_OLD):
                self.stats.add("txns_too_old")
            else:
                self.stats.add("txns_conflicted")
        return reply

    async def _replay(self, version: Version) -> ResolveTransactionBatchReply:
        """A sufficiently delayed duplicate may ask for a version already
        GC'd from the replay window; that is a typed error the proxy's
        commit_unknown_result path absorbs, never a process crash. A
        version still in the pipeline's in-flight window answers with the
        in-flight result once it completes."""
        cached = self._recent.get(version)
        if cached is not None:
            return cached
        inflight = self._inflight.get(version)
        if inflight is not None:
            return await inflight.future
        raise error.please_reboot(f"resolve replay window GC'd version {version}")
