"""The `\\xff` system keyspace: cluster metadata as ordinary keys.

Re-design of fdbclient/SystemData.cpp + fdbserver/ApplyMetadataMutation.h
round-3 scope: shard assignment lives at `\\xff/keyServers/<shard begin>`
and is changed by REAL transactions. The committing proxy copies every
committed system-key mutation into the METADATA_TAG stream of the log
system (the analog of the reference's txnState tag feeding every proxy's
txnStateStore via ApplyMetadataMutation); all proxies drain that stream
up to their batch's prev_version before tagging mutations, which is exact
because commit versions form a single global chain.

Values are wire-encoded dicts, not flat tuples, because the sim's wire
format is the repo-wide stand-in (core/wire.py) — the versioned flat
encoding replaces it at the disk boundary.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core import wire
from ..core.types import Key

SYSTEM_PREFIX = b"\xff"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"
#: holds wire({"tag": <backup tag>}) while a backup is running; proxies
#: copy every committed user mutation into that log tag (the reference's
#: backup mutation ranges through ApplyMetadataMutation)
BACKUP_ACTIVE_KEY = b"\xff/backup/active"
BACKUP_SEQ_KEY = b"\xff/backup/seq"
#: non-empty value = database locked: proxies reject user commits with
#: database_locked; lock-aware (system) transactions pass — the
#: lockDatabase mechanism DR switchover fences with (reference:
#: fdbclient/ManagementAPI.actor.cpp lockDatabase, \xff/dbLocked)
DB_LOCK_KEY = b"\xff/dbLocked"

#: the log-system tag carrying committed system-key mutations to every
#: proxy (the reference's txsTag, TagPartitionedLogSystem.actor.cpp)
METADATA_TAG = -1
#: backup tags count downward from here, one per backup generation
FIRST_BACKUP_TAG = -2


def encode_backup_active(tag: int) -> bytes:
    return wire.dumps({"tag": tag})


def decode_backup_active(value: bytes) -> Optional[int]:
    if not value:
        return None
    return wire.loads(value).get("tag")


def is_system_key(key: Key) -> bool:
    return key.startswith(SYSTEM_PREFIX)


def key_servers_key(shard_begin: Key) -> Key:
    return KEY_SERVERS_PREFIX + shard_begin


def shard_begin_of(key: Key) -> Key:
    assert key.startswith(KEY_SERVERS_PREFIX)
    return key[len(KEY_SERVERS_PREFIX):]


def encode_key_servers(team: List[Tuple[int, str]],
                       extra_tags: Tuple[int, ...] = ()) -> bytes:
    """`team` serves reads and receives writes; `extra_tags` additionally
    receive writes (the destination replicas of an in-flight shard move —
    MoveKeys' old+new keyServers value, MoveKeys.actor.cpp:821)."""
    return wire.dumps({"team": [tuple(m) for m in team],
                      "extra_tags": tuple(extra_tags)})


def decode_key_servers(value: bytes) -> Tuple[List[Tuple[int, str]], Tuple[int, ...]]:
    d = wire.loads(value)
    return [tuple(m) for m in d["team"]], tuple(d.get("extra_tags", ()))
