"""TLog: tag-partitioned replicated in-memory durable log, epoch-aware.

Round-2 scope of fdbserver/TLogServer.actor.cpp: a log generation is K tlog
replicas; the proxy pushes every commit to all of them and acks the client
only when all have fsynced (all-ack = the reference's default quorum with
anti-quorum 0). Each commit carries the proxy's known-committed version
(KCV: the newest version already acked by every replica); peeks serve data
only up to min(durable, KCV), so a storage server can never apply a version
that epoch-end recovery might discard — which is what lets recovery skip
storage rollbacks entirely.

Epoch end (tLogLock:496): a recovering master locks the generation; a
locked tlog rejects further commits (tlog_stopped) and reports
(known_committed, durable end). Locking any single replica freezes the
generation, because all-ack pushes can no longer complete. Commits carry
the generation id; a tlog rejects pushes from any other generation, so an
orphaned previous master's proxies cannot write into a newer generation.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core.types import Mutation, Version
from ..core import error
from ..sim.actors import NotifiedVersion
from ..sim.loop import TaskPriority, delay
from ..sim.network import SimProcess
from .messages import (
    TLogCommitRequest,
    TLogKnownCommittedRequest,
    TLogLockReply,
    TLogLockRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
    TLogRecoveryDataReply,
    TLogRecoveryDataRequest,
)

COMMIT_TOKEN = "tlog.commit"
PEEK_TOKEN = "tlog.peek"
POP_TOKEN = "tlog.pop"
LOCK_TOKEN = "tlog.lock"
KCV_TOKEN = "tlog.knownCommitted"
RECOVERY_DATA_TOKEN = "tlog.recoveryData"

FSYNC_SECONDS = 0.0005


class TLog:
    def __init__(
        self,
        proc: SimProcess,
        start_version: Version = 0,
        gen_id: Tuple[int, int] = (0, 0),
        preload: Optional[Dict[int, List[Tuple[Version, List[Mutation]]]]] = None,
        preload_popped: Optional[Dict[int, Version]] = None,
        token_suffix: str = "",
    ):
        """gen_id = (recovery_count, master_salt): pushes from any other
        generation are rejected. `preload` seeds the tag index with the
        previous generation's un-popped data (the recovery copy), covering
        versions <= start_version. token_suffix distinguishes multiple
        generations hosted by one worker process."""
        self.proc = proc
        self.gen_id = gen_id
        self.version = NotifiedVersion(start_version)
        self.known_committed = NotifiedVersion(start_version)
        self.stopped = False
        # tag -> ordered [(version, mutations)]
        self.tag_data: Dict[int, List[Tuple[Version, List[Mutation]]]] = dict(preload or {})
        self.popped: Dict[int, Version] = dict(preload_popped or {})
        self._inflight: set = set()  # versions appended but not yet durable
        self.tokens = {
            "commit": COMMIT_TOKEN + token_suffix,
            "peek": PEEK_TOKEN + token_suffix,
            "pop": POP_TOKEN + token_suffix,
            "lock": LOCK_TOKEN + token_suffix,
            "kcv": KCV_TOKEN + token_suffix,
            "recovery": RECOVERY_DATA_TOKEN + token_suffix,
        }
        proc.register(self.tokens["commit"], self.commit)
        proc.register(self.tokens["peek"], self.peek)
        proc.register(self.tokens["pop"], self.pop)
        proc.register(self.tokens["lock"], self.lock)
        proc.register(self.tokens["kcv"], self.advance_known_committed)
        proc.register(self.tokens["recovery"], self.recovery_data)

    def unregister(self) -> None:
        for tok in self.tokens.values():
            self.proc.unregister(tok)

    # -- write path ----------------------------------------------------------
    async def commit(self, req: TLogCommitRequest) -> Version:
        """Append one version; ack after (simulated) fsync. Returns the
        durable version (reference: tLogCommit, TLogServer.actor.cpp:1158)."""
        if req.gen_id != self.gen_id:
            raise error.tlog_stopped(f"generation {req.gen_id} != {self.gen_id}")
        if self.stopped:
            raise error.tlog_stopped("locked by epoch end")
        if req.known_committed > self.known_committed.get():
            self.known_committed.set(min(req.known_committed, self.version.get()))
        if req.version <= self.version.get() or req.version in self._inflight:
            # Duplicate delivery (proxy retry) — possibly while the first
            # copy is mid-fsync; never append twice.
            await self.version.when_at_least(req.version)
            return self.version.get()
        await self.version.when_at_least(req.prev_version)
        if self.stopped:
            raise error.tlog_stopped("locked by epoch end")
        if req.version <= self.version.get() or req.version in self._inflight:
            await self.version.when_at_least(req.version)
            return self.version.get()
        self._inflight.add(req.version)
        for tag, muts in req.messages.items():
            self.tag_data.setdefault(tag, []).append((req.version, muts))
        await delay(FSYNC_SECONDS, TaskPriority.TLOG_COMMIT)
        # Chained waiters run only after this version is durable.
        self._inflight.discard(req.version)
        if self.stopped:
            # Locked mid-fsync: the append is durable locally but must not
            # be acked — the epoch has ended and recovery's end-version math
            # already treats it as maybe-committed.
            raise error.tlog_stopped("locked during fsync")
        self.version.set(req.version)
        if req.known_committed > self.known_committed.get():
            self.known_committed.set(min(req.known_committed, self.version.get()))
        return req.version

    async def advance_known_committed(self, req: TLogKnownCommittedRequest) -> None:
        """The proxy reports all replicas acked `version` (the reference
        piggybacks this on the next push; a dedicated message keeps peeks
        moving on an idle system)."""
        if self.stopped:
            return
        v = min(req.version, self.version.get())
        if v > self.known_committed.get():
            self.known_committed.set(v)

    # -- read path -----------------------------------------------------------
    async def peek(self, req: TLogPeekRequest) -> TLogPeekReply:
        """Messages for req.tag with version >= begin_version, clipped to
        the known-committed horizon so nothing recovery could discard is
        ever served (blocks until the horizon passes begin_version)."""
        await self.known_committed.when_at_least(req.begin_version)
        data = self.tag_data.get(req.tag, [])
        horizon = min(self.version.get(), self.known_committed.get())
        msgs = [(v, m) for (v, m) in data if req.begin_version <= v <= horizon]
        return TLogPeekReply(messages=msgs, end_version=horizon)

    async def pop(self, req: TLogPopRequest) -> None:
        prev = self.popped.get(req.tag, 0)
        if req.version <= prev:
            return
        self.popped[req.tag] = req.version
        data = self.tag_data.get(req.tag)
        if data:
            self.tag_data[req.tag] = [(v, m) for (v, m) in data if v > req.version]

    # -- epoch end -----------------------------------------------------------
    async def lock(self, req: TLogLockRequest) -> TLogLockReply:
        """reference: tLogLock (TLogServer.actor.cpp:496). Idempotent."""
        self.stopped = True
        return TLogLockReply(
            gen_id=self.gen_id,
            known_committed=self.known_committed.get(),
            end_version=self.version.get(),
        )

    async def recovery_data(self, req: TLogRecoveryDataRequest) -> TLogRecoveryDataReply:
        """All un-popped data up to the recovery version, for seeding the
        next generation (the copy replaces the reference's old-generation
        peek cursors; bounded by the 5s un-popped window)."""
        clip = req.end_version
        out = {
            tag: [(v, m) for (v, m) in entries if v <= clip]
            for tag, entries in self.tag_data.items()
        }
        return TLogRecoveryDataReply(
            tag_data={t: e for t, e in out.items() if e},
            popped=dict(self.popped),
        )
