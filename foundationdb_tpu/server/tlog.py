"""TLog: tag-partitioned replicated in-memory durable log, epoch-aware.

Round-2 scope of fdbserver/TLogServer.actor.cpp: a log generation is K tlog
replicas; the proxy pushes every commit to all of them and acks the client
only when all have fsynced (all-ack = the reference's default quorum with
anti-quorum 0). Each commit carries the proxy's known-committed version
(KCV: the newest version already acked by every replica); peeks serve data
only up to min(durable, KCV), so a storage server can never apply a version
that epoch-end recovery might discard — which is what lets recovery skip
storage rollbacks entirely.

Epoch end (tLogLock:496): a recovering master locks the generation; a
locked tlog rejects further commits (tlog_stopped) and reports
(known_committed, durable end). Locking any single replica freezes the
generation, because all-ack pushes can no longer complete. Commits carry
the generation id; a tlog rejects pushes from any other generation, so an
orphaned previous master's proxies cannot write into a newer generation.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core.types import Mutation, Version
from ..core import buggify, error, wire
from ..sim.actors import AsyncMutex, NotifiedVersion
from ..sim.loop import Promise, TaskPriority, delay
from ..sim.network import SimProcess
from .disk_queue import DiskQueue
from .messages import (
    TLogCommitRequest,
    TLogKnownCommittedRequest,
    TLogLockReply,
    TLogLockRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
    TLogRecoveryDataReply,
    TLogRecoveryDataRequest,
)

COMMIT_TOKEN = "tlog.commit"
PEEK_TOKEN = "tlog.peek"
POP_TOKEN = "tlog.pop"
LOCK_TOKEN = "tlog.lock"
KCV_TOKEN = "tlog.knownCommitted"
RECOVERY_DATA_TOKEN = "tlog.recoveryData"
QUEUE_INFO_TOKEN = "tlog.queueInfo"


def _spill_key(tag: int, version: Version) -> bytes:
    """Order-preserving (tag, version) key for the spill store. Tags can be
    negative (METADATA_TAG, backup tags), so bias into unsigned space."""
    return (tag + 2**63).to_bytes(8, "big") + version.to_bytes(8, "big")


class TLog:
    def __init__(
        self,
        proc: SimProcess,
        start_version: Version = 0,
        gen_id: Tuple[int, int] = (0, 0),
        preload: Optional[Dict[int, List[Tuple[Version, List[Mutation]]]]] = None,
        preload_popped: Optional[Dict[int, Version]] = None,
        token_suffix: str = "",
        queue: Optional[DiskQueue] = None,
        store_name: Optional[str] = None,
    ):
        """gen_id = (recovery_count, master_salt): pushes from any other
        generation are rejected. `preload` seeds the tag index with the
        previous generation's un-popped data (the recovery copy), covering
        versions <= start_version. token_suffix distinguishes multiple
        generations hosted by one worker process. With a DiskQueue the tlog
        is durable: commits fsync through it (replacing the simulated-fsync
        delay) and a rebooted worker restores the role from disk
        (restorePersistentState, TLogServer.actor.cpp:1630)."""
        self.proc = proc
        self.gen_id = gen_id
        self.version = NotifiedVersion(start_version)
        self.known_committed = NotifiedVersion(start_version)
        self.stopped = False
        self._stop_promise = Promise()  # fires when the generation is locked
        self.queue = queue
        self._store_name = store_name or f"tlog-{gen_id[0]}.{gen_id[1]}"
        # tag -> ordered [(version, mutations)]
        self.tag_data: Dict[int, List[Tuple[Version, List[Mutation]]]] = dict(preload or {})
        self.popped: Dict[int, Version] = dict(preload_popped or {})
        self.tags_seen = set(self.tag_data) | set(self.popped)
        #: tags whose shard moved away (pop version < 0): straggler pops and
        #: repair re-pushes must not resurrect them, or the queue front pins
        self._retired_tags: set = set()
        #: append-order (version, queue end offset) for front-advance math
        self._ver_offsets: List[Tuple[Version, int]] = []
        #: spill tier (updatePersistentData, TLogServer.actor.cpp:539):
        #: versions <= spilled_version live in a durable KVS, not in
        #: tag_data / the DiskQueue — memory and queue length stay bounded
        #: by the spill threshold however far a slow storage lags
        self.spilled_version: Version = 0
        self._spill_store = None     # lazily-opened SSTableStore
        self._mem_bytes = 0
        self._bytes_by_version: List[Tuple[Version, int]] = []
        self._pops_since_persist = 0
        self._spilling = False
        self._deleted = False    # retired + files dropped; stop persisting
        self._side_mutex = AsyncMutex()   # serializes side-state persists
        self._inflight: set = set()  # versions appended but not yet durable
        self.tokens = {
            "commit": COMMIT_TOKEN + token_suffix,
            "peek": PEEK_TOKEN + token_suffix,
            "pop": POP_TOKEN + token_suffix,
            "lock": LOCK_TOKEN + token_suffix,
            "kcv": KCV_TOKEN + token_suffix,
            "recovery": RECOVERY_DATA_TOKEN + token_suffix,
            "queue_info": QUEUE_INFO_TOKEN + token_suffix,
        }
        proc.register(self.tokens["commit"], self.commit)
        proc.register(self.tokens["peek"], self.peek)
        proc.register(self.tokens["pop"], self.pop)
        proc.register(self.tokens["lock"], self.lock)
        proc.register(self.tokens["kcv"], self.advance_known_committed)
        proc.register(self.tokens["recovery"], self.recovery_data)
        proc.register(self.tokens["queue_info"], self.queue_info)

    def unregister(self) -> None:
        for tok in self.tokens.values():
            self.proc.unregister(tok)

    # -- durability ----------------------------------------------------------
    def _meta_name(self) -> str:
        return self._store_name

    def delete_files(self) -> None:
        """Drop this retired generation's disk footprint."""
        self._deleted = True
        if self.queue is None:
            return
        disk = self.queue.disk
        for suffix in (".meta", ".side", ".side.tmp", ".dq", ".dq.tmp"):
            disk.delete(self._store_name + suffix)
        for name in disk.list(self._store_name + "-spill"):
            disk.delete(name)

    async def persist_initial(self, token_suffix: str) -> None:
        """Write role metadata + the recovery-copy preload durably, so the
        seeded window survives a reboot of this worker."""
        if self.queue is None:
            return
        disk = self.queue.disk
        meta = disk.open(self._meta_name() + ".meta")
        await meta.write(0, wire.dumps({
            "gen_id": self.gen_id,
            "start_version": self.version.get(),
            "token_suffix": token_suffix,
        }))
        await meta.sync()
        # Re-key the preload per version so restore replays it uniformly.
        by_version: Dict[Version, Dict[int, List[Mutation]]] = {}
        for tag, entries in self.tag_data.items():
            for v, muts in entries:
                by_version.setdefault(v, {})[tag] = muts
        for v in sorted(by_version):
            off = await self.queue.push(wire.dumps((v, by_version[v])))
            self._ver_offsets.append((v, off))
        await self.queue.commit()
        await self._persist_side_state(force=True)

    async def _persist_side_state(self, force: bool = False) -> None:
        """Popped map + KCV + the version watermark. Mostly lazily durable
        (stale popped/kcv after a crash only re-serves acknowledged
        entries), but _advance_queue_front forces a sync BEFORE dropping
        queue entries: the watermark is otherwise implied by the newest
        queue entry, and restoring a fully-popped tlog at its start version
        would poison the epoch-end min(end) math with a version below
        already-acknowledged commits."""
        if self.queue is None:
            return
        self._pops_since_persist += 1
        if not force and self._pops_since_persist < 16:
            return
        self._pops_since_persist = 0
        # Fresh file + rename (an in-place rewrite torn by a crash would
        # destroy the version watermark this file exists to protect), under
        # a lock (concurrent pop handlers must not interleave write/rename
        # cycles on the shared tmp file). Snapshot taken inside the lock so
        # an older state can never land after a newer one.
        async with self._side_mutex:
            if self._deleted:
                return   # retired mid-persist: nothing to protect anymore
            disk = self.queue.disk
            payload = wire.dumps({
                "popped": dict(self.popped),
                "kcv": self.known_committed.get(),
                "version": self.version.get(),
                "tags_seen": set(self.tags_seen),
                "retired": set(self._retired_tags),
                "spilled": self.spilled_version,
                # the epoch lock is DURABLE state (reference: the tlog's
                # persistent stopped flag): a locked replica that reboots
                # amnesiac would let a deposed generation's straggler proxy
                # complete an all-ack push of versions the new epoch's
                # recovery already discarded — acked-then-lost commits
                # (found by the sim_validation oracle on DiskAttrition)
                "stopped": self.stopped,
            })
            if self._spill_store is not None:
                await self._spill_store.commit()   # pending pop clears
            tmp = disk.open(self._meta_name() + ".side.tmp")
            await tmp.truncate(0)
            await tmp.write(0, payload)
            await tmp.sync()
            if self._deleted or not disk.exists(self._meta_name() + ".side.tmp"):
                return   # retired between sync and rename (delete_files ran)
            disk.rename(self._meta_name() + ".side.tmp", self._meta_name() + ".side")

    @classmethod
    async def restore(cls, proc: SimProcess, disk, meta_name: str) -> Optional["TLog"]:
        """Rebuild a tlog role from its disk files after a worker reboot."""
        meta_file = disk.open(meta_name)
        raw = await meta_file.read(0, meta_file.size())
        try:
            meta = wire.loads(raw)
        except Exception:
            return None  # torn metadata: role was never fully created
        base = meta_name[: -len(".meta")]
        queue = DiskQueue(disk, base)
        entries = await queue.recover()
        side = {}
        side_file = disk.open(base + ".side")
        raw = await side_file.read(0, side_file.size())
        if raw:
            try:
                side = wire.loads(raw)
            except Exception:
                side = {}
        tlog = cls(
            proc,
            start_version=meta["start_version"],
            gen_id=tuple(meta["gen_id"]),
            token_suffix=meta["token_suffix"],
            queue=queue,
            store_name=base,
        )
        tlog.popped = dict(side.get("popped", {}))
        tlog.tags_seen = set(side.get("tags_seen", set())) | set(tlog.popped)
        tlog._retired_tags = set(side.get("retired", set()))
        tlog.spilled_version = side.get("spilled", 0)
        if side.get("stopped"):
            tlog.stopped = True
            if not tlog._stop_promise.is_set:
                tlog._stop_promise.send(None)
        if (disk.exists(base + "-spill.manifest") or disk.exists(base + "-spill.dq")):
            from .kvstore import SSTableStore

            tlog._spill_store = await SSTableStore.open(disk, base + "-spill")
        version = max(meta["start_version"], side.get("version", 0))
        for off, payload in entries:
            v, messages = wire.loads(payload)
            version = max(version, v)
            tlog._ver_offsets.append((v, off))
            if v <= tlog.spilled_version:
                continue   # already served by the spill store
            kept = False
            for tag, muts in messages.items():
                if tag in tlog._retired_tags:
                    continue
                tlog.tags_seen.add(tag)
                if v > tlog.popped.get(tag, 0):
                    tlog.tag_data.setdefault(tag, []).append((v, muts))
                    kept = True
            if kept:
                # one entry per VERSION, matching the commit path (a
                # per-tag count would overstate memory by the tag
                # multiplicity and trip the spill threshold early)
                tlog._bytes_by_version.append((v, len(payload)))
                tlog._mem_bytes += len(payload)
        tlog.version = NotifiedVersion(version)
        # Restored data is durable here but the KCV horizon must be
        # re-learned; the stored floor keeps already-served data servable.
        tlog.known_committed = NotifiedVersion(
            max(side.get("kcv", 0), meta["start_version"])
        )
        return tlog

    # -- spill tier (updatePersistentData, TLogServer.actor.cpp:539) ---------
    async def _maybe_spill(self) -> None:
        """Move the oldest un-popped versions into the durable spill store
        when the in-memory index outgrows the knob: memory and DiskQueue
        length stay bounded no matter how far a slow storage server lags,
        the reference's btree-spill property."""
        from ..core.knobs import SERVER_KNOBS

        if self.queue is None or self._spilling or self.stopped or self._deleted:
            return
        limit = SERVER_KNOBS.tlog_spill_bytes
        if buggify.buggify():
            limit = 512   # spill eagerly: exercises the tier under load
        if self._mem_bytes <= limit:
            return
        self._spilling = True
        try:
            # Spill the oldest versions until memory halves.
            acc = 0
            target = 0
            for v, nb in self._bytes_by_version:
                if self._mem_bytes - acc <= limit // 2:
                    break
                acc += nb
                target = v
            if target <= self.spilled_version:
                return
            if self._spill_store is None:
                from .kvstore import SSTableStore

                self._spill_store = await SSTableStore.open(
                    self.queue.disk, self._store_name + "-spill")
            st = self._spill_store
            for tag, entries in self.tag_data.items():
                for v, muts in entries:
                    if v <= target:
                        st.set(_spill_key(tag, v), wire.dumps(muts))
            await st.commit()
            self.spilled_version = max(self.spilled_version, target)
            for tag in list(self.tag_data):
                kept = [(v, m) for (v, m) in self.tag_data[tag] if v > target]
                if kept:
                    self.tag_data[tag] = kept
                else:
                    del self.tag_data[tag]
            keep = []
            freed = 0
            for v, nb in self._bytes_by_version:
                if v <= target:
                    freed += nb
                else:
                    keep.append((v, nb))
            self._bytes_by_version = keep
            self._mem_bytes -= freed
            # Watermark (incl. spilled_version) BEFORE truncating the queue:
            # the spill store + side state now carry these versions. A crash
            # between store-commit and side-persist double-stores rows —
            # harmless (idempotent keys); restore dedupes via the watermark.
            await self._persist_side_state(force=True)
            tgt_off = None
            keep_off = []
            for v, off in self._ver_offsets:
                if v <= target:
                    tgt_off = off
                else:
                    keep_off.append((v, off))
            if tgt_off is not None:
                self._ver_offsets = keep_off
                await self.queue.pop_to(tgt_off)
        finally:
            self._spilling = False

    async def _spilled_messages(self, tag: int, begin: Version, end: Version):
        """Spill-store rows for `tag` in [begin, end], ascending, plus a
        truncation flag (the caller must clip end_version when truncated)."""
        if self._spill_store is None or begin > self.spilled_version:
            return [], False
        lo = _spill_key(tag, begin)
        hi = _spill_key(tag, min(end, self.spilled_version) + 1)
        items, more = await self._spill_store.get_range(lo, hi, 5_000)
        out = [(int.from_bytes(k[8:], "big"), wire.loads(v)) for k, v in items]
        return out, more

    async def _advance_queue_front(self) -> None:
        """Discard queue entries whose every tag has popped past them
        (DiskQueue front = min pop location over tags, DiskQueue.actor.cpp
        via tLogPop)."""
        if self.queue is None or not self._ver_offsets:
            return
        floor = min((self.popped.get(t, 0) for t in self.tags_seen), default=0)
        if buggify.buggify():
            # defer front advance once: queue entries linger past their
            # pops, and the next advance must catch up in one jump
            return
        target = None
        keep = []
        for v, off in self._ver_offsets:
            if v <= floor:
                target = off
            else:
                keep.append((v, off))
        if target is not None:
            self._ver_offsets = keep
            # Watermark first: the entries being dropped are the only other
            # durable record of how far this replica's log reached.
            await self._persist_side_state(force=True)
            await self.queue.pop_to(target)

    # -- write path ----------------------------------------------------------
    async def commit(self, req: TLogCommitRequest) -> Version:
        """Append one version; ack after (simulated) fsync. Returns the
        durable version (reference: tLogCommit, TLogServer.actor.cpp:1158)."""
        if req.gen_id != self.gen_id:
            raise error.tlog_stopped(f"generation {req.gen_id} != {self.gen_id}")
        if self.stopped:
            raise error.tlog_stopped("locked by epoch end")
        if req.known_committed > self.known_committed.get():
            self.known_committed.set(min(req.known_committed, self.version.get()))
        if req.version <= self.version.get() or req.version in self._inflight:
            # Duplicate delivery (proxy retry) — possibly while the first
            # copy is mid-fsync; never append twice.
            await self._wait_version_or_stop(req.version)
            return self.version.get()
        await self._wait_version_or_stop(req.prev_version)
        if self.stopped:
            raise error.tlog_stopped("locked by epoch end")
        if req.version <= self.version.get() or req.version in self._inflight:
            await self._wait_version_or_stop(req.version)
            return self.version.get()
        self._inflight.add(req.version)
        for tag, muts in req.messages.items():
            if tag in self._retired_tags:
                continue  # late repair re-push of a moved-away shard's tag
            self.tags_seen.add(tag)
            self.tag_data.setdefault(tag, []).append((req.version, muts))
        if buggify.buggify():
            # Slow disk: stretches the fsync window other failures race with.
            await delay(0.02, TaskPriority.TLOG_COMMIT)
        if self.queue is not None:
            payload = wire.dumps((req.version, req.messages))
            off = await self.queue.push(payload)
            self._ver_offsets.append((req.version, off))
            self._bytes_by_version.append((req.version, len(payload)))
            self._mem_bytes += len(payload)
            await self.queue.commit()
        else:
            from ..core.knobs import SERVER_KNOBS
            await delay(SERVER_KNOBS.tlog_fsync_seconds, TaskPriority.TLOG_COMMIT)
        # Chained waiters run only after this version is durable.
        self._inflight.discard(req.version)
        if self.stopped:
            # Locked mid-fsync: the append is durable locally but must not
            # be acked — the epoch has ended and recovery's end-version math
            # already treats it as maybe-committed.
            raise error.tlog_stopped("locked during fsync")
        self.version.set(req.version)
        # Only the PUSHER's known-committed may raise the KCV. prev_version
        # is NOT safe here with multiple proxies: another proxy's partial
        # push (died before full quorum) can be a later pusher's
        # prev_version, and serving it would diverge from what epoch-end
        # recovery keeps. Fresh KCVs arrive via the proxies' phase-5
        # send_kcv one-ways, which fire only after a push's full quorum ack.
        if req.known_committed > self.known_committed.get():
            self.known_committed.set(min(req.known_committed, self.version.get()))
        from ..core.knobs import SERVER_KNOBS
        if (self.queue is not None and not self._spilling
                and self._mem_bytes > SERVER_KNOBS.tlog_spill_bytes):
            from ..sim.loop import spawn
            task = spawn(self._maybe_spill(), TaskPriority.TLOG_COMMIT,
                         name=f"tlog-spill:{self._store_name}")
            self.proc.actors.add(task)
        return req.version

    async def _wait_version_or_stop(self, version: Version) -> None:
        """when_at_least raced against the epoch lock: a waiter chained
        behind an append that the lock aborted mid-fsync would otherwise
        park forever (the aborted copy never sets the version). The loser's
        callback is detached from the long-lived stop future so the hot
        commit path does not accumulate one closure per commit."""
        if self.version.get() >= version:
            return
        if self.stopped:
            raise error.tlog_stopped("locked while awaiting version")
        from ..sim.loop import Future

        out = Future()

        def wake(_f) -> None:
            if not out._ready:
                out._set(None)

        self.version.when_at_least(version).on_ready(wake)
        stop_f = self._stop_promise.future
        stop_f.on_ready(wake)
        try:
            await out
        finally:
            stop_f.remove_callback(wake)
        if self.version.get() < version:
            raise error.tlog_stopped("locked while awaiting version")

    async def queue_info(self, _req):
        """Queue depth for the ratekeeper (the reference's TLogQueueInfo
        via getQueuingMetrics): in-memory index bytes + spill watermark."""
        from .ratekeeper import TLogQueueInfo

        return TLogQueueInfo(mem_bytes=self._mem_bytes,
                             spilled_version=self.spilled_version,
                             version=self.version.get())

    async def advance_known_committed(self, req: TLogKnownCommittedRequest) -> None:
        """The proxy reports all replicas acked `version` (the reference
        piggybacks this on the next push; a dedicated message keeps peeks
        moving on an idle system)."""
        if self.stopped:
            return
        v = min(req.version, self.version.get())
        if v > self.known_committed.get():
            self.known_committed.set(v)

    # -- read path -----------------------------------------------------------
    async def peek(self, req: TLogPeekRequest) -> TLogPeekReply:
        """Messages for req.tag with version >= begin_version, clipped to
        the known-committed horizon so nothing recovery could discard is
        ever served (blocks until the horizon passes begin_version)."""
        await self.known_committed.when_at_least(req.begin_version)
        if buggify.buggify():
            await delay(0.05, TaskPriority.TLOG_PEEK)  # slow peek service
        data = self.tag_data.get(req.tag, [])
        horizon = min(self.version.get(), self.known_committed.get())
        if buggify.buggify() and horizon > req.begin_version:
            # short peek page: serve a clipped horizon so consumers must
            # re-peek (the reference's peek reply byte limit)
            horizon = req.begin_version
        begin = max(req.begin_version, self.popped.get(req.tag, 0) + 1)
        spilled, truncated = await self._spilled_messages(req.tag, begin, horizon)
        if truncated and spilled:
            # partial spill read: serve what we have and clip the horizon so
            # the peeker resumes exactly after the last served version
            horizon = spilled[-1][0]
        msgs = spilled + [(v, m) for (v, m) in data
                          if begin <= v <= horizon and v > self.spilled_version]
        return TLogPeekReply(messages=msgs, end_version=horizon)

    async def pop(self, req: TLogPopRequest) -> None:
        if req.version < 0:
            # Tag retired (its shard moved away, MoveKeys finish): forget it
            # entirely so the queue front no longer waits on it.
            self._retired_tags.add(req.tag)
            self.tag_data.pop(req.tag, None)
            self.popped.pop(req.tag, None)
            self.tags_seen.discard(req.tag)
            await self._advance_queue_front()
            await self._persist_side_state(force=True)
            return
        if req.tag in self._retired_tags:
            return  # straggler pop from the retired replica's update loop
        prev = self.popped.get(req.tag, 0)
        if req.version <= prev:
            return
        self.popped[req.tag] = req.version
        self.tags_seen.add(req.tag)
        data = self.tag_data.get(req.tag)
        if data:
            self.tag_data[req.tag] = [(v, m) for (v, m) in data if v > req.version]
        if self._spill_store is not None:
            # lazily durable (uncommitted clears are memtable-visible; a
            # crash only re-serves acknowledged rows)
            self._spill_store.clear_range(
                _spill_key(req.tag, 0),
                _spill_key(req.tag, min(req.version, self.spilled_version) + 1))
        await self._advance_queue_front()
        await self._persist_side_state()

    # -- epoch end -----------------------------------------------------------
    async def lock(self, req: TLogLockRequest) -> TLogLockReply:
        """reference: tLogLock (TLogServer.actor.cpp:496). Idempotent. The
        lock is made DURABLE before the reply: the recovering master's
        min(end) math counts on this replica rejecting pushes forever,
        across its own reboots."""
        if buggify.buggify():
            # slow lock ack: the recovering master's lock fan-out completes
            # ragged, and commits mid-fsync see the stop flag at odd points
            await delay(0.05, TaskPriority.TLOG_COMMIT)
        self.stopped = True
        if not self._stop_promise.is_set:
            self._stop_promise.send(None)
        # EVERY lock reply waits for a durable stopped flag — a retried or
        # concurrent lock must not ack off the back of a first caller's
        # still-in-flight fsync (the persist mutex serializes; re-persisting
        # an already-durable flag is a no-op-shaped small write)
        await self._persist_side_state(force=True)
        return TLogLockReply(
            gen_id=self.gen_id,
            known_committed=self.known_committed.get(),
            end_version=self.version.get(),
        )

    async def recovery_data(self, req: TLogRecoveryDataRequest) -> TLogRecoveryDataReply:
        """All un-popped data up to the recovery version, for seeding the
        next generation (the copy replaces the reference's old-generation
        peek cursors) — INCLUDING the spilled tier, which holds the oldest
        part of the un-popped window (the reference's recovery peeks read
        through the persistent store the same way)."""
        clip = req.end_version
        out: Dict[int, list] = {}
        for tag in self.tags_seen:
            if tag in self._retired_tags:
                continue
            begin = self.popped.get(tag, 0) + 1
            spilled, truncated = await self._spilled_messages(tag, begin, clip)
            while truncated:
                more, truncated = await self._spilled_messages(
                    tag, spilled[-1][0] + 1, clip)
                spilled.extend(more)
            mem = [(v, m) for (v, m) in self.tag_data.get(tag, [])
                   if v <= clip and v > self.spilled_version]
            if spilled or mem:
                out[tag] = spilled + mem
        return TLogRecoveryDataReply(
            tag_data=out,
            popped=dict(self.popped),
        )
