"""TLog: tag-partitioned in-memory durable log.

Round-1 scope of fdbserver/TLogServer.actor.cpp: commits arrive per version
with messages already bucketed by destination tag (tLogCommit:1158), are
serialized by (prev_version -> version) chaining, indexed per tag, and
served to storage servers via blocking peeks (tLogPeekMessages:950) with
pops (tLogPop:898) trimming acknowledged prefixes. The DiskQueue + spill
machinery arrives with the durable-storage round; in-memory plus a simulated
fsync delay preserves the commit path's latency structure.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..core.types import Mutation, Version
from ..sim.actors import NotifiedVersion
from ..sim.loop import TaskPriority, delay
from ..sim.network import SimProcess
from .messages import (
    TLogCommitRequest,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
)

COMMIT_TOKEN = "tlog.commit"
PEEK_TOKEN = "tlog.peek"
POP_TOKEN = "tlog.pop"

FSYNC_SECONDS = 0.0005


class TLog:
    def __init__(self, proc: SimProcess, start_version: Version = 0):
        self.proc = proc
        self.version = NotifiedVersion(start_version)
        # tag -> ordered [(version, mutations)]
        self.tag_data: Dict[int, List[Tuple[Version, List[Mutation]]]] = {}
        self.popped: Dict[int, Version] = {}
        self._inflight: set = set()  # versions appended but not yet durable
        proc.register(COMMIT_TOKEN, self.commit)
        proc.register(PEEK_TOKEN, self.peek)
        proc.register(POP_TOKEN, self.pop)

    async def commit(self, req: TLogCommitRequest) -> Version:
        """Append one version; ack after (simulated) fsync. Returns the
        durable version."""
        if req.version <= self.version.get() or req.version in self._inflight:
            # Duplicate delivery (proxy retry) — possibly while the first
            # copy is mid-fsync; never append twice.
            await self.version.when_at_least(req.version)
            return self.version.get()
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get() or req.version in self._inflight:
            await self.version.when_at_least(req.version)
            return self.version.get()
        self._inflight.add(req.version)
        for tag, muts in req.messages.items():
            self.tag_data.setdefault(tag, []).append((req.version, muts))
        await delay(FSYNC_SECONDS, TaskPriority.TLOG_COMMIT)
        # Chained waiters run only after this version is durable.
        self._inflight.discard(req.version)
        self.version.set(req.version)
        return req.version

    async def peek(self, req: TLogPeekRequest) -> TLogPeekReply:
        """Messages for req.tag with version >= begin_version; blocks until
        the tlog has seen begin_version so the peeker always advances."""
        await self.version.when_at_least(req.begin_version)
        data = self.tag_data.get(req.tag, [])
        # Clip to the durable version: entries beyond it are mid-fsync and
        # would be applied twice by a peeker that can't advance past them.
        durable = self.version.get()
        msgs = [(v, m) for (v, m) in data if req.begin_version <= v <= durable]
        return TLogPeekReply(messages=msgs, end_version=durable)

    async def pop(self, req: TLogPopRequest) -> None:
        prev = self.popped.get(req.tag, 0)
        if req.version <= prev:
            return
        self.popped[req.tag] = req.version
        data = self.tag_data.get(req.tag)
        if data:
            self.tag_data[req.tag] = [(v, m) for (v, m) in data if v > req.version]
