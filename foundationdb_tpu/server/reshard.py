"""Online resolver resharding: the elastic resolution tier.

ROADMAP item 4, the first change that makes the cluster ADAPT rather
than merely observe and throttle. Two pieces:

  * `ElasticResolverGroup` — a live group of supervised conflict engines
    (fault/resilient.py) partitioned by an epoched key-shard map
    (core/keyshard.EpochedKeyShardMap). Every batch routes by the epoch
    its commit version selects, so a flip at version F is atomic: batches
    below F resolve under the old partition, batches at or above F under
    the new one, and a transaction straddling the flip resolves under
    exactly the epoch its batch version picks — never both. Cross-shard
    batches run the same two-phase structure as the mesh kernel
    (parallel/sharding.py): local history detection per shard, ONE global
    earlier-in-batch-wins sweep on the host (the abort-set exchange), and
    write application of globally committed transactions only — so
    combined verdicts are bit-identical to a single serial oracle over
    the same stream, and no shard's table is ever polluted by a
    transaction another shard aborted.

  * `ReshardController` — the control loop that consumes the group's
    measured keyspace heat (concentration + equal-load split points,
    core/heatmap.py) and the watchdog's burn signal, and executes
    split / merge / move of key ranges on the live cluster: warm a
    recipient engine (pre-warmed spare or fresh), PRE-COPY the donor's
    coalesced committed-write history for the moving range while the
    donor keeps serving (fault/handoff.py), then freeze the range,
    transfer the residual delta, flip the epoch and unfreeze — the
    freeze -> cutover interval is the only per-range blackout, bounded
    by `reshard_blackout_budget_ms` and asserted per executed reshard.

Everything here is host-side and jax-free: device engines arrive through
the injected `engine_factory`, the same stack production nodes run.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core import blackbox, error, telemetry
from ..core.heatmap import (
    LANE_CONFLICTS,
    LANE_WRITES,
    KeyRangeHeatAggregator,
    _fmt_key,
)
from ..core.keyshard import EpochedKeyShardMap, KeyShardMap
from ..core.knobs import SERVER_KNOBS
from ..core.trace import g_spans, span_event, span_now
from ..core.types import (
    CommitTransaction,
    Key,
    KeyRange,
    TransactionCommitResult,
    Version,
)
from ..fault import handoff
from ..sim.loop import Promise, TaskPriority, current_scheduler, delay

#: span segments the reshard protocol emits (`reshard.<segment>` — the
#: fdbtpu-lint span-registry rule checks reshard.* sites against this
#: tuple, like commit-path sites against ATTRIBUTION_SEGMENTS). These are
#: protocol-arc segments on their own timeline, not members of the commit
#: waterfall's telescoping sum.
RESHARD_SEGMENTS = (
    "warm",       # recipient engine build + ladder warmup (outside blackout)
    "precopy",    # unfrozen coalesced history pre-copy rounds
    "transfer",   # frozen residual-delta replay (inside the blackout)
    "blackout",   # freeze -> cutover: the only per-range unavailability
    "cutover",    # epoch install + unfreeze
)

#: pre-copy convergence: stop iterating once the residual delta is this
#: small (the frozen transfer then replays at most this many batches),
#: or after this many rounds regardless
PRECOPY_DELTA_TARGET = 8
PRECOPY_MAX_ROUNDS = 3

#: bounded duplicate-delivery verdict cache (versions -> verdicts)
RECENT_VERDICTS = 512

_COMMITTED = int(TransactionCommitResult.COMMITTED)
_TOO_OLD = int(TransactionCommitResult.TOO_OLD)
_CONFLICT = int(TransactionCommitResult.CONFLICT)


def _overlaps(a_begin: Key, a_end: Key, b_begin: Key, b_end: Key) -> bool:
    return a_begin < b_end and b_begin < a_end


@dataclass
class ShardSlot:
    """One engine's seat in the group. Slots outlive epochs: a donor
    retired by a merge cools down until recycled as a spare (its compiled
    programs survive clear(), so recycling never recompiles)."""

    sid: int
    inner: object
    injector: object
    engine: object            # the ResilientEngine
    batcher: Optional[object] = None


class ElasticResolverGroup:
    """A live, repartitionable group of supervised resolver engines."""

    name = "elastic"

    def __init__(self, engine_factory: Callable,
                 make_batcher: Optional[Callable] = None):
        #: () -> (inner, injector, supervised ResilientEngine) — journal
        #: recording is the factory's choice; the group replays whatever
        #: journals its slot engines kept (parity_check)
        self.engine_factory = engine_factory
        self._make_batcher = make_batcher
        self.slots: Dict[int, ShardSlot] = {}
        self._next_sid = 0
        self.spares: List[int] = []
        self.cooling: List[int] = []
        first = self.new_slot()
        self.emap = EpochedKeyShardMap(KeyShardMap([]))
        #: epoch id -> slot id per span of that epoch's map
        self._assign: Dict[int, List[int]] = {0: [first.sid]}
        #: group-level host-fed heat (core/heatmap.py observe_batch): the
        #: controller's split-planning input, engine-mode agnostic — the
        #: per-engine device histograms keep feeding telemetry separately
        self.heat = KeyRangeHeatAggregator(
            key_words=4, capacity=0, buckets=0,
            decay=float(getattr(SERVER_KNOBS, "resolver_heat_decay", 0.98)))
        telemetry.hub().register_heat(self.heat, "elastic")
        self._oldest: Version = 0
        self.last_version: Version = 0
        #: duplicate-delivery guard: a version resolved once answers from
        #: this cache forever after (bounded), and a version still in
        #: dispatch hands duplicates the in-flight future — across a
        #: handoff a duplicate must RESOLVE ONCE, never re-apply
        self._recent: Dict[Version, List[int]] = {}
        self._inflight: Dict[Version, Promise] = {}
        #: frozen ranges mid-handoff: (begin, end-or-None) spans a batch
        #: touching them waits out (the measured blackout)
        self._frozen: List[Tuple[Key, Optional[Key]]] = []
        self._busy: Optional[Promise] = None
        #: set by the attached ReshardController for the whole handoff arc
        self.reshard_in_flight = False
        self.extra_stats = {"fast_batches": 0, "two_phase_batches": 0,
                            "frozen_waits": 0}

    # -- slots ---------------------------------------------------------------
    def new_slot(self) -> ShardSlot:
        inner, injector, engine = self.engine_factory()
        slot = ShardSlot(self._next_sid, inner, injector, engine,
                         batcher=(self._make_batcher()
                                  if self._make_batcher else None))
        self._next_sid += 1
        self.slots[slot.sid] = slot
        return slot

    def prewarm_spares(self, n: int) -> None:
        """Build + warm standby engines BEFORE traffic so a reshard's
        recipient is ready without compiling on the serving path."""
        for _ in range(max(0, n)):
            slot = self.new_slot()
            fn = getattr(slot.engine, "warmup", None)
            if fn is not None:
                fn()
            self.spares.append(slot.sid)

    def take_recipient(self) -> Tuple[ShardSlot, bool]:
        """(slot, was_prewarmed): a spare if one is ready, else a
        recycled cooling donor (compiled programs persist across
        clear()), else a fresh build — the caller records the warm
        window in the last case. A cooling donor is recyclable only once
        NO retained epoch routes to it any more: the epoch chain is kept
        precisely so versions below the newest flip can still resolve,
        and clearing a slot an old epoch references would serve those
        straddlers an emptied conflict table."""
        if self.spares:
            return self.slots[self.spares.pop(0)], True
        still_routed = {sid for sids in self._assign.values()
                        for sid in sids}
        for i, sid in enumerate(self.cooling):
            if sid in still_routed:
                continue
            slot = self.slots[self.cooling.pop(i)]
            slot.engine.clear(0)
            # the journal restarts with the table: parity_check replays
            # each journal through ONE fresh oracle, so pre-clear batches
            # left in it would replay writes the cleared engine no longer
            # holds and report false mismatches
            if slot.engine.journal is not None:
                slot.engine.journal.clear()
            return slot, True
        return self.new_slot(), False

    def retire_slot(self, sid: int) -> None:
        self.cooling.append(sid)

    def active_sids(self) -> List[int]:
        return list(self._assign[self.emap.epoch])

    # -- engine surface (what ChaosCommitServer / resolvers consume) ---------
    @property
    def degraded(self) -> bool:
        return any(self.slots[s].engine.degraded for s in self.active_sids())

    @property
    def stats(self) -> Dict[str, int]:
        out: Dict[str, int] = dict(self.extra_stats)
        for slot in self.slots.values():
            for k, v in slot.engine.stats.items():
                out[k] = out.get(k, 0) + int(v)
        out["shards"] = len(self.active_sids())
        return out

    @property
    def loop_stats(self) -> Optional[Dict[str, float]]:
        """Aggregated device-loop sync accounting across every slot that
        has one (device_loop engine mode) — blocking_syncs must stay 0
        group-wide; None for step/oracle modes."""
        agg: Optional[Dict[str, float]] = None
        for slot in self.slots.values():
            st = getattr(slot.inner, "loop_stats", None)
            if st is None:
                continue
            if agg is None:
                agg = {}
            for k, v in st.items():
                agg[k] = agg.get(k, 0) + v
        return agg

    def device_view(self) -> Optional[List[dict]]:
        """Per-slot device placement for mesh-backed slots — each active
        slot's mesh engine reports its shard -> device rows (device id,
        table bytes, last measured collective ms) tagged with the slot id
        routing sends it traffic under. None when no slot is mesh-backed
        (single-chip engine modes): `cli shards` renders the epoch map
        alone, old reports stay readable."""
        out: List[dict] = []
        for sid in self.active_sids():
            fn = getattr(self.slots[sid].inner, "device_view", None)
            if fn is None:
                continue
            for row in fn():
                out.append({"sid": sid, **row})
        return out or None

    def health_stats(self) -> dict:
        sev = {"healthy": 0, "suspect": 1, "failed": 2, "probation": 3,
               "quarantined": 4}
        states = [self.slots[s].engine.state for s in self.active_sids()]
        worst = max(states, key=lambda s: sev.get(s, 0)) if states else "healthy"
        return {
            "state": worst,
            "degraded": self.degraded,
            "device": "elastic",
            "shards": len(self.active_sids()),
            "epoch": self.emap.epoch,
            "reshard_in_flight": self.reshard_in_flight,
            "per_shard": [{"sid": s, "state": self.slots[s].engine.state}
                          for s in self.active_sids()],
            **{k: v for k, v in self.stats.items()},
        }

    def heat_snapshot(self, top_n: int = 8, brief: bool = False) -> dict:
        snap = self.heat.snapshot(top_n=top_n, brief=brief)
        if not brief:
            snap["epoch"] = self.emap.epoch
            snap["shard_splits"] = [_fmt_key(k)
                                    for k in self.emap.current().begins[1:]]
        return snap

    def warmup(self) -> "ElasticResolverGroup":
        for sid in self.active_sids():
            fn = getattr(self.slots[sid].engine, "warmup", None)
            if fn is not None:
                fn()
        return self

    def clear(self, version: Version) -> None:
        for slot in self.slots.values():
            slot.engine.clear(version)
        self._recent.clear()

    def parity_check(self) -> Tuple[int, int]:
        """Replay EVERY slot engine's journal through its own clean CPU
        oracle (the per-engine contract of fault/resilient.py, summed):
        each shard's emitted abort sets — handoff adoption batches
        included — must be bit-identical to a fault-free engine's."""
        from ..ops.oracle import OracleConflictEngine

        checked = mismatches = 0
        for slot in self.slots.values():
            clean = OracleConflictEngine()
            for version, txns, new_oldest, verdicts in slot.engine.journal or []:
                want = clean.resolve(list(txns), version, new_oldest)
                checked += 1
                if [int(x) for x in want] != [int(x) for x in verdicts]:
                    mismatches += 1
        return checked, mismatches

    # -- freeze gate ---------------------------------------------------------
    def freeze(self, ranges: Sequence[Tuple[Key, Optional[Key]]]) -> None:
        self._frozen.extend(ranges)

    def unfreeze(self) -> None:
        self._frozen = []

    def _touches_frozen(self, transactions) -> bool:
        if not self._frozen:
            return False
        for txn in transactions:
            for rngs in (txn.read_conflict_ranges, txn.write_conflict_ranges):
                for r in rngs:
                    for fb, fe in self._frozen:
                        if r.begin >= r.end:
                            # empty range: a point probe at begin —
                            # conservative boundary-inclusive test
                            if r.begin >= fb and (fe is None or r.begin <= fe):
                                return True
                        # fe None is a TRUE +inf (the last span), so only
                        # the lower bound constrains the overlap test
                        elif (fe is None or r.begin < fe) and fb < r.end:
                            return True
        return False

    async def quiesce(self) -> None:
        """Wait out the batch in flight at call time (the controller
        freezes first, so every later batch touching the moving ranges
        blocks at the gate; untouched batches keep flowing)."""
        busy = self._busy
        if busy is not None:
            await busy.future

    # -- resolution ----------------------------------------------------------
    async def resolve(self, transactions, now_v: Version,
                      new_oldest: Version):
        cached = self._recent.get(now_v)
        if cached is not None:
            return list(cached)
        inflight = self._inflight.get(now_v)
        if inflight is not None:
            return await inflight.future
        p = Promise()
        self._inflight[now_v] = p
        try:
            verdicts = await self._resolve_impl(transactions, now_v,
                                                new_oldest)
        except BaseException as e:
            self._inflight.pop(now_v, None)
            if not p.is_set:
                p.send_error(e if isinstance(e, error.FDBError)
                             else error.device_fault(
                                 f"elastic resolve {now_v} failed: {e}"))
            raise
        self._recent[now_v] = list(verdicts)
        while len(self._recent) > RECENT_VERDICTS:
            self._recent.pop(next(iter(self._recent)))
        self._inflight.pop(now_v, None)
        p.send(list(verdicts))
        return verdicts

    async def _resolve_impl(self, transactions, now_v: Version,
                            new_oldest: Version):
        # freeze gate: a batch touching a mid-handoff range waits for the
        # cutover (the measured per-range blackout); untouched batches
        # pass the gate. NOTE the per-range guarantee is at THIS
        # interface: a version-ordered serial caller (the commit
        # batcher) cannot overtake a parked batch, so downstream of one
        # the whole pipeline stalls for the blackout — which is exactly
        # why the blackout carries a tight budget and its windows are
        # excluded from the p99 population (docs/elasticity.md)
        if self._touches_frozen(transactions):
            self.extra_stats["frozen_waits"] += 1
            while self._touches_frozen(transactions):
                await delay(0.002, TaskPriority.PROXY_RESOLVER_REPLY)
        self._busy = Promise()
        try:
            _e, _fv, m = self.emap.entry_for_version(now_v)
            sids = self._assign[_e]
            n = len(transactions)
            gate = self._oldest
            too_old = [bool(t.read_conflict_ranges) and t.read_snapshot < gate
                       for t in transactions]
            touched: List[List[int]] = []
            for t, txn in enumerate(transactions):
                sh: set = set()
                if not too_old[t]:
                    for r in txn.read_conflict_ranges:
                        if r.begin >= r.end:
                            sh.add(m.shard_of_point_below(r.begin))
                        else:
                            sh.update(s for s, _b, _e2 in
                                      m.shards_of_range(r.begin, r.end))
                    for r in txn.write_conflict_ranges:
                        if r.begin < r.end:
                            sh.update(s for s, _b, _e2 in
                                      m.shards_of_range(r.begin, r.end))
                touched.append(sorted(sh))
            if all(len(s) <= 1 for s in touched):
                verdicts = await self._resolve_fast(
                    transactions, now_v, new_oldest, m, sids, too_old, touched)
            else:
                verdicts = await self._resolve_two_phase(
                    transactions, now_v, new_oldest, m, sids, too_old)
            if new_oldest > self._oldest:
                self._oldest = new_oldest
                self.emap.gc(self._oldest)
                retained = {e for e, _fv, _m in self.emap.epochs}
                for e in [e for e in self._assign if e not in retained]:
                    del self._assign[e]
            self.last_version = max(self.last_version, now_v)
            self.heat.observe_batch(transactions, verdicts, version=now_v)
            if blackbox.enabled():
                # the group is the resolution tier's top level here: ONE
                # batch record per version (slot engines underneath never
                # record), stamped with the epoch that routed it — the
                # differential-replay unit of core/blackbox.py
                shards_touched = sorted({s for sh in touched for s in sh})
                blackbox.record_batch(
                    transactions, now_v, new_oldest, verdicts,
                    epoch=_e,
                    shard=(shards_touched[0]
                           if len(shards_touched) == 1 else -1),
                    engine="elastic",
                    served_by=("fast" if all(len(s) <= 1 for s in touched)
                               else "two_phase"),
                    witness=self.heat.attribution_for(now_v))
            return verdicts
        finally:
            busy, self._busy = self._busy, None
            if busy is not None and not busy.is_set:
                busy.send(None)

    async def _resolve_fast(self, transactions, now_v, new_oldest, m, sids,
                            too_old, touched):
        """Every transaction's ranges live inside one shard: dispatch each
        shard its whole sub-batch in one pass. Disjoint key families never
        interact in the serial oracle, so per-shard resolution composes to
        exactly the serial verdicts."""
        self.extra_stats["fast_batches"] += 1
        per_shard: Dict[int, List[int]] = {}
        for t, sh in enumerate(touched):
            if too_old[t] or not sh:
                continue
            per_shard.setdefault(sh[0], []).append(t)
        verdicts = [_TOO_OLD if too_old[t] else _COMMITTED
                    for t in range(len(transactions))]
        results = await self._dispatch_shards(
            {s: [transactions[t] for t in per_shard[s]]
             for s in per_shard}, sids, now_v, new_oldest)
        for s, got in results.items():
            for t, vd in zip(per_shard[s], got):
                verdicts[t] = int(vd)
        return verdicts

    async def _resolve_two_phase(self, transactions, now_v, new_oldest, m,
                                 sids, too_old):
        """Cross-shard batch: the host-side analog of the mesh kernel's
        exchange (parallel/sharding.py). Phase 1 asks every shard for
        history hits on its CLIPPED read views (read-only — applies
        nothing); the global earlier-in-batch-wins sweep then runs ONCE
        on the full unclipped ranges (the oracle's intra-batch phase,
        verbatim); phase 2 applies only globally committed transactions'
        clipped writes. Verdicts are bit-identical to one serial oracle
        over the same stream, and no shard table ever contains a write of
        a transaction another shard aborted."""
        self.extra_stats["two_phase_batches"] += 1
        n = len(transactions)
        conflict = [False] * n
        # phase 1: per-shard read-only clipped views
        views: Dict[int, List[Tuple[int, CommitTransaction]]] = {}
        for t, txn in enumerate(transactions):
            if too_old[t] or not txn.read_conflict_ranges:
                continue
            per: Dict[int, CommitTransaction] = {}

            def view(s: int) -> CommitTransaction:
                if s not in per:
                    per[s] = CommitTransaction(
                        read_snapshot=txn.read_snapshot)
                return per[s]

            for r in txn.read_conflict_ranges:
                if r.begin >= r.end:
                    view(m.shard_of_point_below(r.begin)) \
                        .read_conflict_ranges.append(r)
                else:
                    for s, cb, ce in m.shards_of_range(r.begin, r.end):
                        view(s).read_conflict_ranges.append(KeyRange(cb, ce))
            for s, vw in per.items():
                views.setdefault(s, []).append((t, vw))
        results = await self._dispatch_shards(
            {s: [vw for _t, vw in views[s]] for s in views},
            sids, now_v, new_oldest)
        for s, got in results.items():
            for (t, _vw), vd in zip(views[s], got):
                if int(vd) != _COMMITTED:
                    conflict[t] = True
        # global intra-batch sweep, strictly in submission order
        written: List[KeyRange] = []
        for t, txn in enumerate(transactions):
            if too_old[t] or conflict[t]:
                continue
            hit = False
            for r in txn.read_conflict_ranges:
                if r.begin < r.end and any(
                        _overlaps(r.begin, r.end, w.begin, w.end)
                        for w in written):
                    hit = True
                    break
            if hit:
                conflict[t] = True
                continue
            for w in txn.write_conflict_ranges:
                if w.begin < w.end:
                    written.append(w)
        # phase 2: apply globally committed writes, clipped per shard
        wviews: Dict[int, List[CommitTransaction]] = {}
        for t, txn in enumerate(transactions):
            if too_old[t] or conflict[t]:
                continue
            per_w: Dict[int, CommitTransaction] = {}
            for r in txn.write_conflict_ranges:
                if r.begin >= r.end:
                    continue
                for s, cb, ce in m.shards_of_range(r.begin, r.end):
                    vw = per_w.get(s)
                    if vw is None:
                        vw = per_w[s] = CommitTransaction(
                            read_snapshot=now_v)
                    vw.write_conflict_ranges.append(KeyRange(cb, ce))
            for s, vw in per_w.items():
                wviews.setdefault(s, []).append(vw)
        await self._dispatch_shards(wviews, sids, now_v, new_oldest)
        return [
            _TOO_OLD if too_old[t] else
            (_CONFLICT if conflict[t] else _COMMITTED)
            for t in range(n)
        ]

    async def _dispatch_shards(self, sub_by_shard: Dict[int, list], sids,
                               now_v, new_oldest) -> Dict[int, list]:
        """Dispatch every shard's sub-batch CONCURRENTLY and join in
        sorted-shard order (deterministic assembly; batch latency is the
        max of the shard resolves, not their sum — the overlap sharding
        exists for). Every task is awaited even after a failure so no
        dispatch is abandoned mid-flight; the first error propagates."""
        shards = sorted(sub_by_shard)
        if len(shards) == 1:
            s = shards[0]
            return {s: await self._slot_resolve(
                sids[s], self.slots[sids[s]].engine, sub_by_shard[s],
                now_v, new_oldest)}
        sched = current_scheduler()
        tasks = [(s, sched.spawn(
            self._slot_resolve(sids[s], self.slots[sids[s]].engine,
                               sub_by_shard[s], now_v, new_oldest),
            TaskPriority.PROXY_RESOLVER_REPLY,
            name=f"shardResolve.{s}")) for s in shards]
        results: Dict[int, list] = {}
        first_err: Optional[BaseException] = None
        for s, task in tasks:
            try:
                results[s] = await task
            except BaseException as e:   # noqa: BLE001 — collected below
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return results

    async def _slot_resolve(self, sid: int, eng, sub, now_v, new_oldest):
        t0 = span_now()
        got = await eng.resolve(sub, now_v, new_oldest)
        slot = self.slots[sid]
        if slot.batcher is not None and sub:
            slot.batcher.observe(slot.batcher.bucket_of(len(sub)),
                                 (span_now() - t0) * 1e3)
        return got


# -- the control loop ---------------------------------------------------------

@dataclass
class ReshardOp:
    """One executed (or in-flight) reshard, the report/CLI record."""

    id: int
    kind: str                      # split | merge | move
    begin: str                     # moving range, formatted
    end: Optional[str]
    donor_sids: List[int]
    recipient_sid: int = -1
    state: str = "planned"         # planned -> warm -> precopy -> frozen
    #                               -> done | stalled | aborted
    t_start: float = 0.0
    t_freeze: float = 0.0
    t_cutover: float = 0.0
    flip_version: int = 0
    epoch: int = 0
    blackout_ms: float = 0.0
    precopied: int = 0
    delta: int = 0
    prewarmed: bool = False
    ewmas_migrated: int = 0
    error: Optional[str] = None

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class ReshardController:
    """Heat-driven split/merge/move of live resolver key ranges."""

    def __init__(self, group: ElasticResolverGroup,
                 now_fn: Callable[[], float] = span_now,
                 min_heat_batches: int = 20,
                 on_complete: Optional[Callable] = None):
        self.group = group
        self.now_fn = now_fn
        self.min_heat_batches = min_heat_batches
        self.on_complete = on_complete
        self.ops: List[ReshardOp] = []
        self.current: Optional[ReshardOp] = None
        self.executed = 0
        self.stalled = 0
        self.blackout_ms_max = 0.0
        self.blackout_over_budget = 0
        #: {kind, t0, t1} wall-clock records of blackout + inline-warm
        #: intervals — the campaign's SLO exclusion/correlation windows
        self.windows: List[dict] = []
        self._next_id = 1
        self._last_done = 0.0
        self._task = None
        telemetry.hub().register_reshard(self, "controller")

    # -- telemetry read model ------------------------------------------------
    def in_flight(self) -> bool:
        return self.current is not None

    def in_flight_age_s(self) -> float:
        if self.current is None:
            return 0.0
        return max(0.0, self.now_fn() - self.current.t_start)

    def in_flight_detail(self) -> Optional[str]:
        """What a stalled-reshard incident should lead with: the frozen
        range and the donor engine's health state."""
        op = self.current
        if op is None:
            return None
        donors = ", ".join(
            f"r{sid} state={self.group.slots[sid].engine.state}"
            for sid in op.donor_sids if sid in self.group.slots)
        end = op.end if op.end is not None else "+inf"
        return (f"reshard of [{op.begin},{end}) {op.state} · donor {donors}")

    def snapshot(self) -> dict:
        return {
            "executed": self.executed,
            "stalled": self.stalled,
            "in_flight": (self.current.as_dict()
                          if self.current is not None else None),
            "blackout_ms_max": round(self.blackout_ms_max, 3),
            "blackout_budget_ms": float(
                SERVER_KNOBS.reshard_blackout_budget_ms),
            "blackout_over_budget": self.blackout_over_budget,
            "epoch": self.group.emap.epoch,
            "shard_map": self.group.emap.as_dict(),
            "device_view": self.group.device_view(),
            "ops": [op.as_dict() for op in self.ops],
            "group": {k: v for k, v in self.group.extra_stats.items()},
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self, sched) -> None:
        self._task = sched.spawn(self._run(), TaskPriority.RATEKEEPER,
                                 name="reshardController")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            await delay(float(SERVER_KNOBS.reshard_eval_interval_s),
                        TaskPriority.RATEKEEPER)
            op = self.current
            if op is not None:
                if (op.state == "stalled" and self.in_flight_age_s()
                        > 2 * float(SERVER_KNOBS.reshard_stall_s)):
                    # the stalled alert has fired and held; abandon the op
                    # so the cluster returns to a steady (old-epoch) state
                    op.state = "aborted"
                    self.group.unfreeze()
                    self.group.reshard_in_flight = False
                    self.current = None
                continue
            plan = self.plan()
            if plan is not None:
                await self.execute(plan)

    # -- planning ------------------------------------------------------------
    def _min_interval_s(self) -> float:
        base = float(SERVER_KNOBS.reshard_min_interval_s)
        wd = telemetry.hub().watchdog
        if wd is not None and wd.burn_firing():
            # the SLO budget is burning NOW: a partition that no longer
            # tracks the load is a likely cause — react at double speed
            return base / 2
        return base

    def plan(self) -> Optional[dict]:
        g = self.group
        if g.heat.batches < self.min_heat_batches:
            return None
        if self.now_fn() - self._last_done < self._min_interval_s():
            return None
        m = g.emap.current()
        splits = list(m.begins[1:])
        shares = g.heat.split_balance(len(splits) + 1, splits)
        if not shares:
            return None
        max_shards = int(SERVER_KNOBS.reshard_max_shards)
        split_share = float(SERVER_KNOBS.reshard_split_share)
        merge_share = float(SERVER_KNOBS.reshard_merge_share)
        hot = max(range(len(shares)), key=lambda i: shares[i])
        if shares[hot] > split_share and len(shares) < max_shards:
            b = m.begins[hot]
            e = m.span_end(hot)
            k = g.heat.split_key_within(b, e)
            if k is not None:
                return {"kind": "split", "span": hot, "key": k}
        if len(shares) > 1:
            pairs = [(shares[i] + shares[i + 1], i)
                     for i in range(len(shares) - 1)]
            combined, i = min(pairs)
            if combined < merge_share:
                return {"kind": "merge", "span": i}
        if shares[hot] > split_share and len(shares) >= max_shards:
            # at the shard cap: MOVE load off the hottest span by shifting
            # its boundary toward a lighter neighbor
            nb = hot + 1 if hot + 1 < len(shares) else hot - 1
            if 0 <= nb < len(shares) and shares[nb] < merge_share:
                lo = m.begins[hot]
                hi = m.span_end(hot)
                k = g.heat.split_key_within(lo, hi)
                if k is not None:
                    return {"kind": "move", "span": hot, "neighbor": nb,
                            "key": k}
        return None

    # -- execution -----------------------------------------------------------
    async def execute(self, plan: dict) -> Optional[ReshardOp]:
        g = self.group
        m = g.emap.current()
        sids = g.active_sids()
        splits = list(m.begins[1:])
        kind = plan["kind"]
        s = plan["span"]
        if kind == "split":
            # new slot takes [key, span_end); donor keeps [begin, key)
            key = plan["key"]
            moving = [(sids[s], key, m.span_end(s))]
            new_splits = sorted(set(splits + [key]))
            new_sids_of = lambda rsid: (
                sids[: s + 1] + [rsid] + sids[s + 1:])
            retire: List[int] = []
            begin, end = key, m.span_end(s)
        elif kind == "merge":
            # fresh slot takes both spans; both donors retire
            moving = [(sids[s], m.begins[s], m.span_end(s)),
                      (sids[s + 1], m.begins[s + 1], m.span_end(s + 1))]
            new_splits = [k for k in splits if k != m.begins[s + 1]]
            new_sids_of = lambda rsid: (sids[:s] + [rsid] + sids[s + 2:])
            retire = [sids[s], sids[s + 1]]
            begin, end = m.begins[s], m.span_end(s + 1)
        else:   # move: neighbor absorbs [key, span_end(s)) (or the mirror)
            key = plan["key"]
            nb = plan["neighbor"]
            if nb > s:
                # recipient takes [key, end(nb)): donor's tail + neighbor
                moving = [(sids[s], key, m.span_end(s)),
                          (sids[nb], m.begins[nb], m.span_end(nb))]
                new_splits = sorted(set(
                    [k for k in splits if k != m.begins[nb]] + [key]))
                begin, end = key, m.span_end(nb)
            else:
                # recipient takes [begin(nb), key): neighbor + donor's head
                moving = [(sids[nb], m.begins[nb], m.span_end(nb)),
                          (sids[s], m.begins[s], key)]
                new_splits = sorted(set(
                    [k for k in splits if k != m.begins[s]] + [key]))
                begin, end = m.begins[nb], key
            new_sids_of = lambda rsid: [
                rsid if i == nb else sid for i, sid in enumerate(sids)]
            retire = [sids[nb]]
        op = ReshardOp(
            id=self._next_id, kind=kind, begin=_fmt_key(begin),
            end=_fmt_key(end) if end is not None else None,
            donor_sids=[sid for sid, _b, _e in moving],
            t_start=self.now_fn())
        self._next_id += 1
        self.ops.append(op)
        self.current = op
        g.reshard_in_flight = True
        spans_on = g_spans.enabled
        rid = f"reshard-{op.id}"
        recipient = None
        try:
            # WARM: recipient out of the spare/cooling pool, or inline
            # (recorded as a window — compiles on the serving path are an
            # incident, not steady state)
            op.state = "warm"
            t0 = self.now_fn()
            ts0 = span_now()
            recipient, prewarmed = g.take_recipient()
            op.recipient_sid, op.prewarmed = recipient.sid, prewarmed
            if not prewarmed:
                fn = getattr(recipient.engine, "warmup", None)
                if fn is not None:
                    fn()
                self.windows.append({"kind": "reshard_warm", "t0": t0,
                                     "t1": self.now_fn()})
            if spans_on:
                span_event("reshard.warm", rid, ts0, span_now(),
                           Proc="reshard", prewarmed=prewarmed)
            if blackbox.enabled():
                blackbox.record_reshard(op, "warm")
            # PRE-COPY: coalesced history while the donors keep serving
            op.state = "precopy"
            ts0 = span_now()
            marks = {sid: 0 for sid, _b, _e in moving}
            # tiered donors (docs/perf.md "Incremental history
            # maintenance") serve later rounds straight off their
            # un-merged device runs: seed the per-donor (nruns, merge
            # epoch) chain BEFORE the full shadow read so a batch
            # landing in between is re-fetched, never skipped
            run_marks: Dict[int, tuple] = {}
            for sid, _b, _e in moving:
                wm = handoff.run_watermarks(g.slots[sid].engine)
                if wm is not None and wm[1] is not None:
                    run_marks[sid] = wm
            entries = self._slice_all(moving, marks)
            entries = handoff.coalesce(entries, begin, end)
            for sid, _b, _e in moving:
                marks[sid] = handoff.last_shadow_version(
                    g.slots[sid].engine)
            op.precopied += await handoff.replay_slice(recipient.engine,
                                                       entries)
            for _round in range(PRECOPY_MAX_ROUNDS):
                delta = self._slice_all(moving, marks, run_marks)
                if len(delta) <= PRECOPY_DELTA_TARGET:
                    break
                for sid, _b, _e in moving:
                    marks[sid] = handoff.last_shadow_version(
                        g.slots[sid].engine)
                op.precopied += await handoff.replay_slice(
                    recipient.engine, sorted(delta))
            if spans_on:
                span_event("reshard.precopy", rid, ts0, span_now(),
                           Proc="reshard", batches=op.precopied)
            if blackbox.enabled():
                blackbox.record_reshard(op, "precopy")
            # FREEZE -> residual delta -> CUTOVER: the blackout
            op.state = "frozen"
            g.freeze([(b, e) for _sid, b, e in moving])
            op.t_freeze = self.now_fn()
            if blackbox.enabled():
                blackbox.record_reshard(op, "frozen")
            ts_freeze = span_now()
            await g.quiesce()
            delta = sorted(self._slice_all(moving, marks, run_marks))
            op.delta = await handoff.replay_slice(recipient.engine, delta)
            if spans_on:
                span_event("reshard.transfer", rid, ts_freeze, span_now(),
                           Proc="reshard", batches=op.delta)
            ts_cut = span_now()
            op.flip_version = g.last_version + 1
            new_map = KeyShardMap(new_splits)
            op.epoch = g.emap.flip(new_map, op.flip_version)
            g._assign[op.epoch] = new_sids_of(recipient.sid)
            g.unfreeze()
            op.t_cutover = self.now_fn()
            op.blackout_ms = (op.t_cutover - op.t_freeze) * 1e3
            if blackbox.enabled():
                # the epoch flip, with the new split keys: routing under
                # any version is reconstructible from the journal alone
                blackbox.record_reshard(
                    op, "flip", epoch=op.epoch,
                    flip_version=op.flip_version,
                    splits=[_fmt_key(k) for k in new_map.begins[1:]])
            if spans_on:
                span_event("reshard.cutover", op.flip_version, ts_cut,
                           span_now(), Proc="reshard", epoch=op.epoch)
                span_event("reshard.blackout", op.flip_version, ts_freeze,
                           span_now(), Proc="reshard", kind=kind,
                           begin=op.begin, end=op.end,
                           blackout_ms=round(op.blackout_ms, 3))
            # mid-flight adaptation: the donor's observed latency EWMAs
            # move with the range (no cold re-learn), donors cool for
            # recycling, admission rebalances via on_complete
            op.ewmas_migrated = sum(
                handoff.migrate_ewmas(g.slots[sid].batcher,
                                      recipient.batcher)
                for sid in op.donor_sids)
            for sid in retire:
                g.retire_slot(sid)
            op.state = "done"
            self.executed += 1
            self.blackout_ms_max = max(self.blackout_ms_max, op.blackout_ms)
            if op.blackout_ms > float(SERVER_KNOBS.reshard_blackout_budget_ms):
                self.blackout_over_budget += 1
            self.windows.append({"kind": "reshard", "t0": op.t_freeze,
                                 "t1": op.t_cutover})
            # the whole handoff arc (plan -> warm -> pre-copy -> cutover)
            # as a CORRELATION-ONLY window, the device-incident
            # failover->swap-back precedent: on CPU-emulated engines the
            # pre-copy/warm work shares the host with serving, so alerts
            # lit by that contention must correlate to the arc — but the
            # arc is NOT excluded from the p99 population (the service
            # keeps serving through it; only the blackout is planned
            # unavailability)
            self.windows.append({"kind": "reshard_arc", "t0": op.t_start,
                                 "t1": op.t_cutover})
            telemetry.hub().chaos_event("reshard_" + kind,
                                        begin=op.begin, end=op.end)
            self._last_done = self.now_fn()
            self.current = None
            g.reshard_in_flight = False
            if blackbox.enabled():
                blackbox.record_reshard(op, "done", epoch=op.epoch,
                                        flip_version=op.flip_version)
            if self.on_complete is not None:
                self.on_complete(op)
            return op
        except Exception as e:   # noqa: BLE001 — a stalled handoff must
            #                       surface as an alert, never crash serving
            op.state = "stalled"
            op.error = f"{type(e).__name__}: {e}"
            self.stalled += 1
            if blackbox.enabled():
                blackbox.record_reshard(op, "stalled")
            g.unfreeze()
            # the recipient never went live (op.epoch is only set at the
            # flip): cool it for recycling instead of leaking the warmed
            # engine — take_recipient clears any partially adopted
            # history on reuse
            if recipient is not None and op.epoch == 0:
                g.retire_slot(recipient.sid)
            if op.t_freeze > 0:
                # acks blocked at the freeze gate during the failed
                # handoff are planned-maintenance latency like a
                # completed blackout: record the interval so the
                # campaign excludes and correlates it
                self.windows.append({"kind": "reshard_aborted",
                                     "t0": op.t_freeze,
                                     "t1": self.now_fn()})
            return None

    def _slice_all(self, moving, marks,
                   run_marks=None) -> List[handoff.HistoryBatch]:
        """One pre-copy round's entries across the moving donors. With
        `run_marks` ({sid: (nruns vector, merge epoch)}), a tiered
        donor's round reads only the runs appended since its chain mark
        — O(delta) off the device image — falling back to the
        always-sufficient shadow when the donor can't serve the path or
        a compaction broke the chain (resync). Duplicate entries at or
        below a donor's version mark are filtered exactly like the
        shadow path filters them."""
        out: List[handoff.HistoryBatch] = []
        for sid, b, e in moving:
            eng = self.group.slots[sid].engine
            mv = marks.get(sid, 0)
            got = None
            if run_marks is not None and sid in run_marks:
                since, epoch = run_marks[sid]
                got = handoff.run_slice(eng, b, e, since_runs=since,
                                        since_epoch=epoch)
                if got is not None and got["resync"]:
                    got = None
            if got is None:
                if run_marks is not None and sid in run_marks:
                    # re-seed before the shadow read so the NEXT round
                    # can go incremental again
                    wm = handoff.run_watermarks(eng)
                    if wm is not None and wm[1] is not None:
                        run_marks[sid] = wm
                    else:
                        run_marks.pop(sid, None)
                out.extend(handoff.shadow_slice(eng, b, e, min_version=mv))
            else:
                run_marks[sid] = (got["watermarks"], got["epoch"])
                out.extend((v, w) for v, w in got["entries"] if v > mv)
        return out


def rebalance_admission(admission, heat: KeyRangeHeatAggregator,
                        sep: bytes = b"/", floor: float = 0.05) -> Dict[str, float]:
    """Recompute per-tenant admission weights from the post-reshard heat
    fractions: tenants whose key prefixes carry the measured load get the
    matching share of the published rate (server/ratekeeper.py
    TenantAdmission). Keys follow the workload convention
    `<tenant><sep><suffix>`; load is the write+conflict lane sum.

    Weights are normalized to MEAN 1.0, not sum 1.0: TenantAdmission
    gives tenants absent from the weight table a default weight of 1.0,
    so fractional weights would let any tenant the decayed/pruned heat
    no longer retains (a light uniform tenant can fall out of the
    bounded range map entirely) out-weigh every measured one. Tenants
    the admission layer has already seen but heat no longer measures
    keep the floor share instead of dropping to the default."""
    by_tenant: Dict[str, float] = {}
    total = 0.0
    for key, w in heat._w.items():
        name = key.split(sep, 1)[0].decode("latin-1")
        load = float(w[LANE_WRITES] + w[LANE_CONFLICTS])
        by_tenant[name] = by_tenant.get(name, 0.0) + load
        total += load
    if not by_tenant or total <= 0:
        return {}
    if admission is not None:
        for name in set(admission.admitted) | set(admission.rejected) \
                | set(admission.weights):
            by_tenant.setdefault(name, 0.0)
    fracs = {t: max(floor, load / total) for t, load in by_tenant.items()}
    mean = sum(fracs.values()) / len(fracs)
    weights = {t: f / mean for t, f in fracs.items()}
    if admission is not None:
        admission.weights = dict(weights)
    return weights
