"""Worker: hosts dynamically recruited roles on one process.

Re-design of fdbserver/worker.actor.cpp (workerServer:481): every cluster
process runs a worker that (1) finds the cluster controller through the
coordinators, (2) registers with it on a heartbeat (registrationClient:253)
and receives ServerDBInfo updates back, (3) constructs roles on Initialize*
requests, keyed by recovery generation so a worker can host the locked
previous tlog generation next to the current one, and (4) retires
generations the master declares dead after a durable cstate hand-over.

Roles die with the process (the sim kill cancels proc.actors and clears
handlers); a rebooted worker re-registers empty — in-memory roles are gone,
which is exactly the reference's behavior for stateless transaction roles.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core import buggify, error
from ..sim.actors import AsyncVar
from ..sim.loop import TaskPriority, delay, spawn
from ..sim.network import Endpoint, SimProcess
from .coordination import GET_LEADER_TOKEN, GetLeaderRequest, LeaderInfo
from .leader_election import monitor_leader
from .disk_queue import DiskQueue
from .proxy import Proxy, ProxyConfig
from .resolver import Resolver
from .storage import StorageServer
from .tlog import TLog
from .wait_failure import serve_wait_failure

INIT_TLOG_TOKEN = "worker.initTLog"
INIT_RESOLVER_TOKEN = "worker.initResolver"
INIT_PROXY_TOKEN = "worker.initProxy"
INIT_STORAGE_TOKEN = "worker.initStorage"
INIT_MASTER_TOKEN = "worker.initMaster"
RETIRE_TOKEN = "worker.retireGenerations"
RETIRE_STORAGE_TOKEN = "worker.retireStorage"

REGISTER_INTERVAL = 0.5


@dataclass
class ServerDBInfo:
    """reference: ServerDBInfo.h — the broadcast view of the transaction
    system every process tracks. info_version orders updates."""

    info_version: int = 0
    recovery_count: int = 0
    #: orders in-generation map updates (DD moves/splits/merges) from the
    #: same master; one-ways can reorder under clogging
    dd_version: int = 0
    recovery_state: str = "unconfigured"
    master_addr: Optional[str] = None
    proxy_addrs: tuple = ()
    log_config: Any = None                 # LogSystemConfig
    storage_tags: tuple = ()               # (tag, begin, end, address)
    master_status_ep: Any = None           # Endpoint of the master's status


@dataclass
class InitializeTLogRequest:
    gen_id: Tuple[int, int]
    start_version: int
    token_suffix: str
    replica_index: int = 0
    preload: Dict[int, list] = field(default_factory=dict)
    preload_popped: Dict[int, int] = field(default_factory=dict)


@dataclass
class InitializeResolverRequest:
    gen_id: Tuple[int, int]
    start_version: int
    token_suffix: str
    replica_index: int = 0


@dataclass
class InitializeProxyRequest:
    gen_id: Tuple[int, int]
    cfg: ProxyConfig
    start_version: int


@dataclass
class InitializeStorageRequest:
    tag: int
    begin: bytes
    end: bytes
    #: when set, the new replica first copies its shard from these
    #: addresses at fetch_version (MoveKeys' fetchKeys destination)
    fetch_from: Optional[List[str]] = None
    fetch_version: int = 0


@dataclass
class RetireStorageRequest:
    """Drop storage roles whose tag is in `tags` (MoveKeys finish), or —
    with prune=True — any storage role whose tag is NOT in `tags` (the
    master's post-recovery reconcile of orphaned move destinations)."""

    tags: tuple
    prune: bool = False


@dataclass
class InitializeMasterRequest:
    coordinator_addrs: List[str]
    worker_addrs: List[str]
    salt: int
    cc_addr: str
    cluster_cfg: Any                      # DynamicClusterConfig
    #: addr -> (machine_id, dc_id) from worker registrations
    worker_localities: Any = None


@dataclass
class RetireGenerationsRequest:
    """Drop roles of generations with recovery_count < keep_min — sent only
    after the successor generation's cstate write is durable."""

    keep_min: int


class Worker:
    def __init__(self, sim, proc: SimProcess, coordinator_addrs: List[str],
                 engine_factory, cc_priority: Optional[int] = None,
                 cluster_cfg: Any = None):
        self.sim = sim
        self.net = sim.net
        self.proc = proc
        self.coords = list(coordinator_addrs)
        self.engine_factory = engine_factory
        self.cluster_cfg = cluster_cfg
        self.db_info = AsyncVar(ServerDBInfo())
        self.log_view = AsyncVar(None)     # LogSystemConfig for storage
        self.leader = AsyncVar(None)
        #: (kind, recovery_count, salt) -> role object
        self.roles: Dict[Tuple[str, int, int], Any] = {}
        serve_wait_failure(proc)
        proc.register(INIT_TLOG_TOKEN, self.init_tlog)
        proc.register(INIT_RESOLVER_TOKEN, self.init_resolver)
        proc.register(INIT_PROXY_TOKEN, self.init_proxy)
        proc.register(INIT_STORAGE_TOKEN, self.init_storage)
        proc.register(INIT_MASTER_TOKEN, self.init_master)
        proc.register(RETIRE_TOKEN, self.retire_generations)
        proc.register(RETIRE_STORAGE_TOKEN, self.retire_storage)
        proc.actors.add(spawn(
            monitor_leader(self.net, proc.address, self.coords, self.leader),
            TaskPriority.COORDINATION, name=f"monLeader:{proc.name}",
        ))
        proc.actors.add(spawn(self.registration_loop(), TaskPriority.CLUSTER_CONTROLLER,
                              name=f"register:{proc.name}"))
        proc.actors.add(spawn(self.restore_roles(), TaskPriority.CLUSTER_CONTROLLER,
                              name=f"restore:{proc.name}"))
        if cc_priority is not None:
            proc.actors.add(spawn(self.cc_candidacy(cc_priority),
                                  TaskPriority.CLUSTER_CONTROLLER,
                                  name=f"ccCand:{proc.name}"))

    # -- cluster controller candidacy ----------------------------------------
    async def cc_candidacy(self, priority: int) -> None:
        """Every worker may stand for cluster controllership (fdbd():997
        composes candidacy into every process)."""
        from .cluster_controller import ClusterController
        from .leader_election import hold_leadership, try_become_leader

        info = LeaderInfo(self.proc.address,
                          id=self.sim.sched.rng.random_unique_id(),
                          priority=priority)
        while True:
            await try_become_leader(self.net, self.proc.address, self.coords, info)
            cc = ClusterController(self)
            try:
                await hold_leadership(self.net, self.proc.address, self.coords, info)
            finally:
                cc.shutdown()
            await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)

    # -- registration ---------------------------------------------------------
    async def registration_loop(self) -> None:
        """Heartbeat the CC; its replies carry ServerDBInfo
        (registrationClient:253 + the ServerDBInfo broadcast collapsed into
        one request/reply exchange)."""
        from .cluster_controller import CC_REGISTER_TOKEN, WorkerRegisterRequest

        # info_version is scoped to ONE cluster controller instance; a CC
        # failover restarts it at zero, so the known-version watermark must
        # reset when the leader changes or every post-failover broadcast
        # would compare stale-high and be dropped (storage would never learn
        # the new log generation).
        known_version = -1
        last_leader_id = None
        while True:
            leader = self.leader.get()
            if leader is None:
                await self.leader.on_change()
                continue
            if leader.id != last_leader_id:
                last_leader_id = leader.id
                known_version = -1
            try:
                info = await self.net.request(
                    self.proc.address,
                    Endpoint(leader.address, CC_REGISTER_TOKEN),
                    WorkerRegisterRequest(addr=self.proc.address,
                                          known_info_version=known_version,
                                          roles=tuple(sorted({k[0] for k in self.roles})),
                                          locality=(self.proc.machine_id,
                                                    self.proc.dc_id)),
                    TaskPriority.CLUSTER_CONTROLLER,
                    timeout=2.0,
                )
            except error.FDBError:
                await delay(REGISTER_INTERVAL, TaskPriority.CLUSTER_CONTROLLER)
                continue
            if info is not None and info.info_version > known_version:
                if buggify.buggify():
                    # broadcast applied late: roles run a beat behind the
                    # cluster view (stale log_config, stale proxy list)
                    await delay(0.5, TaskPriority.CLUSTER_CONTROLLER)
                known_version = info.info_version
                if info.recovery_count >= self.db_info.get().recovery_count:
                    self.db_info.set(info)
                    if (info.log_config is not None
                            and info.log_config != self.log_view.get()):
                        self.log_view.set(info.log_config)
            interval = REGISTER_INTERVAL
            if buggify.buggify():
                # sluggish registrant: CC liveness/recruitment must not
                # depend on prompt re-registration
                interval = REGISTER_INTERVAL * 6
            await delay(interval, TaskPriority.CLUSTER_CONTROLLER)

    # -- role construction -----------------------------------------------------
    async def init_tlog(self, req: InitializeTLogRequest) -> str:
        if buggify.buggify():
            # slow role construction: recovery must wait, and a competing
            # recovery generation may overtake this one mid-initialize
            await delay(0.3, TaskPriority.CLUSTER_CONTROLLER)
        key = ("tlog", req.gen_id[0], req.gen_id[1], req.replica_index)
        if key not in self.roles:
            disk = self.sim.disk_for(self.proc.address)
            store = f"tlog-{req.gen_id[0]}.{req.gen_id[1]}.{req.replica_index}"
            tlog = TLog(
                self.proc, start_version=req.start_version, gen_id=req.gen_id,
                preload=req.preload, preload_popped=req.preload_popped,
                token_suffix=req.token_suffix,
                queue=DiskQueue(disk, store), store_name=store,
            )
            await tlog.persist_initial(req.token_suffix)
            self.roles[key] = tlog
        return self.proc.address

    async def init_resolver(self, req: InitializeResolverRequest) -> str:
        key = ("resolver", req.gen_id[0], req.gen_id[1], req.replica_index)
        if key not in self.roles:
            pipe = None
            pipe_knobs = getattr(self.cluster_cfg, "resolver_pipeline", None)
            if pipe_knobs is not None:   # {} = pipeline with all defaults
                from ..pipeline.service import PipelineConfig

                pipe = PipelineConfig(**pipe_knobs)
            # device-fault supervisor (fault/resilient.py): watchdog +
            # retries + bit-identical CPU-oracle failover around whatever
            # engine the factory built
            from ..fault import maybe_wrap

            engine = maybe_wrap(self.engine_factory(), self.cluster_cfg)
            self.roles[key] = Resolver(
                self.proc, engine,
                start_version=req.start_version, token_suffix=req.token_suffix,
                index=req.replica_index, pipeline=pipe,
            )
        return self.proc.address

    async def init_proxy(self, req: InitializeProxyRequest) -> str:
        # One proxy per worker: the newcomer replaces any predecessor (its
        # generation is over by construction — recruitment happens after the
        # old generation is locked).
        for key in [k for k in self.roles if k[0] == "proxy"]:
            self.roles.pop(key).shutdown()
        key = ("proxy", req.gen_id[0], req.gen_id[1], 0)
        self.roles[key] = Proxy(self.proc, self.net, req.cfg,
                                start_version=req.start_version)
        return self.proc.address

    async def init_storage(self, req: InitializeStorageRequest) -> str:
        from ..core.types import KeyRange

        key = ("storage", 0, req.tag, 0)
        if key not in self.roles:
            fetch = req.fetch_from is not None
            ss = await StorageServer.create(
                self.proc, tag=req.tag, shard=KeyRange(req.begin, req.end),
                log_view=self.log_view, net=self.net,
                disk=self.sim.disk_for(self.proc.address),
                defer_update_loop=fetch,
            )
            if fetch:
                # MoveKeys destination: copy the shard BEFORE persisting the
                # role (a crash mid-fetch leaves no half-alive replica), then
                # let the update loop drain this tag's buffered mutations.
                if buggify.buggify():
                    # stalled fetch start: the donor team serves reads (and
                    # the tag stream buffers at the tlogs) meanwhile
                    await delay(0.5, TaskPriority.FETCH_KEYS)
                await ss.fetch_keys(req.fetch_from, req.fetch_version)
                await ss.persist_initial()
                ss.start_update_loop()
            else:
                await ss.persist_initial()
            self.roles[key] = ss
        return self.proc.address

    async def retire_storage(self, req: RetireStorageRequest) -> None:
        for key in list(self.roles):
            kind, _z, tag, _i = key
            if kind != "storage":
                continue
            drop = (tag not in req.tags) if req.prune else (tag in req.tags)
            if drop:
                self.roles.pop(key).retire()

    async def init_master(self, req: InitializeMasterRequest):
        from .masterserver import MasterServer

        ms = MasterServer(self, req)
        key = ("master", 0, req.salt, 0)
        self.roles[key] = ms
        wf_token = f"waitFailure:master:{req.salt}"
        serve_wait_failure(self.proc, wf_token)
        task = spawn(ms.run(), TaskPriority.CLUSTER_CONTROLLER, name=f"master:{req.salt}")
        self.proc.actors.add(task)

        def on_done(_f) -> None:
            # Master role over (recovery failed or a role died): watchers of
            # the role-scoped wait-failure endpoint see silence -> failure.
            self.proc.unregister(wf_token)
            self.roles.pop(key, None)

        task.on_ready(on_done)
        return Endpoint(self.proc.address, wf_token)

    async def restore_roles(self) -> None:
        """Re-create durable roles from disk after a reboot (the reference
        worker's DiskStore scan + restorePersistentState,
        worker.actor.cpp:208)."""
        disk = self.sim.disk_for(self.proc.address)
        for name in disk.list():
            if not name.endswith(".meta"):
                continue
            # Identity comes from the FILENAME, checked against live roles
            # BEFORE constructing: role constructors register tokens, so a
            # duplicate would silently steal a live role's handlers and
            # open a second writer on its files (round-2 review).
            base = name[: -len(".meta")]
            if name.startswith("tlog-"):
                try:
                    rc_s, salt_s, idx_s = base[len("tlog-"):].split(".")
                    key = ("tlog", int(rc_s), int(salt_s), int(idx_s))
                except ValueError:
                    continue
                if key in self.roles:
                    continue
                tlog = await TLog.restore(self.proc, disk, name)
                if tlog is not None:
                    self.roles[key] = tlog
            elif name.startswith("storage-"):
                try:
                    key = ("storage", 0, int(base[len("storage-"):]), 0)
                except ValueError:
                    continue
                if key in self.roles:
                    continue
                ss = await StorageServer.restore(
                    self.proc, disk, name, self.log_view, self.net
                )
                if ss is not None:
                    self.roles[key] = ss

    async def retire_generations(self, req: RetireGenerationsRequest) -> None:
        for key in list(self.roles):
            kind, rc, salt, idx = key
            if rc >= req.keep_min:
                continue
            if kind == "tlog":
                role = self.roles.pop(key)
                role.unregister()
                role.delete_files()
            elif kind == "resolver":
                self.roles.pop(key).unregister()
            elif kind == "proxy":
                # A deposed generation's proxy must stop serving GRV, or a
                # client with it cached reads pre-jump versions forever
                # (round-2 review finding).
                self.roles.pop(key).shutdown()
