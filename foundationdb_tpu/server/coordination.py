"""Coordinators: replicated generation registers + leader registers.

Re-design of fdbserver/Coordination.actor.cpp. Each coordinator process
hosts

  * a GenerationReg (localGenerationReg:125): a (read_gen, write_gen, value)
    register implementing the disk-paxos-style coordinated state. Reads
    advance read_gen; writes commit only if their generation is >= both
    generations seen so far. A majority of coordinators therefore
    linearizes DBCoreState updates: two would-be masters racing on the
    same generation cannot both win a majority.
  * a LeaderRegister (leaderRegister:203): candidates register themselves;
    the register nominates the best live candidate and forgets a leader
    whose heartbeats stop. Majority agreement on one nominee elects the
    cluster controller (LeaderElection.actor.cpp:78).

State lives in proc.globals so a REBOOT kill preserves it (the disk) while
KILL_INSTANTLY + REBOOT_AND_DELETE clears it — the durability seam until
the sim-disk round replaces globals with files.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core import buggify, error, wire
from ..sim.loop import Promise, TaskPriority, delay, now, spawn
from ..sim.network import Endpoint, SimProcess

GENERATION_READ_TOKEN = "coord.genRead"
GENERATION_WRITE_TOKEN = "coord.genWrite"
CANDIDACY_TOKEN = "coord.candidacy"
LEADER_HEARTBEAT_TOKEN = "coord.leaderHeartbeat"
GET_LEADER_TOKEN = "coord.getLeader"

#: a nominated leader is forgotten this long after its last heartbeat
#: (reference: POLLING_FREQUENCY/timeout in leaderRegister)
LEADER_TIMEOUT = 2.0
#: candidates re-submit at least this often; registrations expire after 2x
CANDIDACY_TTL = 2.0


@dataclass(frozen=True, order=True)
class Generation:
    """Lexicographic (txn, salt) generation id (reference: UniqueGeneration,
    CoordinatedState: higher txn wins; salt breaks ties uniquely)."""

    txn: int = 0
    salt: int = 0


ZERO_GEN = Generation(0, 0)


@dataclass(frozen=True)
class LeaderInfo:
    """A candidate for cluster controllership (reference: LeaderInfo,
    CoordinationInterface.h). Lower (priority, id) is better."""

    address: str
    id: int
    priority: int = 0

    def better_than(self, other: "LeaderInfo") -> bool:
        return (self.priority, self.id) < (other.priority, other.id)


# -- wire types ---------------------------------------------------------------


@dataclass
class GenerationReadRequest:
    key: str
    gen: Generation


@dataclass
class GenerationReadReply:
    value: Any
    value_gen: Generation      # generation at which value was written
    read_gen: Generation       # max generation this register has seen


@dataclass
class GenerationWriteRequest:
    key: str
    gen: Generation
    value: Any


@dataclass
class GenerationWriteReply:
    ok: bool
    max_gen: Generation        # on rejection: the competing generation seen


@dataclass
class CandidacyRequest:
    info: LeaderInfo
    prev_nominee_id: Optional[int] = None   # long-poll: reply when different


@dataclass
class LeaderHeartbeatRequest:
    info: LeaderInfo


@dataclass
class GetLeaderRequest:
    prev_nominee_id: Optional[int] = None   # long-poll: reply when different


class _GenerationReg:
    def __init__(self) -> None:
        self.read_gen: Generation = ZERO_GEN
        self.write_gen: Generation = ZERO_GEN
        self.value: Any = None

    def read(self, gen: Generation) -> GenerationReadReply:
        if gen > self.read_gen:
            self.read_gen = gen
        return GenerationReadReply(self.value, self.write_gen, self.read_gen)

    def write(self, gen: Generation, value: Any) -> GenerationWriteReply:
        if gen >= self.read_gen and gen >= self.write_gen:
            self.write_gen = gen
            self.value = value
            return GenerationWriteReply(True, gen)
        return GenerationWriteReply(False, max(self.read_gen, self.write_gen))


class _LeaderRegister:
    def __init__(self) -> None:
        #: candidate id -> (info, registration deadline)
        self.candidates: Dict[int, Tuple[LeaderInfo, float]] = {}
        self.nominee: Optional[LeaderInfo] = None
        self.lease_until: float = 0.0
        self._watchers: List[Tuple[Optional[int], Promise]] = []

    def _best_candidate(self, t: float) -> Optional[LeaderInfo]:
        live = [info for info, dl in self.candidates.values() if dl > t]
        if not live:
            return None
        best = live[0]
        for c in live[1:]:
            if c.better_than(best):
                best = c
        return best

    def refresh(self, t: float) -> None:
        """Drop an expired leader and (re)nominate the best live candidate.
        A strictly better candidate preempts the incumbent (the reference's
        leaderRegister re-nominates on every candidacy; the deposed leader
        notices via failing heartbeats and abdicates)."""
        if self.nominee is not None and self.lease_until <= t:
            self.nominee = None
        best = self._best_candidate(t)
        if best is not None and (self.nominee is None or best.better_than(self.nominee)):
            self.nominee = best
            self.lease_until = t + LEADER_TIMEOUT
        self._notify()

    def _notify(self) -> None:
        nid = self.nominee.id if self.nominee is not None else None
        still = []
        for prev, p in self._watchers:
            if prev != nid:
                p.send(self.nominee)
            else:
                still.append((prev, p))
        self._watchers = still

    def wait_nominee(self, prev_id: Optional[int]) -> Promise:
        p = Promise()
        nid = self.nominee.id if self.nominee is not None else None
        if nid != prev_id:
            p.send(self.nominee)
        else:
            self._watchers.append((prev_id, p))
        return p

    def drop_watch(self, p: Promise) -> None:
        """Forget a long-poll watcher whose request timed out, so abandoned
        polls don't accumulate across a long simulation."""
        self._watchers = [(prev, w) for (prev, w) in self._watchers if w is not p]


class CoordinationServer:
    """One coordinator process's servables (coordinationServer:413).

    With a disk, generation registers are durable: every promise (read_gen
    advance) and accept (write) is fsynced BEFORE the reply — the register
    must never forget a promise it answered, or a rebooted coordinator
    could accept a write its quorum already rejected (OnDemandStore,
    Coordination.actor.cpp:86). Without a disk, registers live in
    proc.globals (kept for protocol-level tests)."""

    def __init__(self, proc: SimProcess, disk=None, regs=None):
        self.proc = proc
        self.disk = disk
        if regs is not None:
            self.regs = regs
        elif disk is None:
            self.regs: Dict[str, _GenerationReg] = proc.globals.setdefault("coord.regs", {})
        else:
            self.regs = {}
        from ..sim.actors import AsyncMutex

        #: serializes register persists: interleaved write/sync cycles could
        #: make an OLDER snapshot durable after a newer acked one
        self._persist_mutex = AsyncMutex()
        self.leader = _LeaderRegister()   # leadership is NOT durable state
        proc.register(GENERATION_READ_TOKEN, self._gen_read)
        proc.register(GENERATION_WRITE_TOKEN, self._gen_write)
        proc.register(CANDIDACY_TOKEN, self._candidacy)
        proc.register(LEADER_HEARTBEAT_TOKEN, self._heartbeat)
        proc.register(GET_LEADER_TOKEN, self._get_leader)
        proc.actors.add(spawn(self._sweeper(), TaskPriority.COORDINATION, name="coordSweep"))

    @classmethod
    async def create(cls, proc: SimProcess, disk) -> "CoordinationServer":
        """Boot-time constructor restoring durable registers from disk.
        State is read BEFORE any handler registers: a request served in the
        restore window must never see an empty register (the promise would
        be forgotten)."""
        regs: Dict[str, _GenerationReg] = {}
        f = disk.open("coord.regs")
        raw = await f.read(0, f.size())
        if raw:
            try:
                for key, (rg, wg, value) in wire.loads(raw).items():
                    reg = _GenerationReg()
                    reg.read_gen, reg.write_gen, reg.value = rg, wg, value
                    regs[key] = reg
            except Exception:
                pass  # torn register file: recovered as empty (first boot)
        return cls(proc, disk=disk, regs=regs)

    def _reg(self, key: str) -> _GenerationReg:
        r = self.regs.get(key)
        if r is None:
            r = self.regs[key] = _GenerationReg()
        return r

    async def _persist_regs(self) -> None:
        """Durable register snapshot, crash-safe and serialized: the
        snapshot is taken under the persist lock (no older in-flight
        snapshot can land after a newer acked one) and written to a fresh
        file + rename (an in-place rewrite torn mid-crash would erase
        previously synced promises)."""
        if self.disk is None:
            return
        if buggify.buggify():
            # stretch the window between answering and persisting races
            await delay(0.05, TaskPriority.COORDINATION)
        async with self._persist_mutex:
            payload = wire.dumps({
                k: (r.read_gen, r.write_gen, r.value) for k, r in self.regs.items()
            })
            tmp = self.disk.open("coord.regs.tmp")
            await tmp.truncate(0)
            await tmp.write(0, payload)
            await tmp.sync()
            self.disk.rename("coord.regs.tmp", "coord.regs")

    async def _gen_read(self, req: GenerationReadRequest) -> GenerationReadReply:
        reg = self._reg(req.key)
        before = reg.read_gen
        reply = reg.read(req.gen)
        if reg.read_gen != before:
            # The promise must be durable before it is given.
            await self._persist_regs()
        return reply

    async def _gen_write(self, req: GenerationWriteRequest) -> GenerationWriteReply:
        if buggify.buggify():
            # reorder writes against competing masters' broadcasts: the
            # exclusive-write generation check must still pick one winner
            await delay(0.05, TaskPriority.COORDINATION)
        reply = self._reg(req.key).write(req.gen, req.value)
        if reply.ok:
            await self._persist_regs()
        return reply

    async def _candidacy(self, req: CandidacyRequest) -> Optional[LeaderInfo]:
        t = now()
        self.leader.candidates[req.info.id] = (req.info, t + 2 * CANDIDACY_TTL)
        self.leader.refresh(t)
        # Long-poll: reply with the nominee once it differs from what the
        # candidate last saw (bounded so re-registration keeps flowing).
        p = self.leader.wait_nominee(req.prev_nominee_id)
        await _first(p.future, delay(CANDIDACY_TTL, TaskPriority.COORDINATION))
        self.leader.drop_watch(p)
        return self.leader.nominee

    async def _heartbeat(self, req: LeaderHeartbeatRequest) -> bool:
        t = now()
        self.leader.candidates[req.info.id] = (req.info, t + 2 * CANDIDACY_TTL)
        if self.leader.nominee is not None and self.leader.nominee.id == req.info.id:
            self.leader.lease_until = t + LEADER_TIMEOUT
            return True
        self.leader.refresh(t)
        return self.leader.nominee is not None and self.leader.nominee.id == req.info.id

    async def _get_leader(self, req: GetLeaderRequest) -> Optional[LeaderInfo]:
        p = self.leader.wait_nominee(req.prev_nominee_id)
        await _first(p.future, delay(LEADER_TIMEOUT, TaskPriority.COORDINATION))
        self.leader.drop_watch(p)
        return self.leader.nominee

    async def _sweeper(self) -> None:
        """Expire silent leaders even with no request traffic."""
        while True:
            tick = LEADER_TIMEOUT / 2
            if buggify.buggify():
                # eager sweeper: leases expire at the earliest legal moment,
                # so heartbeat renewal races the sweep
                tick = LEADER_TIMEOUT / 16
            await delay(tick, TaskPriority.COORDINATION)
            self.leader.refresh(now())


async def _first(a, b):
    """Wait until either future resolves (errors propagate)."""
    from ..sim.actors import any_of

    await any_of([a, b])


wire.register_record(Generation)
