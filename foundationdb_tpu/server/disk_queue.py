"""DiskQueue: a checksummed durable append log with torn-tail recovery.

Re-design of fdbserver/DiskQueue.actor.cpp: the write-ahead structure under
the tlog and the memory storage engine. One file holds a dual-slot header
page followed by framed entries [length u32][crc32 u32][payload]. A crash
can tear any un-synced write (sim/disk.py crash semantics), so:

  * recovery scans frames from the front and stops at the first bad one —
    everything before was covered by an fsync ack, everything after was
    never acknowledged to anyone;
  * the pop cursor is written to ALTERNATING header slots with a sequence
    number, so a torn header write loses at most the newest pop (re-serving
    acknowledged entries is safe; losing the whole queue is not);
  * compaction builds a fresh file and renames it over the old one — a
    crash on either side of the rename leaves one complete file.

Offsets handed to callers are LOGICAL and monotone for the queue's
lifetime; compaction preserves them (the reference achieves the same with
its paired-file location scheme).
"""
from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

from ..core import buggify
from ..sim.actors import AsyncMutex
from ..sim.disk import SimDisk, SimFile

#: frame = [length u32][crc u32][payload]; the crc covers the frame's
#: logical START position + length + payload, so zero-filled gaps (a lost
#: write followed by an applied one pads with zeros) can never parse as a
#: valid empty frame (crc32(b"") == 0), and a frame replayed at the wrong
#: position is rejected — the reference gets the same from its page
#: sequence numbers.
_FRAME = struct.Struct("<II")


def _frame_crc(position: int, payload: bytes) -> int:
    return zlib.crc32(payload, zlib.crc32(struct.pack("<QI", position, len(payload))))
_SLOT = struct.Struct("<QQQI")     # seq, begin logical, base logical, crc32
SLOT_SIZE = 32                     # _SLOT.size (28) padded
HEADER_SIZE = 2 * SLOT_SIZE


class DiskQueue:
    def __init__(self, disk: SimDisk, name: str):
        self.disk = disk
        self.name = name
        #: serializes push/commit/pop/compact: a frame pushed while a
        #: compaction rewrites the file would land in the orphaned old file
        #: and be lost after the rename despite an fsync ack (round-2 review)
        self._mutex = AsyncMutex()
        self.data: SimFile = disk.open(f"{name}.dq")
        self._seq = 0            # header write sequence
        self._base = 0           # logical offset of physical HEADER_SIZE
        self._begin = 0          # logical front (popped boundary)
        self._end = 0            # logical append position

    # -- header slots ----------------------------------------------------------
    def _pack_slot(self) -> bytes:
        body = struct.pack("<QQQ", self._seq, self._begin, self._base)
        return body + struct.pack("<I", zlib.crc32(body)) + b"\x00" * (SLOT_SIZE - _SLOT.size)

    @staticmethod
    def _parse_slot(raw: bytes):
        if len(raw) < _SLOT.size:
            return None
        seq, begin, base, crc = _SLOT.unpack(raw[:_SLOT.size])
        if crc != zlib.crc32(raw[:24]):
            return None
        return seq, begin, base

    async def _write_header(self) -> None:
        self._seq += 1
        slot = self._seq % 2
        await self.data.write(slot * SLOT_SIZE, self._pack_slot())
        await self.data.sync()

    # -- recovery --------------------------------------------------------------
    async def recover(self) -> List[Tuple[int, bytes]]:
        """Scan surviving frames; returns [(logical_end_offset, payload)] in
        append order for entries past the popped front. A torn or partial
        frame ends the scan (nothing past it was ever acked)."""
        raw = await self.data.read(0, self.data.size())
        best = None
        for slot in (0, 1):
            parsed = self._parse_slot(bytes(raw[slot * SLOT_SIZE:(slot + 1) * SLOT_SIZE]))
            if parsed is not None and (best is None or parsed[0] > best[0]):
                best = parsed
        if best is not None:
            self._seq, self._begin, self._base = best
        else:
            self._seq = self._begin = self._base = 0
            if len(raw) < HEADER_SIZE:
                # Fresh queue: lay down both header slots.
                await self.data.truncate(0)
                await self.data.write(0, self._pack_slot() + self._pack_slot())
                await self.data.sync()
                self._end = 0
                return []
        out: List[Tuple[int, bytes]] = []
        off = HEADER_SIZE
        while off + _FRAME.size <= len(raw):
            length, crc = _FRAME.unpack(raw[off:off + _FRAME.size])
            payload = raw[off + _FRAME.size: off + _FRAME.size + length]
            logical_start = self._base + (off - HEADER_SIZE)
            if len(payload) < length or _frame_crc(logical_start, bytes(payload)) != crc:
                break  # torn tail
            off += _FRAME.size + length
            logical_end = self._base + (off - HEADER_SIZE)
            if logical_end > self._begin:
                out.append((logical_end, bytes(payload)))
        self._end = self._base + (off - HEADER_SIZE)
        return out

    # -- append ----------------------------------------------------------------
    async def push(self, payload: bytes) -> int:
        """Buffered append; returns the entry's logical end offset (pass to
        pop_to once consumed downstream). Durable only after commit()."""
        async with self._mutex:
            frame = _FRAME.pack(len(payload), _frame_crc(self._end, payload)) + payload
            off = HEADER_SIZE + (self._end - self._base)
            if buggify.buggify():
                # write split across two page-cache entries: a crash can
                # tear between them — recovery's frame crc must catch it
                mid = len(frame) // 2
                await self.data.write(off, frame[:mid])
                await self.data.write(off + mid, frame[mid:])
            else:
                await self.data.write(off, frame)
            self._end += len(frame)
            return self._end

    async def commit(self) -> None:
        """fsync the appended frames (the ack boundary)."""
        if buggify.buggify():
            # slow fsync: stretches the pre-ack window other failures race
            from ..sim.loop import TaskPriority, delay
            await delay(0.02, TaskPriority.DEFAULT_DELAY)
        async with self._mutex:
            await self.data.sync()

    # -- pop / compaction ------------------------------------------------------
    async def pop_to(self, logical_offset: int) -> None:
        if logical_offset <= self._begin:
            return
        async with self._mutex:
            self._begin = min(max(logical_offset, self._begin), self._end)
            await self._write_header()
            compact_at = (1 << 10) if buggify.buggify() else (1 << 16)
            if (self._begin - self._base) > compact_at and \
                    (self._begin - self._base) * 2 > (self._end - self._base):
                await self._compact()

    async def _compact(self) -> None:
        live = await self.data.read(
            HEADER_SIZE + (self._begin - self._base), self._end - self._begin
        )
        self._base = self._begin
        tmp_name = f"{self.name}.dq.tmp"
        tmp = self.disk.open(tmp_name)
        await tmp.truncate(0)
        await tmp.write(0, self._pack_slot() + self._pack_slot() + bytes(live))
        await tmp.sync()
        self.disk.rename(tmp_name, f"{self.name}.dq")
        self.data = self.disk.open(f"{self.name}.dq")

    @property
    def end_offset(self) -> int:
        return self._end
