"""Simulation test harness.

The analog of the reference's test stack (SURVEY.md §4): TestWorkload
classes (fdbserver/workloads/workloads.h:42-85) composed by declarative
specs, run against a simulated cluster with anti-quiescence fault injectors,
then checked after quiescence. Any failure replays exactly from its seed.
"""
from .workload import TestWorkload, WorkloadContext, run_spec, Spec

__all__ = ["TestWorkload", "WorkloadContext", "run_spec", "Spec"]
