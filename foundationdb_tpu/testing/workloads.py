"""The core workload set (reference: fdbserver/workloads/, 84 files).

Round-1 inventory, mirroring the reference's invariant checkers most
relevant to the resolver north star (SURVEY.md §4.2):

  CycleWorkload           Cycle.actor.cpp — ring permutation invariant
  IncrementWorkload       Increment.actor.cpp — read-modify-write counters
  AtomicOpsWorkload       AtomicOps.actor.cpp — commutative ops, exact totals
  WriteDuringReadWorkload WriteDuringRead.actor.cpp — randomized op streams
                          vs an in-transaction RYW model
  ConflictRangeWorkload   ConflictRange.actor.cpp — randomized range reads
                          vs a version-replayed model under deliberate
                          conflicting writers (external consistency check)
  RandomReadWriteWorkload ReadWrite.actor.cpp — the 90/10 metric workload
  RandomCloggingWorkload  RandomClogging.actor.cpp — anti-quiescence network
                          fault injector
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..core import error
from ..core.types import MutationType
from ..client.database import Database
from ..sim.loop import delay
from .workload import TestWorkload

# ---------------------------------------------------------------------------


class CycleWorkload(TestWorkload):
    """A ring permutation over `nodes` keys; each transaction rotates three
    links; the ring must stay a single cycle (Cycle.actor.cpp cycleCheck)."""

    name = "Cycle"

    @property
    def n(self) -> int:
        return int(self.ctx.options.get("nodes", 12))

    def key(self, i: int) -> bytes:
        return b"cycle/%04d" % i

    async def setup(self, db: Database) -> None:
        tr = db.create_transaction()
        for i in range(self.n):
            tr.set(self.key(i), b"%04d" % ((i + 1) % self.n))
        await tr.commit()

    async def start(self, db: Database) -> None:
        count = int(self.ctx.options.get("transactions", 20))
        # Pace transactions so the run overlaps with injected faults
        # (reference: transactionsPerSecond paces Cycle.actor.cpp; without
        # pacing the workload finishes before attrition ever fires).
        think = float(self.ctx.options.get("think_time", 0.0))
        for _ in range(count):
            async def body(tr):
                r = self.ctx.rng.random_int(0, self.n)
                p1 = int(await tr.get(self.key(r)))
                p2 = int(await tr.get(self.key(p1)))
                p3 = int(await tr.get(self.key(p2)))
                tr.set(self.key(r), b"%04d" % p2)
                tr.set(self.key(p1), b"%04d" % p3)
                tr.set(self.key(p2), b"%04d" % p1)

            await db.run(body)
            self.ctx.count("cycle_txns")
            if think > 0:
                await delay(think * self.ctx.rng.random01() * 2)

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"cycle/", b"cycle0")

        got = await db.run(read_all)
        if len(got) != self.n:
            return False
        nxt = {int(k[-4:]): int(v) for k, v in got}
        seen, at = set(), 0
        for _ in range(self.n):
            if at in seen:
                return False
            seen.add(at)
            at = nxt[at]
        return at == 0


class IncrementWorkload(TestWorkload):
    """Read-modify-write counters under contention (Increment.actor.cpp):
    the final sum must equal the number of committed increments."""

    name = "Increment"

    async def start(self, db: Database) -> None:
        count = int(self.ctx.options.get("transactions", 15))
        keys = int(self.ctx.options.get("keys", 4))
        done = 0
        for _ in range(count):
            async def body(tr):
                k = b"incr/%02d" % self.ctx.rng.random_int(0, keys)
                cur = await tr.get(k)
                n = int.from_bytes(cur or b"\0\0\0\0", "big")
                tr.set(k, (n + 1).to_bytes(4, "big"))

            await db.run(body)
            done += 1
        self.ctx.count("increments", done)

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"incr/", b"incr0")

        got = await db.run(read_all)
        total = sum(int.from_bytes(v, "big") for _, v in got)
        return total == int(self.ctx.shared.get("increments", 0))


class AtomicOpsWorkload(TestWorkload):
    """Blind atomic ADDs never conflict; totals must be exact
    (AtomicOps.actor.cpp)."""

    name = "AtomicOps"

    async def start(self, db: Database) -> None:
        count = int(self.ctx.options.get("transactions", 20))
        keys = int(self.ctx.options.get("keys", 3))
        added = 0
        for _ in range(count):
            tr = db.create_transaction()
            k = b"atomic/%02d" % self.ctx.rng.random_int(0, keys)
            amount = self.ctx.rng.random_int(1, 10)
            tr.atomic_op(k, amount.to_bytes(8, "little"), MutationType.ADD_VALUE)
            await tr.commit()
            added += amount
        self.ctx.count("atomic_added", added)

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"atomic/", b"atomic0")

        got = await db.run(read_all)
        total = sum(int.from_bytes(v, "little") for _, v in got)
        return total == int(self.ctx.shared.get("atomic_added", 0))


# ---------------------------------------------------------------------------


class MemoryKeyValueStore:
    """In-memory model store (reference:
    fdbserver/workloads/MemoryKeyValueStore.cpp)."""

    def __init__(self) -> None:
        self._d: Dict[bytes, bytes] = {}

    def set(self, k: bytes, v: bytes) -> None:
        self._d[k] = v

    def clear_range(self, b: bytes, e: bytes) -> None:
        for k in [k for k in self._d if b <= k < e]:
            del self._d[k]

    def get(self, k: bytes) -> Optional[bytes]:
        return self._d.get(k)

    def get_range(self, b: bytes, e: bytes) -> List[Tuple[bytes, bytes]]:
        return sorted((k, v) for k, v in self._d.items() if b <= k < e)

    def apply_mutation(self, m) -> None:
        from ..core.types import SINGLE_KEY_MUTATIONS, apply_atomic_op

        if m.type == MutationType.SET_VALUE:
            self.set(m.param1, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            self.clear_range(m.param1, m.param2)
        elif m.type in SINGLE_KEY_MUTATIONS:
            self.set(m.param1, apply_atomic_op(m.type, self.get(m.param1), m.param2))


class WriteDuringReadWorkload(TestWorkload):
    """Randomized op streams inside one transaction: every read must see the
    RYW overlay exactly as an in-memory model predicts, and the committed
    state must match the model (WriteDuringRead.actor.cpp strategy)."""

    name = "WriteDuringRead"

    @property
    def _prefix(self) -> bytes:
        return b"wdr%d/" % self.ctx.client_id

    def _rand_key(self) -> bytes:
        return self._prefix + b"%02d" % self.ctx.rng.random_int(0, 12)

    async def start(self, db: Database) -> None:
        rng = self.ctx.rng
        committed = MemoryKeyValueStore()
        rounds = int(self.ctx.options.get("rounds", 15))
        pre = self._prefix
        for _ in range(rounds):
            tr = db.create_transaction()
            model = MemoryKeyValueStore()
            for k, v in committed.get_range(pre, pre + b"\xff"):
                model.set(k, v)
            ops = rng.random_int(3, 12)
            for _ in range(ops):
                o = rng.random01()
                k = self._rand_key()
                if o < 0.25:
                    v = b"v%d" % rng.random_int(0, 1000)
                    tr.set(k, v)
                    model.set(k, v)
                elif o < 0.4:
                    k2 = self._rand_key()
                    b, e = min(k, k2), max(k, k2) + b"\x00"
                    tr.clear_range(b, e)
                    model.clear_range(b, e)
                elif o < 0.55:
                    amt = rng.random_int(1, 100).to_bytes(8, "little")
                    tr.atomic_op(k, amt, MutationType.ADD_VALUE)
                    model.set(k, _le_add(model.get(k), amt))
                elif o < 0.8:
                    got = await tr.get(k)
                    assert got == model.get(k), f"RYW get mismatch at {k}: {got} != {model.get(k)}"
                else:
                    k2 = self._rand_key()
                    b, e = min(k, k2), max(k, k2) + b"\x00"
                    got = await tr.get_range(b, e)
                    want = model.get_range(b, e)
                    assert got == want, f"RYW range mismatch: {got} != {want}"
            await tr.commit()
            committed = model
            self.ctx.count("wdr_rounds")
        self._final = committed

    async def check(self, db: Database) -> bool:
        pre = self._prefix

        async def read_all(tr):
            return await tr.get_range(pre, pre + b"\xff")

        got = await db.run(read_all)
        return got == self._final.get_range(pre, pre + b"\xff")


def _le_add(old: Optional[bytes], param: bytes) -> bytes:
    from ..core.types import apply_atomic_op

    return apply_atomic_op(MutationType.ADD_VALUE, old, param)


class ConflictRangeWorkload(TestWorkload):
    """External-consistency check under deliberate conflicts
    (ConflictRange.actor.cpp re-thought for the version-replay model):

    Writer clients commit random sets/clears recording (commit_version,
    mutations); reader clients record (read_version, range, result). At
    check time, committed writes are replayed in version order; every
    read's result must equal the model at its read version."""

    name = "ConflictRange"
    PREFIX = b"cr/"

    def _rand_key(self) -> bytes:
        return self.PREFIX + b"%02d" % self.ctx.rng.random_int(0, 16)

    async def start(self, db: Database) -> None:
        rng = self.ctx.rng
        self.writes: List[Tuple[int, List]] = []
        self.reads: List[Tuple[int, bytes, bytes, List]] = []
        rounds = int(self.ctx.options.get("rounds", 20))
        for _ in range(rounds):
            if self.ctx.client_id % 2 == 0:
                # writer: random small txn of sets/clears
                tr = db.create_transaction()
                for _ in range(rng.random_int(1, 4)):
                    if rng.random01() < 0.75:
                        tr.set(self._rand_key(), b"w%d" % rng.random_int(0, 10_000))
                    else:
                        a, b = self._rand_key(), self._rand_key()
                        tr.clear_range(min(a, b), max(a, b) + b"\x00")
                muts = list(tr.mutations)
                try:
                    v = await tr.commit()
                    self.writes.append((v, tr.committed_batch_index, muts))
                except error.FDBError as e:
                    if not e.is_retryable():
                        raise
            else:
                # reader: snapshot of a random subrange at its read version
                tr = db.create_transaction()
                a, b = self._rand_key(), self._rand_key()
                lo, hi = min(a, b), max(a, b) + b"\x00"
                got = await tr.get_range(lo, hi, snapshot=True)
                rv = await tr.get_read_version()
                self.reads.append((rv, lo, hi, got))
            await delay(0.001 * rng.random01())
        # Shared registry so client 0's check sees every client's log.
        self.ctx.shared.setdefault("writes", []).extend(self.writes)
        self.ctx.shared.setdefault("reads", []).extend(self.reads)

    async def check(self, db: Database) -> bool:
        writes = self.ctx.shared.get("writes", [])
        reads = self.ctx.shared.get("reads", [])
        model = MemoryKeyValueStore()
        # Commits sharing a version apply in txn_batch_index order; reads at
        # version rv see every commit with version <= rv (kind 1 sorts last).
        events: List[Tuple[int, int, int, object]] = []
        for v, bi, muts in writes:
            events.append((v, 0, bi, muts))
        for rv, lo, hi, got in reads:
            events.append((rv, 1, 0, (lo, hi, got)))
        events.sort(key=lambda e: (e[0], e[1], e[2]))
        for v, kind, _bi, payload in events:
            if kind == 0:
                for m in payload:
                    model.apply_mutation(m)
            else:
                lo, hi, got = payload
                want = model.get_range(lo, hi)
                if got != want:
                    return False
        # Final DB state must match the fully-replayed model.
        tr = db.create_transaction()
        got = await tr.get_range(self.PREFIX, self.PREFIX + b"\xff")
        return got == model.get_range(self.PREFIX, self.PREFIX + b"\xff")


class RandomReadWriteWorkload(TestWorkload):
    """The 90/10 metric workload (ReadWrite.actor.cpp, tests/RandomReadWrite.txt)."""

    name = "RandomReadWrite"

    async def start(self, db: Database) -> None:
        rng = self.ctx.rng
        txns = int(self.ctx.options.get("transactions", 25))
        keys = int(self.ctx.options.get("keys", 64))
        read_frac = float(self.ctx.options.get("read_fraction", 0.9))
        ops_per_txn = int(self.ctx.options.get("ops_per_txn", 10))
        committed = conflicts = 0
        for _ in range(txns):
            tr = db.create_transaction()
            try:
                for _ in range(ops_per_txn):
                    # Zipf-ish: square the uniform draw to bias toward low keys
                    k = b"rw/%04d" % int(rng.random01() ** 2 * keys)
                    if rng.random01() < read_frac:
                        await tr.get(k)
                    else:
                        tr.set(k, b"x" * 16)
                await tr.commit()
                committed += 1
            except error.FDBError as e:
                if e.code == error.not_committed("").code:
                    conflicts += 1
                elif not e.is_retryable():
                    raise
        self.ctx.count("rw_committed", committed)
        self.ctx.count("rw_conflicts", conflicts)


class RandomCloggingWorkload(TestWorkload):
    """Anti-quiescence: randomly clog processes' links while others run
    (RandomClogging.actor.cpp via g_simulator.clogInterface)."""

    name = "RandomClogging"
    anti_quiescence = True

    async def start(self, db: Database) -> None:
        sim = self.ctx.cluster.sim
        rng = self.ctx.rng
        scale = float(self.ctx.options.get("scale", 0.05))
        while True:
            await delay(rng.random01() * 10 * scale)
            procs = list(sim.net.processes.values())
            victim = procs[rng.random_int(0, len(procs))]
            sim.clog_process(victim, rng.random01() * scale)


class MachineAttritionWorkload(TestWorkload):
    """Anti-quiescence: kill (and reboot) workers hosting transaction roles
    while the other workloads run — the reference's core correctness
    strategy (MachineAttrition.actor.cpp). Requires a DynamicCluster, whose
    recovery machinery the kills exercise; storage-hosting workers are
    spared until the durability round makes storage restartable."""

    name = "MachineAttrition"
    anti_quiescence = True

    TXN_TOKENS = ("tlog.commit", "resolver.resolve", "proxy.commit",
                  "master.getCommitVersion")

    def _safe_victims(self, cluster):
        """Kill-safety analysis (reference: ISimulator::canKillProcesses,
        simulator.h:155). With durable tlogs/storage (DiskQueue + snapshot
        WAL) and REBOOT-only kills, every role host recovers from its own
        disk, so any worker hosting a role is a safe victim — including all
        tlog replicas at once (recovery waits for one to reboot and
        restore). Storage kills are gated by the spare_storage option for
        specs that want to isolate transaction-subsystem churn."""
        spare_storage = bool(self.ctx.options.get("spare_storage", False))
        out = []
        for p in cluster.worker_procs:
            if not p.alive:
                continue
            hosts_storage = any(t.startswith("storage.") for t in p.handlers)
            if spare_storage and hosts_storage:
                continue
            if hosts_storage or any(t.startswith(self.TXN_TOKENS) for t in p.handlers):
                out.append(p)
        return out

    async def start(self, db: Database) -> None:
        from ..sim.simulator import KillType

        # One killer only (reference MachineAttrition gates on clientId 0):
        # concurrent independent killers defeat the safety analysis.
        if self.ctx.client_id != 0:
            return
        cluster = self.ctx.cluster
        sim = cluster.sim
        rng = self.ctx.rng
        interval = float(self.ctx.options.get("interval", 8.0))
        await delay(float(self.ctx.options.get("delay_before", 4.0)))
        while True:
            victims = self._safe_victims(cluster)
            if victims:
                victim = victims[rng.random_int(0, len(victims))]
                self.ctx.count("kills")
                sim.kill_process(victim, KillType.REBOOT)
            await delay(interval)


class WatchesWorkload(TestWorkload):
    """Watch/trigger ping-pong (Watches.actor.cpp): client pairs bounce a
    counter; every bounce is driven by a watch firing with the new value."""

    name = "Watches"

    async def start(self, db: Database) -> None:
        rounds = int(self.ctx.options.get("rounds", 6))
        me = self.ctx.client_id
        peer = (me + 1) % self.ctx.client_count
        key_me = b"watch/%02d" % me
        key_peer = b"watch/%02d" % peer

        async def write_my(tr, n):
            tr.set(key_me, b"%06d" % n)

        async def read_peer(tr):
            return await tr.get(key_peer, snapshot=True), tr.read_version

        async def wait_peer_at_least(n):
            """Race-free wait: watch registered against the value THIS read
            observed (the reference registers watches inside the reading
            transaction for the same atomicity)."""
            while True:
                cur, rv = await db.run(read_peer)
                if cur is not None and int(cur) >= n:
                    return cur
                await db.create_transaction().watch(
                    key_peer, expected=cur, expected_version=rv)

        # client 0 serves: write mine, wait for peer's echo via watch
        for n in range(rounds):
            if me == 0:
                await db.run(write_my, n)
                got = await wait_peer_at_least(n)
                if int(got) == n:
                    self.ctx.count("watch_bounces")
            else:
                if n > 0:
                    await wait_peer_at_least(n)
                await db.run(write_my, n)

    async def check(self, db: Database) -> bool:
        # liveness is the check: every round required a watch to fire
        return self.ctx.shared.get("watch_bounces", 0) >= 1


class SelectorCorrectnessWorkload(TestWorkload):
    """Key-selector resolution vs a host model (SelectorCorrectness
    .actor.cpp): random selectors over a known key set must resolve to the
    model's answer."""

    name = "SelectorCorrectness"

    async def setup(self, db: Database) -> None:
        async def w(tr):
            for i in range(20):
                tr.set(b"sel/%03d" % (i * 5), b"v")
        await db.run(w)

    async def start(self, db: Database) -> None:
        from ..client.database import KeySelector

        rng = self.ctx.rng
        keys = [b"sel/%03d" % (i * 5) for i in range(20)]
        checks = int(self.ctx.options.get("checks", 30))

        def model(anchor, or_equal, offset):
            """Resolution index within this workload's key set; None when it
            would walk outside sel/ (other workloads' keys live there, so
            the database's answer is out of this model's scope)."""
            i0 = (bisect.bisect_right(keys, anchor) if or_equal
                  else bisect.bisect_left(keys, anchor))
            i = i0 + offset - 1
            if 0 <= i < len(keys):
                return keys[i]
            return None

        for _ in range(checks):
            anchor = b"sel/%03d" % rng.random_int(0, 100)
            or_equal = rng.coinflip()
            offset = rng.random_int(-3, 4)
            want = model(anchor, or_equal, offset)
            if want is None:
                continue
            sel = KeySelector(anchor, or_equal, offset)

            async def resolve(tr):
                return await tr.get_key(sel)

            got = await db.run(resolve)
            if got != want:
                self.ctx.count("selector_mismatches")
            self.ctx.count("selector_checks")

    async def check(self, db: Database) -> bool:
        return (self.ctx.shared.get("selector_mismatches", 0) == 0
                and self.ctx.shared.get("selector_checks", 0) > 0)


class VersionStampWorkload(TestWorkload):
    """Versionstamped keys/values (VersionStamp.actor.cpp): every committed
    stamp must equal the commit's (version, batch index), stamps must be
    unique and monotone in commit order, and stamped keys must land in the
    keyspace exactly where the stamp says."""

    name = "VersionStamp"

    async def start(self, db: Database) -> None:
        import struct

        rounds = int(self.ctx.options.get("rounds", 8))
        me = self.ctx.client_id
        committed: List[Tuple[int, bytes]] = []
        for n in range(rounds):
            tr = db.create_transaction()
            prefix = b"vsw/%02d/" % me
            raw_key = prefix + b"\x00" * 10 + struct.pack("<i", len(prefix))
            tr.atomic_op(raw_key, b"%04d" % n, MutationType.SET_VERSIONSTAMPED_KEY)
            raw_val = b"\x00" * 10 + b"|%02d|%04d" % (me, n) + struct.pack("<i", 0)
            tr.atomic_op(b"vsv/%02d" % me, raw_val, MutationType.SET_VERSIONSTAMPED_VALUE)
            try:
                v = await tr.commit()
            except error.FDBError as e:
                if e.is_retryable():
                    continue
                raise
            stamp = tr.get_versionstamp()
            assert int.from_bytes(stamp[:8], "big") == v
            committed.append((v, stamp))
            self.ctx.count("stamps")
        # monotone + unique within this client
        stamps = [s for _, s in committed]
        assert stamps == sorted(stamps) and len(set(stamps)) == len(stamps)
        self.ctx.shared.setdefault("by_client", {})[me] = committed

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"vsw/", b"vsw0"), await tr.get_range(b"vsv/", b"vsv0")

        keyed, valued = await db.run(read_all)
        by_client = self.ctx.shared.get("by_client", {})
        # every committed stamped KEY exists exactly where the stamp says
        expect_keys = set()
        for me, committed in by_client.items():
            for _v, stamp in committed:
                expect_keys.add(b"vsw/%02d/" % me + stamp)
        got_keys = {k for k, _ in keyed}
        if not expect_keys <= got_keys:
            return False
        # each client's stamped VALUE carries that client's newest stamp
        for me, committed in by_client.items():
            if not committed:
                continue
            newest = committed[-1][1]
            row = dict(valued).get(b"vsv/%02d" % me)
            if row is None or row[:10] != newest:
                return False
        return True


class ConsistencyCheckWorkload(TestWorkload):
    """Quiescent replica consistency check (ConsistencyCheck.actor.cpp,
    run by tester.actor.cpp:740 after most specs): at one read version,
    read every shard's full contents directly from EVERY replica of its
    team and require bit-identical results. Replicas that stay unreachable
    across retries are skipped (a killed-and-never-restored replica must
    not fail the check — that is the scenario replication exists for), but
    at least one replica per shard must serve."""

    name = "ConsistencyCheck"
    END = b"\xff\xff\xff"

    async def check(self, db: Database) -> bool:
        from ..server import storage as storage_mod
        from ..server.messages import GetKeyValuesRequest
        from ..sim.network import Endpoint
        from ..sim.loop import TaskPriority

        tr = db.create_transaction()
        while True:
            try:
                rv = await tr.get_read_version()
                locs = await db.get_locations(b"", self.END)
                break
            except error.FDBError as e:
                await tr.on_error(e)
                tr = db.create_transaction()

        _transport = {
            error.connection_failed("").code,
            error.request_maybe_delivered("").code,
            error.timed_out("").code,
        }

        async def read_replica(addr, rng):
            """Full clipped shard contents from one replica at rv. Returns
            None only for a replica that stays UNREACHABLE (transport
            errors) — a live replica that keeps erroring (future_version,
            wrong_shard: lagging or divergent state) must fail the check,
            not be skipped, or the workload would excuse exactly the bug
            class it exists to catch."""
            rows, cb, ce = [], rng.begin, min(rng.end, self.END)
            transport_errs = live_errs = 0
            while cb < ce:
                try:
                    reply = await db.net.request(
                        db.client_addr,
                        Endpoint(addr, storage_mod.GET_KEY_VALUES_TOKEN),
                        GetKeyValuesRequest(begin=cb, end=ce, version=rv,
                                            limit=10_000),
                        TaskPriority.DEFAULT_ENDPOINT, timeout=5.0,
                    )
                except error.FDBError as e:
                    if e.code in _transport:
                        transport_errs += 1
                        if transport_errs >= 10:
                            return None
                    else:
                        live_errs += 1
                        if live_errs >= 60:
                            self.ctx.count("replica_stuck_erroring")
                            return "stuck:%s" % e.name
                    await delay(0.5)
                    continue
                rows.extend(reply.data)
                if not reply.more or not reply.data:
                    break
                from ..core.types import key_after

                cb = key_after(reply.data[-1][0])
            return rows

        for rng, addrs in locs:
            views = []
            for addr in addrs:
                rows = await read_replica(addr, rng)
                if isinstance(rows, str):
                    return False  # live replica stuck erroring: never skip
                if rows is not None:
                    views.append((addr, rows))
            if not views:
                self.ctx.count("shards_with_no_replica")
                return False
            self.ctx.count("replicas_checked", len(views))
            baseline = views[0][1]
            for addr, rows in views[1:]:
                if rows != baseline:
                    self.ctx.count("replica_mismatches")
                    return False
        return True


class RandomMoveKeysWorkload(TestWorkload):
    """Move random shards to random spare workers while other workloads
    run (RandomMoveKeys.actor.cpp): the shard map is discovered through
    the `\\xff/keyServers/` system keyspace, and every move must leave the
    database consistent (the spec's other checkers + ConsistencyCheck
    prove it)."""

    name = "RandomMoveKeys"

    async def start(self, db: Database) -> None:
        from ..server import system_keys
        from ..server.masterserver import MOVE_SHARD_TOKEN, MoveShardRequest
        from ..sim.loop import TaskPriority
        from ..sim.network import Endpoint

        if self.ctx.client_id != 0:
            return
        cluster = self.ctx.cluster
        sim = cluster.sim
        rng = self.ctx.rng
        moves = int(self.ctx.options.get("moves", 2))
        interval = float(self.ctx.options.get("interval", 4.0))
        await delay(float(self.ctx.options.get("delay_before", 3.0)))
        for _ in range(moves):
            await delay(interval * (0.5 + rng.random01()))
            ep = None
            for p in cluster.worker_procs:
                for tok in p.handlers:
                    if tok.startswith(MOVE_SHARD_TOKEN):
                        ep = Endpoint(p.address, tok)
            if ep is None:
                continue
            # shard map + team sizes from the system keyspace
            async def read_meta(tr):
                return await tr.get_range(system_keys.KEY_SERVERS_PREFIX,
                                          system_keys.KEY_SERVERS_PREFIX + b"\xff")
            try:
                rows = await db.run(read_meta)
            except error.FDBError:
                continue
            if not rows:
                continue
            key, value = rows[rng.random_int(0, len(rows))]
            begin = system_keys.shard_begin_of(key)
            team, _extra = system_keys.decode_key_servers(value)
            storage_addrs = {
                p.address for p in cluster.worker_procs
                if any(t.startswith("storage.getValue") for t in p.handlers)
            }
            spare = [p.address for p in cluster.worker_procs
                     if p.alive and p.address not in storage_addrs]
            if len(spare) < len(team):
                continue
            dests = []
            pool = list(spare)
            for _i in range(len(team)):
                dests.append(pool.pop(rng.random_int(0, len(pool))))
            try:
                await sim.net.request(
                    db.client_addr, ep,
                    MoveShardRequest(begin=begin, dest_workers=dests),
                    TaskPriority.MOVE_KEYS, timeout=120.0,
                )
                self.ctx.count("moves")
            except error.FDBError:
                self.ctx.count("move_failures")


class FuzzApiCorrectnessWorkload(TestWorkload):
    """Randomized multi-transaction op streams vs the in-memory model
    (FuzzApiCorrectness.actor.cpp strategy). Each client owns a prefix.

    Unknown-result settling: every transaction READS the marker key (a
    conflict range) and then writes its own id to it. Two copies of the
    same logical transaction therefore conflict, so on
    commit_unknown_result the client can safely re-issue the SAME ops in
    a fresh transaction and loop — whichever copy lands first aborts the
    other, and the marker tells which id committed. No race window
    remains between "read the marker" and "the in-flight copy lands"."""

    name = "FuzzApiCorrectness"

    @property
    def _prefix(self) -> bytes:
        return b"fuzz%d/" % self.ctx.client_id

    def _k(self) -> bytes:
        return self._prefix + b"%03d" % self.ctx.rng.random_int(0, 24)

    async def start(self, db: Database) -> None:
        from ..core.types import Mutation

        rng = self.ctx.rng
        model = MemoryKeyValueStore()
        pre = self._prefix
        marker = pre + b"!txn"
        txns = int(self.ctx.options.get("transactions", 20))
        for txn_id in range(1, txns + 1):
            # Build this transaction's op list once; commits may re-issue it.
            ops: List = []
            for _ in range(rng.random_int(1, 8)):
                op = rng.random_int(0, 6)
                if op == 0:
                    ops.append(("set", self._k(), b"v%d" % rng.random_int(0, 1000)))
                elif op == 1:
                    ops.append(("clear", self._k()))
                elif op == 2:
                    a, b = sorted([self._k(), self._k()])
                    ops.append(("clear_range", a, b))
                elif op == 3:
                    ops.append(("get", self._k()))
                elif op == 4:
                    a, b = sorted([self._k(), self._k()])
                    ops.append(("get_range", a, b))
                else:
                    ops.append(("atomic_add", self._k(),
                                rng.random_int(1, 9).to_bytes(8, "little")))

            async def attempt(check_ryw: bool):
                """One execution of the op list; returns staged mutations."""
                tr = db.create_transaction()
                # conflict guard vs our twin — and if the twin already
                # landed, do NOT apply a second copy on top of it
                if await tr.get(marker) == b"%06d" % txn_id:
                    return "already"
                staged: List[Mutation] = []
                view = MemoryKeyValueStore()  # model + staged, maintained
                view._d = dict(model._d)
                for op in ops:
                    kind = op[0]
                    n_before = len(tr.mutations)
                    if kind == "set":
                        tr.set(op[1], op[2])
                    elif kind == "clear":
                        tr.clear(op[1])
                    elif kind == "clear_range":
                        tr.clear_range(op[1], op[2])
                    elif kind == "atomic_add":
                        tr.atomic_op(op[1], op[2], MutationType.ADD_VALUE)
                    elif kind == "get":
                        got = await tr.get(op[1])
                        if check_ryw:
                            assert got == view.get(op[1]), (op, got)
                    else:
                        got = await tr.get_range(op[1], op[2])
                        if check_ryw:
                            assert got == view.get_range(op[1], op[2]), op
                    for m in tr.mutations[n_before:]:
                        staged.append(m)
                        view.apply_mutation(m)
                tr.set(marker, b"%06d" % txn_id)
                staged.append(tr.mutations[-1])
                await tr.commit()
                return staged

            committed_staged = None
            check_ryw = True
            while True:
                try:
                    committed_staged = await attempt(check_ryw)
                    if committed_staged == "already":
                        committed_staged = "landed"
                    break
                except error.FDBError as e:
                    if e.is_maybe_committed():
                        # Re-issue; the marker read makes twins conflict.
                        # RYW asserts are skipped on replays: the first copy
                        # may have landed, changing the base the model knows.
                        check_ryw = False
                        async def read_marker(tr2):
                            return await tr2.get(marker)
                        if await db.run(read_marker) == b"%06d" % txn_id:
                            committed_staged = "landed"
                            break
                        continue
                    if e.is_retryable():
                        continue
                    raise
            if committed_staged == "landed":
                # the in-flight copy won; rebuild its staged effects by
                # replaying ops against the model (deterministic op list)
                view = MemoryKeyValueStore()
                view._d = dict(model._d)
                for op in ops:
                    if op[0] == "set":
                        view.apply_mutation(Mutation(MutationType.SET_VALUE, op[1], op[2]))
                    elif op[0] == "clear":
                        from ..core.types import key_after
                        view.apply_mutation(Mutation(MutationType.CLEAR_RANGE, op[1], key_after(op[1])))
                    elif op[0] == "clear_range":
                        if op[1] < op[2]:
                            view.apply_mutation(Mutation(MutationType.CLEAR_RANGE, op[1], op[2]))
                    elif op[0] == "atomic_add":
                        view.apply_mutation(Mutation(MutationType.ADD_VALUE, op[1], op[2]))
                view.set(marker, b"%06d" % txn_id)
                model = view
            else:
                for m in committed_staged:
                    model.apply_mutation(m)
            self.ctx.count("fuzz_commits")
        self.ctx.shared.setdefault("models", {})[self.ctx.client_id] = model

    async def check(self, db: Database) -> bool:
        for cid, model in self.ctx.shared.get("models", {}).items():
            pre = b"fuzz%d/" % cid

            async def read_all(tr):
                return await tr.get_range(pre, pre + b"\xff")

            got = await db.run(read_all)
            if got != model.get_range(pre, pre + b"\xff"):
                return False
        return True


class SerializabilityWorkload(TestWorkload):
    """Write-skew + invariant checks that snapshot isolation would violate
    but serializability forbids (Serializability.actor.cpp's intent,
    reduced to two classic anomalies):

      * on-call constraint: each txn reads BOTH duty keys and may resign
        (zero its own) only if the other is still on duty — serializable
        histories always leave >= 1 on duty;
      * bank transfers: total balance is invariant under concurrent
        read-check-move transactions."""

    name = "Serializability"

    #: keys deliberately spread across the keyspace so duty pairs and
    #: transfers straddle resolver shards — a broken cross-resolver vote
    #: combine is invisible to single-shard transactions
    DUTY_A = b"\x10ser/dutyA"
    DUTY_B = b"\xd0ser/dutyB"

    @staticmethod
    def bank_key(i: int, n: int) -> bytes:
        return bytes([(256 * i) // n]) + b"ser/bank/%d" % i

    async def start(self, db: Database) -> None:
        rng = self.ctx.rng
        me = self.ctx.client_id
        n_banks = 4
        if me == 0 and self.ctx.client_count > 0:
            async def init(tr):
                tr.set(self.DUTY_A, b"1")
                tr.set(self.DUTY_B, b"1")
                for i in range(n_banks):
                    tr.set(self.bank_key(i, n_banks), b"100")
            await db.run(init)
            self.ctx.shared["initialized"] = True
        while not self.ctx.shared.get("initialized"):
            await delay(0.1)

        rounds = int(self.ctx.options.get("rounds", 10))
        for _ in range(rounds):
            if rng.random01() < 0.5:
                # write-skew attempt: resignations are PERMANENT — under
                # serializability at most one duty key can ever reach 0
                # (the second resigner must see the first's write), so the
                # invariant is observable mid-run AND at check time; a
                # snapshot-isolation-only resolver lets both clients
                # resign concurrently
                mine = self.DUTY_A if rng.random01() < 0.5 else self.DUTY_B
                other = self.DUTY_B if mine == self.DUTY_A else self.DUTY_A

                async def resign(tr):
                    a = int(await tr.get(mine) or b"0")
                    b = int(await tr.get(other) or b"0")
                    if a + b >= 2:
                        tr.set(mine, b"0")
                        return True
                    return False

                if await db.run(resign):
                    self.ctx.count("resignations")

                async def observe(tr):
                    return (int(await tr.get(self.DUTY_A) or b"0")
                            + int(await tr.get(self.DUTY_B) or b"0"))

                if await db.run(observe) < 1:
                    self.ctx.shared["write_skew_observed"] = True
            else:
                i, j = rng.random_int(0, n_banks), rng.random_int(0, n_banks)
                if i == j:
                    continue
                amt = rng.random_int(1, 40)
                ki, kj = self.bank_key(i, n_banks), self.bank_key(j, n_banks)

                async def transfer(tr):
                    a = int(await tr.get(ki) or b"0")
                    if a >= amt:
                        b = int(await tr.get(kj) or b"0")
                        tr.set(ki, str(a - amt).encode())
                        tr.set(kj, str(b + amt).encode())

                await db.run(transfer)
                self.ctx.count("transfers")

    async def check(self, db: Database) -> bool:
        n_banks = 4

        async def read(tr):
            duty = [int(await tr.get(self.DUTY_A) or b"0"),
                    int(await tr.get(self.DUTY_B) or b"0")]
            total = 0
            for i in range(n_banks):
                total += int(await tr.get(self.bank_key(i, n_banks)) or b"0")
            return duty, total

        duty, total = await db.run(read)
        # at least one on duty (no write skew, final AND mid-run) and
        # balance conserved
        return (sum(duty) >= 1 and total == 400
                and not self.ctx.shared.get("write_skew_observed"))


class BackupCorrectnessWorkload(TestWorkload):
    """Back up under live load, restore into a second cluster in the same
    simulation, and require the restored keyspace to equal the source at
    the backup's end version (BackupCorrectness.actor.cpp)."""

    name = "BackupCorrectness"

    async def start(self, db: Database) -> None:
        from ..backup import BackupAgent, BlobContainer

        if self.ctx.client_id != 0:
            return
        sim = self.ctx.cluster.sim
        container = BlobContainer(sim.new_process("wl-blobstore"))
        agent = BackupAgent(sim, db, container.proc.address)
        await delay(float(self.ctx.options.get("delay_before", 1.0)))
        await agent.start_backup()
        await agent.snapshot(chunks=int(self.ctx.options.get("chunks", 4)),
                             workers=2)
        await delay(float(self.ctx.options.get("tail_seconds", 1.0)))
        await agent.finish_backup()
        # capture the source state AT end_version now, while the MVCC
        # window still covers it (check runs after quiesce, possibly
        # several virtual seconds later)
        try:
            tr = db.create_transaction()
            tr.read_version = agent.end_version
            self.ctx.shared["src_rows"] = await tr.get_range(
                b"", b"\xff", limit=100_000, snapshot=True)
        except error.FDBError as e:
            if e.code != error.transaction_too_old("").code:
                raise
            # a stalled finish (recovery mid-backup) outlived the window:
            # the equality check is skipped, visibly
            self.ctx.shared["src_rows"] = None
            self.ctx.count("capture_window_missed")
        self.ctx.shared["agent"] = agent
        self.ctx.count("backups")

    async def check(self, db: Database) -> bool:
        from ..server.cluster import DynamicCluster, DynamicClusterConfig

        agent = self.ctx.shared.get("agent")
        if agent is None:
            return False
        sim = self.ctx.cluster.sim
        dst = DynamicCluster(sim, DynamicClusterConfig(
            n_workers=5, n_tlogs=2, n_resolvers=1, n_storage=2))
        db2 = dst.new_client()
        await agent.restore(db2)

        src_rows = self.ctx.shared.get("src_rows")
        if src_rows is None:
            # capture window missed (counted above): restore ran, equality
            # unverifiable this run
            return True
        # through run(): a recovery straddling this read (cluster churn
        # continues during restore) surfaces as retryable
        # transaction_too_old, not a spec failure
        async def read_dst(tr2):
            return await tr2.get_range(b"", b"\xff", limit=100_000,
                                       snapshot=True)
        rows2 = await db2.run(read_dst)
        if rows2 != src_rows:
            self.ctx.count("restore_mismatch")
            return False
        return True


class InventoryWorkload(TestWorkload):
    """Conditional read-modify-writes over per-item stock counters
    (Inventory.actor.cpp): restock or (only if in stock) take one item.
    The final physical stock must equal restocks minus takes — lost
    updates or phantom takes break the equation."""

    name = "Inventory"

    async def start(self, db: Database) -> None:
        rng = self.ctx.rng
        items = int(self.ctx.options.get("items", 6))
        ops = int(self.ctx.options.get("ops", 15))
        me = self.ctx.client_id
        for op_i in range(ops):
            item = b"inv/%02d" % rng.random_int(0, items)
            want_take = rng.random01() < 0.45
            # per-op marker: a maybe-committed retry must neither re-apply
            # the RMW nor double-count (the marker read is also the
            # conflict guard that serializes the retry against its twin)
            marker = b"inv!/%02d/%04d" % (me, op_i)

            async def body(tr):
                prev = await tr.get(marker)
                if prev is not None:
                    return prev.decode()   # the earlier attempt landed
                stock = int(await tr.get(item) or b"0")
                if want_take and stock > 0:
                    tr.set(item, str(stock - 1).encode())
                    action = "take"
                else:
                    tr.set(item, str(stock + 1).encode())
                    action = "restock"
                tr.set(marker, action.encode())
                return action

            what = await db.run(body)
            self.ctx.count("takes" if what == "take" else "restocks")

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"inv/", b"inv0")

        rows = await db.run(read_all)
        stock = sum(int(v) for _, v in rows)
        if any(int(v) < 0 for _, v in rows):
            return False
        return stock == (self.ctx.shared.get("restocks", 0)
                         - self.ctx.shared.get("takes", 0))


class BulkLoadWorkload(TestWorkload):
    """Sequential batch loading (BulkLoad.actor.cpp): each client commits
    `batches` transactions of `batch_size` contiguous rows; every row must
    land exactly once, and the sustained load rate is reported."""

    name = "BulkLoad"

    async def start(self, db: Database) -> None:
        from ..sim.loop import now

        me = self.ctx.client_id
        batches = int(self.ctx.options.get("batches", 6))
        size = int(self.ctx.options.get("batch_size", 40))
        t0 = now()
        for b in range(batches):
            async def body(tr):
                for i in range(size):
                    tr.set(b"bulk/%02d/%04d" % (me, b * size + i), b"x" * 16)
            await db.run(body)
            self.ctx.count("rows_loaded", size)
        dt = max(now() - t0, 1e-9)
        # count() sums across clients (rates add: total cluster rate)
        self.ctx.count("bulk_rows_per_sec", round(batches * size / dt, 1))

    async def check(self, db: Database) -> bool:
        me = self.ctx.client_id
        batches = int(self.ctx.options.get("batches", 6))
        size = int(self.ctx.options.get("batch_size", 40))

        async def count(tr):
            return len(await tr.get_range(b"bulk/%02d/" % me, b"bulk/%02d0" % me,
                                          limit=100_000))

        return await db.run(count) == batches * size


class QueuePushWorkload(TestWorkload):
    """Contended queue appends via versionstamped keys
    (QueuePush.actor.cpp): pushes never conflict, land in commit order,
    and the queue length equals the number of committed pushes."""

    name = "QueuePush"

    async def start(self, db: Database) -> None:
        import struct

        pushes = int(self.ctx.options.get("pushes", 12))
        me = self.ctx.client_id
        for i in range(pushes):
            tr = db.create_transaction()
            raw_key = b"queue/" + b"\x00" * 10 + struct.pack("<i", len(b"queue/"))
            tr.atomic_op(raw_key, b"%02d:%04d" % (me, i),
                         MutationType.SET_VERSIONSTAMPED_KEY)
            try:
                await tr.commit()
                self.ctx.count("pushes")
            except error.FDBError as e:
                if not e.is_retryable() and not e.is_maybe_committed():
                    raise
                if e.is_maybe_committed():
                    self.ctx.count("maybe_pushes")

    async def check(self, db: Database) -> bool:
        async def read_all(tr):
            return await tr.get_range(b"queue/", b"queue0", limit=100_000)

        rows = await db.run(read_all)
        keys = [k for k, _ in rows]
        if keys != sorted(keys):
            return False
        certain = self.ctx.shared.get("pushes", 0)
        maybe = self.ctx.shared.get("maybe_pushes", 0)
        if not (certain <= len(rows) <= certain + maybe):
            return False
        # commit order: each client's sequence numbers must be increasing
        # along the versionstamped key order (QueuePush.actor.cpp's check)
        last_seq: Dict[bytes, int] = {}
        for _k, v in rows:
            client, seq = v.split(b":")
            if client in last_seq and int(seq) <= last_seq[client]:
                return False
            last_seq[client] = int(seq)
        return True


class ThroughputWorkload(TestWorkload):
    """The timed 90/10 measurement loop (Throughput.actor.cpp): runs for
    a fixed virtual duration and reports transactions/sec as a metric the
    spec harness records."""

    name = "Throughput"

    async def start(self, db: Database) -> None:
        from ..sim.loop import now

        rng = self.ctx.rng
        seconds = float(self.ctx.options.get("seconds", 5.0))
        keys = int(self.ctx.options.get("keys", 128))
        t0 = now()
        done = 0
        while now() - t0 < seconds:
            async def body(tr):
                for _ in range(9):
                    await tr.get(b"tp/%04d" % rng.random_int(0, keys))
                tr.set(b"tp/%04d" % rng.random_int(0, keys), b"v")
            try:
                await db.run(body)
                done += 1
            except error.FDBError:
                pass
        self.ctx.count("throughput_txns", done)
        self.ctx.count("txns_per_sec", round(done / (now() - t0), 1))


class FullClusterRebootWorkload(TestWorkload):
    """The restarting-test shape (tests/restarting/ + SaveAndKill.actor.cpp):
    after `delay_before`, REBOOT-kill EVERY process in the cluster at once —
    coordinators included. The whole database must re-form from disks alone
    (coordination registers, tlog queues + spill, the storage LSM engines),
    and the surrounding workloads' invariants must hold across the gap."""

    name = "FullClusterReboot"
    anti_quiescence = True

    async def start(self, db: Database) -> None:
        from ..sim.simulator import KillType

        if self.ctx.client_id != 0:
            return
        await delay(float(self.ctx.options.get("delay_before", 6.0)))
        cluster = self.ctx.cluster
        sim = cluster.sim
        for p in getattr(cluster, "coord_procs", []) + cluster.worker_procs:
            if p.alive:
                sim.kill_process(p, KillType.REBOOT)
        self.ctx.count("full_reboots")
        rounds = int(self.ctx.options.get("rounds", 1))
        for _ in range(rounds - 1):
            await delay(float(self.ctx.options.get("interval", 12.0)))
            for p in getattr(cluster, "coord_procs", []) + cluster.worker_procs:
                if p.alive:
                    sim.kill_process(p, KillType.REBOOT)
            self.ctx.count("full_reboots")


class DatacenterKillWorkload(TestWorkload):
    """Kill EVERY process of one datacenter at once (the multi-region
    failure the topology exists to survive): coordinators, txn roles, and
    storage replicas of `dc` all go down; with satellite logs + cross-DC
    teams + a surviving coordinator majority, the next recovery recruits
    in the other DC and nothing acknowledged is lost. With `revive` the
    DC returns later (REBOOT), rejoining as the secondary."""

    name = "DatacenterKill"
    anti_quiescence = True

    async def start(self, db: Database) -> None:
        from ..sim.simulator import KillType

        if self.ctx.client_id != 0:
            return
        await delay(float(self.ctx.options.get("delay_before", 5.0)))
        dc = self.ctx.options.get("dc", "dc0")
        cluster = self.ctx.cluster
        sim = cluster.sim
        victims = [p for p in (getattr(cluster, "coord_procs", [])
                               + cluster.worker_procs)
                   if p.alive and p.dc_id == dc]
        for p in victims:
            sim.kill_process(p, KillType.KILL_INSTANTLY)
        self.ctx.count("dc_killed", len(victims))
        revive_after = float(self.ctx.options.get("revive_after", 0.0))
        if revive_after > 0:
            await delay(revive_after)
            for p in victims:
                sim.revive_process(p)
            self.ctx.count("dc_revived")


class DeviceFaultValidationWorkload(TestWorkload):
    """Check-phase auditor for the device-nemesis campaign (fault/).

    Every ResilientEngine the simulation created — including engines of
    generations whose processes have since died — must have emitted a
    bit-identical verdict stream: its journal replayed through a fresh
    reference oracle reproduces every abort set exactly, injected
    exceptions/hangs/slow batches, watchdog retries, CPU-oracle failovers
    and swap-backs notwithstanding. Health counters are folded into the
    spec metrics so a multi-seed campaign can assert failover and
    swap-back coverage (ISSUE 2 acceptance)."""

    name = "DeviceFaultCheck"

    HEALTH_KEYS = ("failovers", "swap_backs", "retries", "dispatch_faults",
                   "probes", "probe_mismatches", "oracle_batches",
                   "rewarm_failures")

    async def check(self, db: Database) -> bool:
        from ..core.trace import Severity, TraceEvent
        from ..fault import abort_set_digest, registered_engines
        from ..ops.oracle import OracleConflictEngine

        ok = True
        engines = registered_engines()
        self.ctx.count("engines_checked", len(engines))
        for eng in engines:
            st = eng.health_stats()
            for k in self.HEALTH_KEYS:
                self.ctx.count(f"engine_{k}", st.get(k, 0))
            if st.get("probe_mismatches"):
                # a quarantine means corruption reached the verdict stream
                # at least once before the probe caught it — SevError, and
                # the spec fails (flips are off in nemesis defaults; this
                # arm exists for the corruption-variant runs)
                ok = False
            if eng.journal is None:
                continue
            # flight recorder (docs/observability.md): the incident ring's
            # abort-set digests must replay — each recorded dispatch's
            # digest equals the digest of a clean oracle's verdicts for the
            # same batch (post-mortem parity without the full journal)
            flight_by_version = {rec["version"]: rec
                                 for rec in eng.flight.dump()}
            self.ctx.count("flight_records", len(flight_by_version))
            # heat/occupancy snapshots riding the records (PR 10): replay
            # tolerates their presence and checks the fields are sane —
            # a malformed snapshot in an incident dump is itself a bug
            for rec in flight_by_version.values():
                heat = rec.get("heat")
                if heat is None:
                    continue
                self.ctx.count("flight_heat_records")
                frac = heat.get("occupancy_frac", 0.0)
                if not (0.0 <= frac <= 1.0) or heat.get("conflicts", 0) < 0:
                    TraceEvent("FlightRecorderHeatMalformed",
                               severity=Severity.ERROR) \
                        .detail("Version", rec["version"]) \
                        .detail("Heat", heat).log()
                    self.ctx.count("flight_heat_malformed")
                    ok = False
            clean = OracleConflictEngine()
            for version, txns, new_oldest, verdicts in eng.journal:
                want = clean.resolve(list(txns), version, new_oldest)
                if list(verdicts) != [int(v) for v in want]:
                    TraceEvent("DeviceFaultParityMismatch",
                               severity=Severity.ERROR) \
                        .detail("Version", version) \
                        .detail("Got", list(verdicts)) \
                        .detail("Want", [int(v) for v in want]).log()
                    self.ctx.count("parity_mismatches")
                    ok = False
                    break
                rec = flight_by_version.get(version)
                if rec is not None and rec["digest"] != abort_set_digest(want):
                    TraceEvent("FlightRecorderDigestMismatch",
                               severity=Severity.ERROR) \
                        .detail("Version", version) \
                        .detail("Recorded", rec["digest"]) \
                        .detail("Replayed", abort_set_digest(want)).log()
                    self.ctx.count("flight_digest_mismatches")
                    ok = False
                    break
        return ok
