"""TestWorkload interface + spec runner.

reference: fdbserver/workloads/workloads.h:42-85 (description/setup/start/
check + clientId/clientCount), fdbserver/tester.actor.cpp:778-1124 (phase
driving), tests/*.txt (declarative specs composing workloads).

A Spec composes workload classes with options; run_spec builds a simulated
cluster from the seed, runs setup -> start (all workloads and clients
concurrently) -> quiesce -> check, and returns collected metrics. Fault
injectors (clogging etc.) are workloads whose start() runs until the test
phase ends, exactly like the reference's anti-quiescence workloads.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Type

from ..core import error
from ..core.rng import DeterministicRandom
from ..client.database import Database
from ..server.cluster import Cluster, ClusterConfig, DynamicCluster, DynamicClusterConfig
from ..sim.actors import all_of
from ..sim.loop import Future, set_scheduler
from ..sim.simulator import Simulator


class WorkloadContext:
    def __init__(
        self,
        cluster: Cluster,
        client_id: int,
        client_count: int,
        rng: DeterministicRandom,
        options: Dict[str, Any],
        shared: Optional[Dict[str, Any]] = None,
    ):
        self.cluster = cluster
        self.client_id = client_id
        self.client_count = client_count
        self.rng = rng
        self.options = options
        self.metrics: Dict[str, float] = {}
        #: one dict per workload entry, shared by all its clients — for
        #: cross-client totals the check phase needs (the reference tester
        #: sums getMetrics across clients before checking)
        self.shared: Dict[str, Any] = shared if shared is not None else {}

    def count(self, key: str, delta: float = 1) -> None:
        self.shared[key] = self.shared.get(key, 0) + delta
        self.metrics[key] = self.metrics.get(key, 0) + delta


class TestWorkload:
    """Subclass and override; every phase gets a fresh Database client."""

    name = "workload"
    #: fault injectors keep running during start and are cancelled at
    #: quiescence instead of awaited (reference: anti-quiescence workloads)
    anti_quiescence = False

    def __init__(self, ctx: WorkloadContext):
        self.ctx = ctx

    async def setup(self, db: Database) -> None:
        pass

    async def start(self, db: Database) -> None:
        pass

    async def check(self, db: Database) -> bool:
        return True


@dataclass
class Spec:
    """One test = cluster config + composed workloads (tests/fast/*.txt)."""

    title: str
    workloads: List[tuple] = field(default_factory=list)  # (cls, options)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    #: when set, the spec runs against a recruitment-era DynamicCluster
    #: (coordinators + workers + recovery) instead of the static assembly —
    #: required for attrition workloads
    dynamic: Optional[DynamicClusterConfig] = None
    client_count: int = 1
    timeout: float = 3600.0
    #: BUGGIFY-randomize the knob registries for this run (always reset
    #: afterwards); the reference randomizes knobs in every sim run
    randomize_knobs: bool = True


@dataclass
class SpecResult:
    ok: bool
    metrics: Dict[str, float]
    seed: int
    virtual_time: float


async def quiet_database(cluster, timeout: float = 120.0) -> None:
    """Wait until every reachable storage replica has caught up to a
    post-workload read version (waitForQuietDatabase reduced to its
    storage-lag core: no checker should race the mutation pipeline)."""
    from ..core import error as _error
    from ..server.ratekeeper import STORAGE_QUEUE_INFO_TOKEN
    from ..sim.loop import TaskPriority, delay, now
    from ..sim.network import Endpoint

    sim = cluster.sim
    db = cluster.new_client()
    tr = db.create_transaction()
    while True:
        try:
            rv = await tr.get_read_version()
            break
        except _error.FDBError as e:
            await tr.on_error(e)
            tr = db.create_transaction()

    deadline = now() + timeout
    while now() < deadline:
        procs = [p for p in getattr(cluster, "worker_procs", [])
                 if p.alive and STORAGE_QUEUE_INFO_TOKEN in p.handlers]
        procs += [getattr(s, "proc") for s in getattr(cluster, "storages", [])
                  if s.proc.alive and STORAGE_QUEUE_INFO_TOKEN in s.proc.handlers]
        lagging = False
        for p in procs:
            try:
                info = await sim.net.request(
                    db.client_addr, Endpoint(p.address, STORAGE_QUEUE_INFO_TOKEN),
                    None, TaskPriority.DEFAULT_ENDPOINT, timeout=2.0,
                )
            except _error.FDBError:
                continue  # dead/unreachable replicas don't gate quiescence
            if info.version < rv:
                lagging = True
                break
        if not lagging:
            return
        await delay(0.5, TaskPriority.DEFAULT_ENDPOINT)
    # Giving up silently would let checks race the mutation pipeline —
    # the exact flakiness this phase exists to prevent. Fail loudly.
    raise _error.timed_out("quiet_database: storage still lagging at deadline")


def run_spec(spec: Spec, seed: int) -> SpecResult:
    """Deterministic: same spec+seed -> same result and metrics."""
    sim = Simulator(seed, randomize_knobs=spec.randomize_knobs)
    if spec.dynamic is not None:
        cluster = DynamicCluster(sim, spec.dynamic)
    else:
        cluster = Cluster(sim, spec.cluster)
    instances: List[TestWorkload] = []
    for cls, options in spec.workloads:
        shared: Dict[str, Any] = {}
        for cid in range(spec.client_count):
            ctx = WorkloadContext(cluster, cid, spec.client_count, sim.sched.rng, dict(options), shared)
            instances.append(cls(ctx))

    metrics: Dict[str, float] = {}
    ok = True

    async def drive():
        nonlocal ok
        # setup: client 0 of each workload only (reference: clientId==0 gates)
        for w in instances:
            if w.ctx.client_id == 0:
                await w.setup(cluster.new_client())
        # start: all clients concurrently; injectors cancelled at quiescence
        main_tasks = []
        injector_tasks = []
        for w in instances:
            t = sim.sched.spawn(w.start(cluster.new_client()), name=f"wl:{w.name}:{w.ctx.client_id}")
            (injector_tasks if w.anti_quiescence else main_tasks).append(t)
        await all_of(main_tasks)
        for t in injector_tasks:
            t.cancel()
        # quiesce, then check (waitForQuietDatabase, QuietDatabase.actor.cpp:304)
        try:
            await quiet_database(cluster)
        except error.FDBError:
            ok = False
            metrics["quiesce_timeout"] = 1
            return
        for w in instances:
            if w.ctx.client_id == 0:
                if not await w.check(cluster.new_client()):
                    ok = False
        for w in instances:
            metrics.update(w.ctx.metrics)

    task = sim.sched.spawn(drive(), name=f"spec:{spec.title}")
    try:
        sim.run_until(task, until=spec.timeout)
    finally:
        set_scheduler(None)
        if spec.randomize_knobs:
            from ..core import knobs
            knobs.reset_all()
    # sim_validation oracle (sim/validation.py): ANY recovery that picked a
    # version below a fully-acked push fails the spec, whatever the
    # workload checks said — acked durability is never up for debate.
    from ..sim import validation as sim_validation

    if sim_validation.violations:
        ok = False
        metrics["durability_violations"] = len(sim_validation.violations)
    return SpecResult(ok=ok, metrics=metrics, seed=seed, virtual_time=sim.sched.time)
