"""Spec-runner CLI with seed replay.

The analog of `fdbserver -r simulation -f tests/fast/CycleTest.txt -s SEED`:
run one named spec (or all) under a seed; failures replay exactly by
re-running with the same seed. `--repeat N` runs N consecutive seeds, the
miniature of the reference's thousands-of-seeds correctness runs.
"""
from __future__ import annotations

import argparse
import sys

from .specs import SPECS
from .workload import run_spec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="simulation spec runner")
    ap.add_argument("--spec", help="spec name (see --list)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--repeat", type=int, default=1, help="run seeds seed..seed+N-1")
    ap.add_argument("--all", action="store_true", help="run every spec")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SPECS):
            print(name)
        return 0

    names = sorted(SPECS) if args.all else ([args.spec] if args.spec else [])
    if not names:
        ap.error("--spec NAME, --all, or --list required")

    failures = 0
    for name in names:
        make = SPECS.get(name)
        if make is None:
            print(f"unknown spec: {name}", file=sys.stderr)
            return 2
        for seed in range(args.seed, args.seed + args.repeat):
            res = run_spec(make(), seed)
            status = "OK " if res.ok else "FAIL"
            print(
                f"{status} {name} seed={seed} vtime={res.virtual_time:.2f}s "
                + " ".join(f"{k}={v:g}" for k, v in sorted(res.metrics.items()))
            )
            if not res.ok:
                failures += 1
                print(f"  replay: python -m foundationdb_tpu.testing.runner --spec {name} --seed {seed}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
